"""Scrape manager — the Prometheus scrape loop, in-process.

Discovers control-plane and node ``/metrics`` endpoints, scrapes them
concurrently over one shared session (the ClusterMonitor pattern: sweep
time is the slowest single scrape, not the sum), parses the text
exposition format, and ingests samples into the TSDB with ``job`` and
``instance`` target labels attached.

Target discovery:

- **apiserver** — every configured apiserver URL (HA replicas each get
  their own target; the sharded apiserver's per-worker series all ride
  the one registry, labeled by loop);
- **scheduler / controller-manager** — the components' metrics
  listeners (metrics/http.py), handed in by the composer;
- **node** — LIST Nodes, resolve each agent's daemon endpoint
  (client/nodeaccess.py — same credential policy as ``ktl top``), and
  scrape ``/metrics`` filtered to the per-chip ``tpu_*`` families with
  the target node's own label. The filter matters in single-process
  clusters where every component shares one registry: without it, N
  node targets would each re-ingest the whole fleet's series N times.

Per-target bookkeeping series written into the TSDB every sweep:
``up{job,instance}`` (1/0) and
``kmon_scrape_duration_seconds{job,instance}``. A failed scrape marks
every series previously ingested from that target STALE (tsdb.py NaN
markers), so instant queries stop seeing a dead target immediately —
carrying a dead apiserver's last loop-busy number forward would hide
exactly the outage the pipeline exists to surface.
"""
from __future__ import annotations

import asyncio
import logging
import re
import time
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

from ..metrics.registry import Counter
from .tsdb import TSDB, Matcher

log = logging.getLogger("kmon.scrape")

SCRAPES = Counter(
    "kmon_scrapes_total",
    "kmon scrape attempts by job and result",
    labels=("job", "result"))

SCRAPE_SAMPLES = Counter(
    "kmon_scrape_samples_total",
    "Samples ingested from scrapes, by job",
    labels=("job",))

#: Per-chip node families ingested from node targets (aggregator
#: rollups enter the TSDB through the pipeline's snapshot recording,
#: not through node scrapes).
NODE_FAMILIES = ("tpu_duty_cycle_pct", "tpu_hbm_used_bytes",
                 "tpu_hbm_total_bytes", "tpu_ici_tx_bytes",
                 "tpu_ici_rx_bytes", "tpu_ici_links_up",
                 "tpu_chip_healthy", "tpu_chip_assigned",
                 "tpu_libtpu_probe_healthy")

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_ESCAPE_RE = re.compile(r'\\(.)')
_ESCAPES = {'"': '"', "n": "\n", "\\": "\\"}


def _unescape_label(raw: str) -> str:
    """One-pass exposition unescape (\\" \\n \\\\); chained
    str.replace would mis-handle a literal backslash followed by 'n'
    (``C:\\\\nightly`` must stay ``C:\\nightly``, not gain a newline).
    Unknown escapes pass through verbatim, like the Prometheus
    parser."""
    return _ESCAPE_RE.sub(
        lambda m: _ESCAPES.get(m.group(1), "\\" + m.group(1)), raw)


def parse_exposition(text: str) -> Iterable[tuple[str, dict, float]]:
    """(name, labels, value) per sample line of Prometheus text
    exposition. Comment/TYPE/HELP lines and unparsable lines are
    skipped — a scrape must never fail on one malformed series."""
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        labels: dict = {}
        if brace != -1:
            close = line.rfind("}")
            if close == -1:
                continue
            name = line[:brace]
            for m in _LABEL_RE.finditer(line[brace + 1:close]):
                labels[m.group(1)] = _unescape_label(m.group(2))
            rest = line[close + 1:].strip()
        else:
            name, _, rest = line.partition(" ")
            rest = rest.strip()
        if not rest:
            continue
        value = rest.split()[0]
        try:
            yield name, labels, float(value)
        except ValueError:
            continue


@dataclass
class ScrapeTarget:
    """One endpoint the manager scrapes each sweep."""
    job: str
    instance: str
    url: str  # full /metrics URL
    ssl: object = None
    #: Metric-name prefixes to ingest; () = everything.
    families: tuple = ()
    #: Labels a sample must carry verbatim to be ingested (e.g.
    #: ``{"node": "node-3"}`` on node targets).
    require_labels: dict = field(default_factory=dict)

    def wants(self, name: str, labels: dict) -> bool:
        if self.families and not any(name.startswith(p)
                                     for p in self.families):
            return False
        return all(labels.get(k) == v
                   for k, v in self.require_labels.items())


def ingest_exposition(tsdb: TSDB, text: str, ts: float, job: str,
                      instance: str, target: Optional[ScrapeTarget] = None
                      ) -> int:
    """Parse + ingest one exposition payload; returns samples accepted.
    Also the perf harnesses' promql-compat seam (perf/__init__.py):
    one parser, whether the text came from a live scrape or a bench's
    one-shot GET."""
    n = 0
    for name, labels, value in parse_exposition(text):
        if target is not None and not target.wants(name, labels):
            continue
        labels["job"] = job
        labels["instance"] = instance
        if tsdb.add(name, labels, value, ts):
            n += 1
    return n


class ScrapeManager:
    def __init__(self, client, tsdb: TSDB, interval: float = 5.0,
                 ssl_context=None,
                 apiserver_urls: Sequence[str] = (),
                 component_urls: Sequence[tuple[str, str]] = (),
                 scrape_timeout: float = 3.0):
        """``component_urls``: (job, base URL) pairs for scheduler /
        controller-manager metrics listeners. ``ssl_context`` carries
        cluster credentials for TLS apiserver + node endpoints."""
        self.client = client
        self.tsdb = tsdb
        self.interval = interval
        self._ssl = ssl_context
        self.apiserver_urls = list(apiserver_urls)
        self.component_urls = list(component_urls)
        self.scrape_timeout = scrape_timeout
        #: Instances that succeeded last sweep, per (job, instance) —
        #: the staleness edge detector.
        self._was_up: set[tuple[str, str]] = set()
        self.sweeps = 0

    # -- discovery --------------------------------------------------------

    async def discover(self) -> list[ScrapeTarget]:
        targets = []
        for url in self.apiserver_urls:
            targets.append(ScrapeTarget(
                job="apiserver", instance=_instance_of(url),
                url=url.rstrip("/") + "/metrics",
                ssl=self._ssl if url.startswith("https") else None,
                families=("apiserver_", "replication_", "chaos_")))
        for job, url in self.component_urls:
            families = {"scheduler": ("scheduler_",),
                        "controller-manager": ("tpu_monitor_", "kmon_")}
            targets.append(ScrapeTarget(
                job=job, instance=_instance_of(url),
                url=url.rstrip("/") + "/metrics",
                ssl=self._ssl if url.startswith("https") else None,
                families=families.get(job, ())))
        from ..api import errors
        from ..client.nodeaccess import resolve_node_agent
        try:
            nodes, _rev = await self.client.list("nodes")
        except errors.StatusError as e:
            log.warning("kmon: node list failed: %s", e)
            nodes = []
        # Resolve CONCURRENTLY, passing the just-LISTed Node objects:
        # sequential resolution serializes the 2s /healthz probe
        # timeouts of every dead node and pushes the whole sweep
        # behind schedule — the exact failure mode the monitor's
        # concurrent scrape exists to avoid.
        conns = await asyncio.gather(
            *(resolve_node_agent(self.client, n.metadata.name, node=n)
              for n in nodes))
        for node, conn in zip(nodes, conns):
            name = node.metadata.name
            if conn is None:
                # Unresolvable counts as a down target: the node is
                # LISTED, so its absence is signal, not configuration.
                targets.append(ScrapeTarget(
                    job="node", instance=name, url="",
                    families=NODE_FAMILIES,
                    require_labels={"node": name}))
                continue
            base, node_ssl = conn
            if self._ssl is not None:
                node_ssl = self._ssl
            targets.append(ScrapeTarget(
                job="node", instance=name, url=f"{base}/metrics",
                ssl=node_ssl, families=NODE_FAMILIES,
                require_labels={"node": name}))
        return targets

    # -- the sweep --------------------------------------------------------

    async def sweep(self, now: Optional[float] = None) -> dict:
        """Discover + scrape every target once; returns
        ``{instance_key: up}`` (tests drive this directly)."""
        import aiohttp
        now = time.time() if now is None else now
        targets = await self.discover()
        async with aiohttp.ClientSession() as session:
            results = await asyncio.gather(
                *(self._scrape_one(t, session, now) for t in targets))
        up_now: set[tuple[str, str]] = set()
        report = {}
        for target, ok in zip(targets, results):
            key = (target.job, target.instance)
            report[f"{target.job}/{target.instance}"] = ok
            if ok:
                up_now.add(key)
            elif key in self._was_up:
                # Freshly down: stale-mark everything this target fed.
                self.tsdb.mark_stale(now, matchers=[
                    Matcher("job", "=", target.job),
                    Matcher("instance", "=", target.instance)])
                # ... except its own up series, re-added below.
        for target in targets:
            key = (target.job, target.instance)
            meta = {"job": target.job, "instance": target.instance}
            self.tsdb.add("up", meta, 1.0 if key in up_now else 0.0, now)
        self._was_up = up_now
        self.sweeps += 1
        self.tsdb.gc(now)
        return report

    async def _scrape_one(self, target: ScrapeTarget, session,
                          now: float) -> bool:
        import aiohttp
        from ..client.nodeaccess import ssl_kw
        if not target.url:
            SCRAPES.inc(job=target.job, result="unreachable")
            return False
        t0 = time.perf_counter()
        try:
            async with session.get(
                    target.url,
                    timeout=aiohttp.ClientTimeout(
                        total=self.scrape_timeout),
                    **ssl_kw(target.ssl)) as r:
                if r.status != 200:
                    SCRAPES.inc(job=target.job, result="error")
                    return False
                text = await r.text()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — target down mid-sweep
            log.debug("kmon: scrape %s/%s failed: %s",
                      target.job, target.instance, e)
            SCRAPES.inc(job=target.job, result="error")
            return False
        n = ingest_exposition(self.tsdb, text, now, target.job,
                              target.instance, target)
        self.tsdb.add(
            "kmon_scrape_duration_seconds",
            {"job": target.job, "instance": target.instance},
            round(time.perf_counter() - t0, 6), now)
        SCRAPES.inc(job=target.job, result="ok")
        SCRAPE_SAMPLES.inc(n, job=target.job)
        return True


def _instance_of(url: str) -> str:
    return url.split("://", 1)[-1].rstrip("/")
