"""In-memory ring TSDB — the Prometheus-storage analog, hard-bounded.

One process-local store for the kmon pipeline (scrape.py feeds it,
promql.py queries it, rules.py records into it). Design constraints,
in order:

1. **Never unbounded.** Every axis has a ceiling: series count
   (``max_series``), samples per series (a ring — old samples fall
   off), and retention age (``retention_seconds``). Anything refused
   is COUNTED (``kmon_tsdb_dropped_samples_total`` by reason), never
   silently lost — the ROADMAP item-6 hygiene requirement applied to
   the monitoring pipeline itself.
2. **Step-aligned downsampling.** Timestamps quantize to the scrape
   step (``step`` > 0), keep-last per step: two scrapes landing in one
   step cost one sample, and range queries see a regular grid instead
   of jittered scrape instants.
3. **Explicit staleness.** A failed scrape writes a NaN staleness
   marker (the Prometheus 2.x mechanism) so instant queries stop
   returning a dead target's last value immediately instead of after
   the whole lookback window.

Values are stored as (ts, value) tuples in a ``deque(maxlen=...)`` —
the ring bound is structural, not a janitor loop that can fall behind.
"""
from __future__ import annotations

import math
import re
from collections import deque
from typing import Iterable, Optional, Sequence

from ..metrics.registry import Counter, Gauge
from ..util.lockdep import make_lock

#: NaN staleness marker (Prometheus uses a special NaN bit pattern;
#: plain NaN suffices here — no real sample is ever NaN).
STALE = float("nan")

TSDB_INGESTED = Counter(
    "kmon_tsdb_ingested_samples_total",
    "Samples accepted into the kmon TSDB")

TSDB_DROPPED = Counter(
    "kmon_tsdb_dropped_samples_total",
    "Samples the kmon TSDB refused, by reason "
    "(series_limit/out_of_order/retention)",
    labels=("reason",))

TSDB_SERIES = Gauge(
    "kmon_tsdb_series",
    "Live series in the kmon TSDB")

TSDB_SAMPLES = Gauge(
    "kmon_tsdb_samples",
    "Samples currently held across all kmon TSDB series")


def is_stale(value: float) -> bool:
    return isinstance(value, float) and math.isnan(value)


class Matcher:
    """One label matcher: ``=``, ``!=``, ``=~`` (anchored), ``!~``."""

    __slots__ = ("label", "op", "value", "_re")

    def __init__(self, label: str, op: str, value: str):
        if op not in ("=", "!=", "=~", "!~"):
            raise ValueError(f"unknown matcher op {op!r}")
        self.label = label
        self.op = op
        self.value = value
        if op in ("=~", "!~"):
            try:
                self._re = re.compile(f"^(?:{value})$")
            except re.error as e:
                # ValueError, not re.error: callers (the PromQL
                # parser) turn it into a 400, never a 500.
                raise ValueError(
                    f"bad regex in matcher {label}{op}{value!r}: "
                    f"{e}") from None
        else:
            self._re = None

    def matches(self, labels: dict) -> bool:
        got = labels.get(self.label, "")
        if self.op == "=":
            return got == self.value
        if self.op == "!=":
            return got != self.value
        hit = self._re.match(got) is not None
        return hit if self.op == "=~" else not hit

    def __repr__(self):
        return f"{self.label}{self.op}{self.value!r}"


class Series:
    __slots__ = ("name", "labels", "samples")

    def __init__(self, name: str, labels: dict, maxlen: int):
        self.name = name
        self.labels = dict(labels)
        self.samples: deque = deque(maxlen=maxlen)

    def latest(self) -> Optional[tuple]:
        return self.samples[-1] if self.samples else None


def series_key(name: str, labels: dict) -> tuple:
    return (name,) + tuple(sorted(labels.items()))


class TSDB:
    """Bounded in-memory time-series store.

    ``step`` > 0 aligns timestamps down to the step grid (keep-last per
    bucket). ``max_series`` is a hard ceiling — a label-cardinality
    explosion drops NEW series (counted), it does not grow the map.
    """

    def __init__(self, retention_seconds: float = 900.0,
                 max_samples_per_series: int = 512,
                 max_series: int = 20_000,
                 step: float = 0.0):
        self.retention_seconds = float(retention_seconds)
        self.max_samples_per_series = int(max_samples_per_series)
        self.max_series = int(max_series)
        self.step = float(step)
        self._series: dict[tuple, Series] = {}
        #: name -> {series_key: Series}: selector evaluation is
        #: O(series of that name), not a scan of the whole map —
        #: range queries re-evaluate selectors per step, so a flat
        #: scan would multiply to (steps x max_series) comparisons
        #: under the lock.
        self._by_name: dict[str, dict[tuple, Series]] = {}
        #: Reentrant (mark_stale -> add) lock: the pipeline mutates on
        #: the event loop while the apiserver offloads RANGE queries to
        #: a thread (query_range re-evaluates per step — inline it
        #: would stall the router loop; see _debug_query).
        self._lock = make_lock("kmon.TSDB", rlock=True)
        #: Instance-local drop counts by reason (tests assert these;
        #: the kmon_* counters aggregate across instances).
        self.dropped: dict[str, int] = {}
        self.ingested = 0

    @property
    def series_count(self) -> int:
        """Live series (the number the ``max_series`` ceiling bounds) —
        the fleet-width cardinality gate reads this."""
        with self._lock:
            return len(self._series)

    # -- write path -------------------------------------------------------

    def add(self, name: str, labels: dict, value: float,
            ts: float) -> bool:
        """Ingest one sample; False (+ counted drop) when refused."""
        with self._lock:
            return self._add(name, labels, value, ts)

    def _add(self, name: str, labels: dict, value: float,
             ts: float) -> bool:
        if self.step > 0 and not is_stale(value):
            ts = ts - (ts % self.step)
        key = series_key(name, labels)
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self.max_series:
                self._drop("series_limit")
                return False
            s = self._series[key] = Series(
                name, labels, self.max_samples_per_series)
            self._by_name.setdefault(name, {})[key] = s
        last = s.latest()
        if last is not None:
            if ts < last[0]:
                self._drop("out_of_order")
                return False
            if ts == last[0]:
                # Keep-last within a step bucket (downsampling), and
                # idempotent re-ingest of the same instant.
                s.samples[-1] = (ts, value)
                return True
        s.samples.append((ts, value))
        self.ingested += 1
        TSDB_INGESTED.inc()
        return True

    def mark_stale(self, ts: float,
                   matchers: Sequence[Matcher] = (),
                   name: str = "") -> int:
        """Append a staleness marker to every matching live series
        (skipping those already stale). Returns how many were marked.
        Marker timestamps sit on the step grid like real samples, so a
        subsequent same-instant live write (e.g. the ``up=0`` the
        scrape manager records for a down target) lands keep-last on
        top of the marker instead of colliding out-of-order."""
        if self.step > 0:
            ts = ts - (ts % self.step)
        n = 0
        with self._lock:
            for s in list(self._match(name, matchers)):
                last = s.latest()
                if last is None or is_stale(last[1]):
                    continue
                if self._add(s.name, s.labels, STALE, max(ts, last[0])):
                    n += 1
        return n

    def gc(self, now: float) -> int:
        """Retention prune: drop samples older than the window and
        delete series that emptied out (or hold only a stale marker
        older than the window). Returns samples dropped."""
        horizon = now - self.retention_seconds
        dropped = 0
        dead = []
        with self._lock:
            for key, s in self._series.items():
                while s.samples and s.samples[0][0] < horizon:
                    s.samples.popleft()
                    dropped += 1
                if not s.samples:
                    dead.append(key)
            for key in dead:
                s = self._series.pop(key)
                bucket = self._by_name.get(s.name)
                if bucket is not None:
                    bucket.pop(key, None)
                    if not bucket:
                        del self._by_name[s.name]
        if dropped:
            TSDB_DROPPED.inc(dropped, reason="retention")
            self.dropped["retention"] = \
                self.dropped.get("retention", 0) + dropped
        self._export()
        return dropped

    # -- read path --------------------------------------------------------

    def _match(self, name: str,
               matchers: Sequence[Matcher]) -> Iterable[Series]:
        pool = (self._by_name.get(name, {}).values() if name
                else self._series.values())
        for s in pool:
            if all(m.matches(s.labels) for m in matchers):
                yield s

    def select_range(self, name: str, matchers: Sequence[Matcher],
                     start: float, end: float) -> list[tuple[dict, list]]:
        """[(labels, [(ts, value), ...]), ...] for samples in
        (start, end], stale markers excluded (a range is data points,
        the marker only delimits instant lookback)."""
        out = []
        with self._lock:
            for s in self._match(name, matchers):
                pts = [(ts, v) for ts, v in s.samples
                       if start < ts <= end and not is_stale(v)]
                if pts:
                    out.append((dict(s.labels), pts))
        return out

    def select_instant(self, name: str, matchers: Sequence[Matcher],
                       at: float, lookback: float
                       ) -> list[tuple[dict, float, float]]:
        """[(labels, ts, value), ...]: per matching series, the newest
        sample at or before ``at`` within ``lookback`` — unless that
        sample is a staleness marker, which silences the series."""
        out = []
        with self._lock:
            for s in self._match(name, matchers):
                picked = None
                for ts, v in reversed(s.samples):
                    if ts <= at:
                        picked = (ts, v)
                        break
                if picked is None:
                    continue
                ts, v = picked
                if is_stale(v) or ts < at - lookback:
                    continue
                out.append((dict(s.labels), ts, v))
        return out

    def latest_value(self, name: str, **labels) -> Optional[tuple]:
        """(ts, value) of the newest sample of one exact series, stale
        markers included (None when the series does not exist)."""
        with self._lock:
            s = self._series.get(series_key(name, labels))
            return s.latest() if s is not None else None

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted({s.name for s in self._series.values()})

    # -- accounting -------------------------------------------------------

    def _drop(self, reason: str) -> None:
        self.dropped[reason] = self.dropped.get(reason, 0) + 1
        TSDB_DROPPED.inc(reason=reason)

    def _export(self) -> None:
        with self._lock:
            series = len(self._series)
            samples = sum(len(s.samples) for s in self._series.values())
        TSDB_SERIES.set(float(series))
        TSDB_SAMPLES.set(float(samples))

    def stats(self) -> dict:
        with self._lock:
            samples = sum(len(s.samples)
                          for s in self._series.values())
        self._export()
        return {
            "series": len(self._series),
            "samples": samples,
            "ingested": self.ingested,
            "dropped": dict(self.dropped),
            "max_series": self.max_series,
            "max_samples_per_series": self.max_samples_per_series,
            "retention_seconds": self.retention_seconds,
            # Structural ceiling, not a measurement: ~64B per (ts, v)
            # tuple pair + object overhead. The point is that it is a
            # CONSTANT for a given config.
            "bound_bytes_estimate":
                self.max_series * self.max_samples_per_series * 64,
        }
