"""Recording + alerting rules — the Prometheus rule-group analog.

Recording rules evaluate a PromQL-lite expression every tick and write
the result back into the TSDB under a ``level:metric:operation`` name
(the ``ktl dash`` sparkline sources). Alerting rules evaluate an
expression whose non-empty result means "this label set is in
violation"; an element must stay in violation for the rule's ``for:``
hold-down before the alert FIRES (one noisy scrape must not taint a
node), and an element that disappears resolves the alert.

The engine is pure state over the TSDB — side effects (Events, node
taints) belong to the pipeline, which consumes the transition list
``evaluate`` returns. That split keeps hold-down/resolve logic unit-
testable with a hand-fed store.

Built-in rules (``builtin_rules(interval)``) express the ROADMAP
item-5 seam: sick chips (health gone, duty collapse on an assigned
chip, ICI counter stall), node stragglers vs the fleet mean, apiserver
loop saturation, stale replication followers, and scrape-target-down.
Hold-downs scale with the scrape interval so a CI smoke at 0.3s
intervals and production at 10s get the same *number of confirming
scrapes*.
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..metrics.registry import Gauge
from . import promql
from .tsdb import TSDB

log = logging.getLogger("kmon.rules")

ALERTS_ACTIVE = Gauge(
    "kmon_alerts_active",
    "kmon alerts by rule name and state (pending/firing)",
    labels=("alertname", "state"))

#: The taint the pipeline applies for node-degrading firing alerts
#: (behind the AlertNodeTainting gate) — the seam the future migration
#: controller consumes.
TAINT_DEGRADED = "tpu.google.com/degraded"

PENDING = "pending"
FIRING = "firing"


@dataclass(frozen=True)
class RecordingRule:
    record: str
    expr: str


@dataclass(frozen=True)
class AlertRule:
    name: str
    expr: str
    for_seconds: float
    severity: str = "warning"
    summary: str = ""
    #: Firing instances whose labels name a node degrade that node
    #: (pipeline taints it when AlertNodeTainting is on).
    taint: bool = False


@dataclass
class AlertInstance:
    rule: AlertRule
    labels: dict
    state: str
    active_since: float
    value: float
    firing_since: float = 0.0

    def to_dict(self) -> dict:
        out = {
            "name": self.rule.name,
            "severity": self.rule.severity,
            "state": self.state,
            "labels": dict(sorted(self.labels.items())),
            "value": self.value,
            "active_since": round(self.active_since, 3),
            "summary": self.rule.summary,
        }
        if self.state == FIRING:
            out["firing_since"] = round(self.firing_since, 3)
        return out


@dataclass(frozen=True)
class Transition:
    kind: str  # "firing" | "resolved"
    rule: AlertRule
    labels: dict
    value: float = 0.0


def builtin_recording_rules() -> list[RecordingRule]:
    return [
        RecordingRule("cluster:tpu_duty:avg",
                      "avg(tpu_node_duty_cycle_avg_pct)"),
        RecordingRule("cluster:tpu_tokens:sum",
                      "sum(tpu_node_tokens_per_sec)"),
        RecordingRule("cluster:chips_unhealthy:sum",
                      "sum(1 - tpu_chip_healthy)"),
        RecordingRule("cluster:hbm_used:sum",
                      "sum(tpu_node_hbm_used_bytes)"),
        RecordingRule("cluster:fragmentation:max",
                      "max(tpu_cluster_fragmentation)"),
        RecordingRule("job:up:sum", "sum by (job) (up)"),
        RecordingRule("apiserver:loop_busy:max",
                      "max(apiserver_loop_busy_fraction)"),
    ]


def builtin_rules(interval: float) -> list[AlertRule]:
    """Hold-downs in confirming-scrape units: 2 scrapes for hard
    binary signals (health bit, up), 4 for derived/rate signals."""
    short = 2 * interval
    long = 4 * interval
    ici_window = max(6 * interval, 2.0)
    return [
        AlertRule(
            "TpuChipSick", "tpu_chip_healthy == 0",
            for_seconds=short, severity="critical", taint=True,
            summary="device plugin reports the chip unhealthy"),
        # The interesting metric sits LEFT of `and` — the alert's
        # value (ktl alerts VALUE, the Event message) comes from the
        # left vector, and "duty=2%" diagnoses; "assigned=1" doesn't.
        AlertRule(
            "TpuChipDutyCollapse",
            "tpu_duty_cycle_pct < 5 and tpu_chip_assigned == 1",
            for_seconds=long, severity="warning", taint=True,
            summary="assigned chip's duty cycle collapsed (<5%)"),
        AlertRule(
            "TpuIciStall",
            f"rate(tpu_ici_tx_bytes[{ici_window:g}s]) == 0 "
            "and tpu_chip_assigned == 1",
            for_seconds=long, severity="warning", taint=True,
            summary="assigned chip's ICI tx counter stopped moving"),
        AlertRule(
            "TpuNodeStraggler",
            "tpu_node_duty_cycle_avg_pct < 0.5 * "
            "scalar(avg(tpu_node_duty_cycle_avg_pct))",
            for_seconds=long, severity="warning",
            summary="node duty cycle under half the fleet mean"),
        AlertRule(
            "ApiServerLoopSaturated",
            "apiserver_loop_busy_fraction > 0.9",
            for_seconds=long, severity="critical",
            summary="apiserver event loop busy fraction above 0.9"),
        AlertRule(
            "ReplicationFollowerStale",
            "scalar(max(replication_commit_revision)) "
            "- replication_commit_revision > 200",
            for_seconds=long, severity="warning",
            summary="replica's committed revision lags the leader"),
        AlertRule(
            "ScrapeTargetDown", "up == 0",
            for_seconds=short, severity="critical",
            summary="scrape target down"),
    ]


def _instance_key(rule_name: str, labels: dict) -> tuple:
    return (rule_name,) + tuple(sorted(labels.items()))


class RuleEngine:
    def __init__(self, tsdb: TSDB,
                 alert_rules: Sequence[AlertRule] = (),
                 recording_rules: Sequence[RecordingRule] = (),
                 lookback: float = promql.DEFAULT_LOOKBACK):
        self.tsdb = tsdb
        self.alert_rules = list(alert_rules)
        self.recording_rules = list(recording_rules)
        self.lookback = lookback
        self._asts: dict[str, object] = {}
        self._active: dict[tuple, AlertInstance] = {}

    def _eval(self, expr: str, now: float):
        ast = self._asts.get(expr)
        if ast is None:
            ast = self._asts[expr] = promql.parse(expr)
        return promql.evaluate(
            ast, promql.EvalContext(self.tsdb, now, self.lookback))

    def evaluate(self, now: Optional[float] = None) -> list[Transition]:
        """One tick: recording rules write back, alerting rules step
        their pending/firing state machines. Returns the edge
        transitions (fire / resolve) for the pipeline to act on."""
        now = time.time() if now is None else now
        for rule in self.recording_rules:
            try:
                v = self._eval(rule.expr, now)
            except promql.PromQLError as e:
                log.warning("recording rule %s: %s", rule.record, e)
                continue
            if isinstance(v, float):
                self.tsdb.add(rule.record, {}, v, now)
            else:
                for labels, value in v:
                    self.tsdb.add(rule.record, labels, value, now)
        transitions: list[Transition] = []
        seen: set[tuple] = set()
        for rule in self.alert_rules:
            try:
                v = self._eval(rule.expr, now)
            except promql.PromQLError as e:
                log.warning("alert rule %s: %s", rule.name, e)
                continue
            if isinstance(v, float):
                v = [({}, v)] if v else []
            for labels, value in v:
                key = _instance_key(rule.name, labels)
                seen.add(key)
                inst = self._active.get(key)
                if inst is None:
                    inst = self._active[key] = AlertInstance(
                        rule=rule, labels=dict(labels), state=PENDING,
                        active_since=now, value=value)
                inst.value = value
                if inst.state == PENDING \
                        and now - inst.active_since >= rule.for_seconds:
                    inst.state = FIRING
                    inst.firing_since = now
                    transitions.append(Transition(
                        "firing", rule, dict(inst.labels), value))
        for key, inst in list(self._active.items()):
            if key in seen:
                continue
            del self._active[key]
            if inst.state == FIRING:
                transitions.append(Transition(
                    "resolved", inst.rule, dict(inst.labels)))
        self._export()
        return transitions

    def _export(self) -> None:
        counts: dict[tuple, int] = {}
        for inst in self._active.values():
            k = (inst.rule.name, inst.state)
            counts[k] = counts.get(k, 0) + 1
        for name in {r.name for r in self.alert_rules}:
            for state in (PENDING, FIRING):
                ALERTS_ACTIVE.set(float(counts.get((name, state), 0)),
                                  alertname=name, state=state)

    def alerts(self) -> list[dict]:
        """JSON-able active alerts (pending + firing), stable order."""
        return [inst.to_dict() for _k, inst in
                sorted(self._active.items())]

    def firing(self) -> list[AlertInstance]:
        return [i for i in self._active.values() if i.state == FIRING]
