"""PromQL-lite — the query half of the kmon pipeline.

A deliberately small, total subset of PromQL evaluated over the
in-process :class:`~kubernetes_tpu.monitoring.tsdb.TSDB`:

- instant + range selectors: ``name{label="v",other!="x",re=~"a.*"}``,
  ``name{...}[30s]``;
- functions: ``rate``, ``increase``, ``avg_over_time``,
  ``min_over_time``, ``max_over_time``, ``sum_over_time``,
  ``count_over_time``, ``last_over_time`` (newest raw sample in the
  window, staleness markers excluded), ``quantile_over_time(q, sel[d])``
  (nearest-rank over RAW samples, the bench-harness discipline),
  ``scalar``, ``abs``, ``timestamp`` (the sample timestamp of each
  element — with ``last_over_time`` this answers "how old is the
  last known point", the ktl stale-row query);
- aggregations: ``sum/avg/min/max/count [by (l1, l2)] (expr)``;
- binary ops: arithmetic ``+ - * /`` and comparisons
  ``> < >= <= == !=`` between scalars, vector/scalar (comparison
  filters, PromQL-style), and vector/vector matched one-to-one on
  identical label sets; set ops ``and``, ``or``, ``unless``.

That grammar covers every query the perf harnesses hand-rolled before
this PR (single-family gauge reads, loop-busy shares, quantiles) and
everything the built-in alerting rules need. It is NOT Prometheus:
no offset/@, no histogram_quantile, no group_left.

Evaluation is pure CPU over in-memory deques — instant queries on a
bounded TSDB are microseconds, safe on the apiserver loop.
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Optional, Sequence

from .tsdb import TSDB, Matcher

#: Instant-selector lookback (Prometheus: 5m). Staleness markers cut a
#: dead target off immediately; the lookback only bounds how far back a
#: LIVE series' newest sample may be.
DEFAULT_LOOKBACK = 300.0

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)?$")
_DURATION_UNIT = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0,
                  "d": 86400.0, None: 1.0}

_AGG_OPS = ("sum", "avg", "min", "max", "count")
_RANGE_FNS = {
    "rate", "increase", "avg_over_time", "min_over_time",
    "max_over_time", "sum_over_time", "count_over_time",
    "last_over_time",
}
_COMPARISONS = {">", "<", ">=", "<=", "==", "!="}


class PromQLError(ValueError):
    pass


def parse_duration(text: str) -> float:
    m = _DURATION_RE.match(text)
    if m is None:
        raise PromQLError(f"bad duration {text!r}")
    return float(m.group(1)) * _DURATION_UNIT[m.group(2)]


# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<duration>\d+(?:\.\d+)?(?:ms|[smhd])\b)
  | (?P<number>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<ident>[a-zA-Z_:][a-zA-Z0-9_:]*)
  | (?P<string>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<op>=~|!~|==|!=|>=|<=|[-+*/(){}\[\],><=])
""", re.VERBOSE)


def _lex(text: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise PromQLError(
                f"unexpected character {text[pos]!r} at {pos} in {text!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind != "ws":
            out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass
class Selector:
    name: str
    matchers: list = field(default_factory=list)
    range_seconds: float = 0.0  # > 0: range selector


@dataclass
class NumberLit:
    value: float


@dataclass
class FuncCall:
    fn: str
    args: list


@dataclass
class Aggregation:
    op: str
    by: tuple
    expr: object


@dataclass
class BinOp:
    op: str
    left: object
    right: object


class _Parser:
    def __init__(self, text: str):
        self.text = text
        self.toks = _lex(text)
        self.i = 0

    def peek(self) -> tuple[str, str]:
        return self.toks[self.i]

    def next(self) -> tuple[str, str]:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, val: str) -> None:
        kind, got = self.next()
        if got != val:
            raise PromQLError(
                f"expected {val!r}, got {got!r} in {self.text!r}")

    # precedence: or < and/unless < comparison < additive < product
    def parse(self):
        e = self.p_or()
        if self.peek()[0] != "eof":
            raise PromQLError(
                f"trailing input at {self.peek()[1]!r} in {self.text!r}")
        return e

    def p_or(self):
        e = self.p_and()
        while self.peek() == ("ident", "or"):
            self.next()
            e = BinOp("or", e, self.p_and())
        return e

    def p_and(self):
        e = self.p_cmp()
        while self.peek()[0] == "ident" \
                and self.peek()[1] in ("and", "unless"):
            op = self.next()[1]
            e = BinOp(op, e, self.p_cmp())
        return e

    def p_cmp(self):
        e = self.p_add()
        while self.peek()[0] == "op" and self.peek()[1] in _COMPARISONS:
            op = self.next()[1]
            e = BinOp(op, e, self.p_add())
        return e

    def p_add(self):
        e = self.p_mul()
        while self.peek() in (("op", "+"), ("op", "-")):
            op = self.next()[1]
            e = BinOp(op, e, self.p_mul())
        return e

    def p_mul(self):
        e = self.p_atom()
        while self.peek() in (("op", "*"), ("op", "/")):
            op = self.next()[1]
            e = BinOp(op, e, self.p_atom())
        return e

    def p_atom(self):
        kind, val = self.peek()
        if kind == "op" and val == "(":
            self.next()
            e = self.p_or()
            self.expect(")")
            return e
        if kind == "op" and val == "-":
            self.next()
            return BinOp("*", NumberLit(-1.0), self.p_atom())
        if kind in ("number", "duration"):
            self.next()
            return NumberLit(parse_duration(val)
                             if kind == "duration" else float(val))
        if kind != "ident":
            raise PromQLError(
                f"unexpected {val!r} in {self.text!r}")
        # aggregation / function / selector — disambiguate on lookahead
        if val in _AGG_OPS and self.toks[self.i + 1][1] in ("(", "by"):
            return self.p_aggregation()
        if self.toks[self.i + 1] == ("op", "(") \
                and (val in _RANGE_FNS
                     or val in ("quantile_over_time", "scalar", "abs",
                                "timestamp")):
            return self.p_func()
        return self.p_selector()

    def p_aggregation(self):
        op = self.next()[1]
        by: tuple = ()
        if self.peek() == ("ident", "by"):
            self.next()
            self.expect("(")
            labels = []
            while self.peek()[0] == "ident":
                labels.append(self.next()[1])
                if self.peek() == ("op", ","):
                    self.next()
            self.expect(")")
            by = tuple(labels)
        self.expect("(")
        e = self.p_or()
        self.expect(")")
        return Aggregation(op, by, e)

    def p_func(self):
        fn = self.next()[1]
        self.expect("(")
        args = [self.p_or()]
        while self.peek() == ("op", ","):
            self.next()
            args.append(self.p_or())
        self.expect(")")
        return FuncCall(fn, args)

    def p_selector(self):
        name = self.next()[1]
        matchers = []
        if self.peek() == ("op", "{"):
            self.next()
            while self.peek()[0] == "ident":
                label = self.next()[1]
                kind, op = self.next()
                if op not in ("=", "!=", "=~", "!~"):
                    raise PromQLError(f"bad matcher op {op!r}")
                skind, sval = self.next()
                if skind != "string":
                    raise PromQLError(
                        f"matcher value must be quoted, got {sval!r}")
                try:
                    matchers.append(Matcher(label, op, _unquote(sval)))
                except ValueError as e:  # bad =~/!~ regex
                    raise PromQLError(str(e)) from None
                if self.peek() == ("op", ","):
                    self.next()
            self.expect("}")
        rng = 0.0
        if self.peek() == ("op", "["):
            self.next()
            kind, dur = self.next()
            if kind not in ("duration", "number"):
                raise PromQLError(f"bad range duration {dur!r}")
            rng = parse_duration(dur)
            self.expect("]")
        return Selector(name, matchers, rng)


def _unquote(s: str) -> str:
    body = s[1:-1]
    return body.replace('\\"', '"').replace("\\'", "'").replace(
        "\\\\", "\\")


def parse(expr: str):
    """Parse to an AST (callers cache this for repeated evaluation)."""
    return _Parser(expr).parse()


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------

#: Instant vector element: (labels dict, value). Range vector element:
#: (labels dict, [(ts, value), ...]).


@dataclass
class EvalContext:
    tsdb: TSDB
    at: float
    lookback: float = DEFAULT_LOOKBACK


def _labels_no_name(labels: dict) -> dict:
    return {k: v for k, v in labels.items() if k != "__name__"}


def _lkey(labels: dict) -> tuple:
    return tuple(sorted(_labels_no_name(labels).items()))


def evaluate(node, ctx: EvalContext):
    """Scalar (float) or instant vector (list[(labels, value)])."""
    if isinstance(node, NumberLit):
        return node.value
    if isinstance(node, Selector):
        if node.range_seconds > 0:
            raise PromQLError(
                f"range selector {node.name}[...] needs a function "
                f"(rate/avg_over_time/...)")
        out = []
        for labels, _ts, v in ctx.tsdb.select_instant(
                node.name, node.matchers, ctx.at, ctx.lookback):
            labels["__name__"] = node.name
            out.append((labels, v))
        return out
    if isinstance(node, FuncCall):
        return _eval_func(node, ctx)
    if isinstance(node, Aggregation):
        return _eval_agg(node, ctx)
    if isinstance(node, BinOp):
        return _eval_binop(node, ctx)
    raise PromQLError(f"cannot evaluate {node!r}")


def _eval_range(node, ctx: EvalContext):
    if not isinstance(node, Selector) or node.range_seconds <= 0:
        raise PromQLError("expected a range selector, e.g. name[30s]")
    return ctx.tsdb.select_range(
        node.name, node.matchers, ctx.at - node.range_seconds, ctx.at)


def _rate(samples: list, window: float, counter: bool) -> Optional[float]:
    if len(samples) < 2:
        return None
    first_ts, first_v = samples[0]
    total = 0.0
    prev = first_v
    for _ts, v in samples[1:]:
        if counter and v < prev:
            total += prev  # counter reset: the pre-reset value counts
        prev = v
    increase = total + prev - first_v
    span = samples[-1][0] - first_ts
    if span <= 0:
        return None
    return increase / span


def _eval_func(node: FuncCall, ctx: EvalContext):
    fn = node.fn
    if fn == "scalar":
        v = evaluate(node.args[0], ctx)
        if isinstance(v, float):
            return v
        return v[0][1] if len(v) == 1 else math.nan
    if fn == "abs":
        v = evaluate(node.args[0], ctx)
        if isinstance(v, float):
            return abs(v)
        return [(labels, abs(x)) for labels, x in v]
    if fn == "timestamp":
        # Restricted vs Prometheus: the argument must be something
        # with a REAL sample timestamp — an instant selector, or
        # last_over_time(sel[d]). (General expressions would need
        # every element to carry a timestamp through the evaluator
        # for no current consumer.)
        arg = node.args[0]
        if isinstance(arg, Selector) and arg.range_seconds == 0:
            return [(_labels_no_name(labels), ts)
                    for labels, ts, _v in ctx.tsdb.select_instant(
                        arg.name, arg.matchers, ctx.at, ctx.lookback)]
        if isinstance(arg, FuncCall) and arg.fn == "last_over_time":
            return [(_labels_no_name(labels), samples[-1][0])
                    for labels, samples in _eval_range(arg.args[0], ctx)
                    if samples]
        raise PromQLError(
            "timestamp() takes an instant selector or "
            "last_over_time(sel[d])")
    if fn == "quantile_over_time":
        if len(node.args) != 2:
            raise PromQLError("quantile_over_time(q, sel[d])")
        q = evaluate(node.args[0], ctx)
        if not isinstance(q, float):
            raise PromQLError("quantile_over_time: q must be a scalar")
        if not 0.0 <= q <= 1.0:
            # Negative q would wrap around via Python indexing and
            # silently answer the window max.
            raise PromQLError(
                f"quantile_over_time: q must be in [0, 1], got {q:g}")
        out = []
        for labels, samples in _eval_range(node.args[1], ctx):
            vals = sorted(v for _ts, v in samples)
            idx = min(len(vals) - 1, int(q * len(vals)))
            out.append((_labels_no_name(labels), vals[idx]))
        return out
    if fn not in _RANGE_FNS:
        raise PromQLError(f"unknown function {fn!r}")
    out = []
    rv = _eval_range(node.args[0], ctx)
    window = node.args[0].range_seconds
    for labels, samples in rv:
        labels = _labels_no_name(labels)
        if fn in ("rate", "increase"):
            r = _rate(samples, window, counter=True)
            if r is None:
                continue
            out.append((labels, r * window if fn == "increase" else r))
            continue
        vals = [v for _ts, v in samples]
        if fn == "avg_over_time":
            out.append((labels, sum(vals) / len(vals)))
        elif fn == "min_over_time":
            out.append((labels, min(vals)))
        elif fn == "max_over_time":
            out.append((labels, max(vals)))
        elif fn == "sum_over_time":
            out.append((labels, sum(vals)))
        elif fn == "count_over_time":
            out.append((labels, float(len(vals))))
        elif fn == "last_over_time":
            out.append((labels, vals[-1]))
    return out


def _eval_agg(node: Aggregation, ctx: EvalContext):
    v = evaluate(node.expr, ctx)
    if isinstance(v, float):
        raise PromQLError(f"{node.op}() needs a vector, got a scalar")
    groups: dict[tuple, list[float]] = {}
    group_labels: dict[tuple, dict] = {}
    for labels, value in v:
        key = tuple((l, labels.get(l, "")) for l in node.by)
        groups.setdefault(key, []).append(value)
        group_labels[key] = dict(key)
    out = []
    for key, vals in groups.items():
        if node.op == "sum":
            agg = sum(vals)
        elif node.op == "avg":
            agg = sum(vals) / len(vals)
        elif node.op == "min":
            agg = min(vals)
        elif node.op == "max":
            agg = max(vals)
        else:
            agg = float(len(vals))
        out.append((group_labels[key], agg))
    return out


def _apply(op: str, a: float, b: float) -> Optional[float]:
    """Arithmetic returns a number; comparisons return the LEFT value
    when true, None when false (PromQL filter semantics)."""
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        if b == 0:
            return math.nan if a == 0 else math.copysign(math.inf, a)
        return a / b
    ok = {"<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b,
          "==": a == b, "!=": a != b}[op]
    return a if ok else None


def _eval_binop(node: BinOp, ctx: EvalContext):
    left = evaluate(node.left, ctx)
    right = evaluate(node.right, ctx)
    op = node.op
    if op in ("and", "or", "unless"):
        if isinstance(left, float) or isinstance(right, float):
            raise PromQLError(f"{op} needs vectors on both sides")
        rkeys = {_lkey(labels) for labels, _v in right}
        if op == "and":
            return [(l, v) for l, v in left if _lkey(l) in rkeys]
        if op == "unless":
            return [(l, v) for l, v in left if _lkey(l) not in rkeys]
        lkeys = {_lkey(labels) for labels, _v in left}
        return list(left) + [(l, v) for l, v in right
                             if _lkey(l) not in lkeys]
    if isinstance(left, float) and isinstance(right, float):
        r = _apply(op, left, right)
        if op in _COMPARISONS:
            # scalar comparison yields 1/0, not a filter
            return 1.0 if r is not None else 0.0
        return r
    if isinstance(left, float) or isinstance(right, float):
        vec, scalar, flipped = ((right, left, True)
                                if isinstance(left, float)
                                else (left, right, False))
        out = []
        for labels, v in vec:
            a, b = (scalar, v) if flipped else (v, scalar)
            r = _apply(op, a, b)
            if r is None:
                continue
            if op in _COMPARISONS:
                r = v  # filter keeps the vector element's own value
            out.append((_labels_no_name(labels), r))
        return out
    # vector (op) vector: one-to-one on identical label sets
    rindex = {_lkey(labels): v for labels, v in right}
    out = []
    for labels, v in left:
        key = _lkey(labels)
        if key not in rindex:
            continue
        r = _apply(op, v, rindex[key])
        if r is None:
            continue
        out.append((_labels_no_name(labels), r))
    return out


# ---------------------------------------------------------------------------
# query API (the /debug/v1/query response shape)
# ---------------------------------------------------------------------------

def query_instant(tsdb: TSDB, expr: str, at: float,
                  lookback: float = DEFAULT_LOOKBACK) -> dict:
    """Prometheus-shaped instant query result:
    ``{"resultType": "vector"|"scalar", "result": ...}``."""
    v = evaluate(parse(expr), EvalContext(tsdb, at, lookback))
    if isinstance(v, float):
        return {"resultType": "scalar", "result": [at, v]}
    return {"resultType": "vector", "result": [
        {"metric": _present_labels(labels), "value": [at, value]}
        for labels, value in sorted(
            v, key=lambda e: sorted(e[0].items()))]}


def query_range(tsdb: TSDB, expr: str, start: float, end: float,
                step: float,
                lookback: float = DEFAULT_LOOKBACK) -> dict:
    """Evaluate the expression at each step in [start, end]:
    ``{"resultType": "matrix", "result": [{"metric", "values"}]}``."""
    if not (math.isfinite(start) and math.isfinite(end)
            and math.isfinite(step)):
        # inf/NaN bypass the resolution guard (inf/inf is NaN) and
        # turn the step loop into a CPU-pinned spin — reject early.
        raise PromQLError("start/end/step must be finite")
    if step <= 0:
        raise PromQLError("step must be > 0")
    if end < start:
        raise PromQLError("end must be >= start")
    if (end - start) / step > 11_000:
        raise PromQLError("range query resolves to more than 11000 "
                          "points; widen the step")
    ast = parse(expr)
    by_series: dict[tuple, dict] = {}
    t = start
    while t <= end + 1e-9:
        v = evaluate(ast, EvalContext(tsdb, t, lookback))
        if isinstance(v, float):
            ent = by_series.setdefault((), {"metric": {}, "values": []})
            ent["values"].append([t, v])
        else:
            for labels, value in v:
                labels = _present_labels(labels)
                key = tuple(sorted(labels.items()))
                ent = by_series.setdefault(
                    key, {"metric": labels, "values": []})
                ent["values"].append([t, value])
        t += step
    return {"resultType": "matrix",
            "result": [by_series[k] for k in sorted(by_series)]}


def _present_labels(labels: dict) -> dict:
    return dict(sorted(labels.items()))
