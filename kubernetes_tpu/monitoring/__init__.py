"""Cluster-level monitoring — the metrics-server / DCGM-rollup analog.

Aggregates every node's ``/stats/summary`` into cluster-level
``tpu_cluster_*`` / per-node ``tpu_node_*`` series (aggregator.py) and
keeps a queryable snapshot — the custom-metrics seam the ROADMAP's
inference-autoscaling item will scale on.
"""
from .aggregator import ClusterMonitor  # noqa: F401
