"""Cluster-level monitoring — the metrics-server / DCGM-rollup analog,
plus kmon, the in-process Prometheus analog.

Two halves:

- ``aggregator.py`` (ClusterMonitor): every node's ``/stats/summary``
  rolled into cluster-level ``tpu_cluster_*`` / per-node ``tpu_node_*``
  series + the ``latest()`` snapshot the inference autoscaler reads.
- kmon (gate ``ClusterMetricsPipeline``): ``scrape.py`` (scrape
  manager) -> ``tsdb.py`` (bounded ring store) -> ``promql.py``
  (PromQL-lite, served at ``/debug/v1/query`` / ``ktl query``) ->
  ``rules.py`` (recording + alerting rules) -> ``pipeline.py``
  (the controller tying them together: Events + gated node taints).
"""
from .aggregator import ClusterMonitor  # noqa: F401
