"""Example out-of-process driver: a checkpoint-store mount.

The storage pattern TPU training actually needs: every pod of an
elastic job mounts the SAME durable checkpoint directory, so an
evicted-and-rescheduled worker resumes from the store
(``workloads/checkpoint.py`` reads/writes it). Stage materializes the
store's volume directory once per node; Publish gives each pod a
stable path into it (a symlink, this runtime's bind-mount analog) and
drops a breadcrumb so operators can see who mounted what.

Run out-of-process:
``python -m kubernetes_tpu.volumedriver.checkpoint_driver \
    --socket <dir>/checkpoint-store.sock --store <backing_dir>``

Reference analog: a CSI driver deployment's node plugin
(``pkg/volume/csi/csi_attacher.go`` consumers), collapsed to the
node-only subset this runtime's API carries.
"""
from __future__ import annotations

import json
import os
import time

import grpc

from . import api_pb2 as pb
from .service import VolumeDriverServicer

DRIVER_NAME = "checkpoint-store"


class CheckpointStoreDriver(VolumeDriverServicer):
    def __init__(self, store_dir: str):
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)

    def _volume_dir(self, volume_id: str) -> str:
        safe = volume_id.replace("/", "_")
        return os.path.join(self.store_dir, safe)

    def GetDriverInfo(self, request, context) -> pb.DriverInfo:
        return pb.DriverInfo(name=DRIVER_NAME, version="1.0")

    def NodeStageVolume(self, request, context) -> pb.StageResponse:
        vdir = self._volume_dir(request.volume_id)
        os.makedirs(vdir, exist_ok=True)
        # Store metadata written once (idempotent): which job this
        # checkpoint volume belongs to, from PV volume_attributes.
        meta = os.path.join(vdir, ".store.json")
        if not os.path.exists(meta):
            with open(meta, "w") as f:
                json.dump({"volume_id": request.volume_id,
                           "created": time.time(),
                           "parameters": dict(request.parameters)}, f)
        return pb.StageResponse()

    def NodePublishVolume(self, request, context) -> pb.PublishResponse:
        vdir = self._volume_dir(request.volume_id)
        if not os.path.isdir(vdir):
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"volume {request.volume_id} is not staged")
        target = request.target_path
        os.makedirs(os.path.dirname(target), exist_ok=True)
        # Symlink = this runtime's bind mount (ProcessRuntime projects
        # host paths the same way). Idempotent republish.
        if os.path.islink(target):
            os.unlink(target)
        elif os.path.isdir(target):
            os.rmdir(target)
        os.symlink(vdir, target)
        with open(os.path.join(vdir, ".publishers.json"), "a") as f:
            f.write(json.dumps({"pod_uid": request.pod_uid,
                                "at": time.time()}) + "\n")
        return pb.PublishResponse(host_path=target)

    def NodeUnpublishVolume(self, request, context) -> pb.UnpublishResponse:
        if os.path.islink(request.target_path):
            os.unlink(request.target_path)
        return pb.UnpublishResponse()

    def NodeUnstageVolume(self, request, context) -> pb.UnstageResponse:
        # The STORE is durable by definition — unstage is a no-op
        # beyond forgetting node-local state (none here).
        return pb.UnstageResponse()


def main(argv=None) -> int:
    import argparse
    import signal
    import threading

    from .service import serve

    p = argparse.ArgumentParser(prog="checkpoint-store-driver")
    p.add_argument("--socket", required=True)
    p.add_argument("--store", required=True)
    args = p.parse_args(argv)
    server = serve(CheckpointStoreDriver(args.store), args.socket)
    print(f"SERVING {args.socket}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop(grace=1.0)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
