"""Driver discovery — sockets in a directory, like device plugins.

Reference: kubelet plugin registration
(``pkg/kubelet/util/pluginwatcher`` in later reference versions; the
device-plugin socket-dir convention in this one). A driver named
``store`` serves on ``<dir>/store.sock``; the agent resolves PV specs
whose ``driver`` field says ``store`` through that socket. No watch
machinery: mounts are infrequent, so an on-demand stat of the socket
path is honest and race-free (a dead socket fails the mount, which
retries on the next pod sync — crash-only).
"""
from __future__ import annotations

import os
from typing import Optional

from .service import VolumeDriverClient


class DriverRegistry:
    def __init__(self, driver_dir: str):
        self.driver_dir = driver_dir
        self._clients: dict[str, VolumeDriverClient] = {}

    def get(self, driver: str) -> Optional[VolumeDriverClient]:
        """Client for ``driver``, or None when its socket is absent."""
        path = os.path.join(self.driver_dir, f"{driver}.sock")
        if not os.path.exists(path):
            self._drop(driver)
            return None
        client = self._clients.get(driver)
        if client is None or client.socket_path != path:
            self._drop(driver)
            client = VolumeDriverClient(path)
            self._clients[driver] = client
        return client

    def _drop(self, driver: str) -> None:
        old = self._clients.pop(driver, None)
        if old is not None:
            old.close()

    def close(self) -> None:
        for name in list(self._clients):
            self._drop(name)
