"""Out-of-process volume drivers — the CSI-analog seam.

The one vendor-neutral gRPC boundary of the reference's storage stack
(``pkg/volume/csi/csi_plugin.go:40`` over ``pkg/volume/plugins.go:49``)
rebuilt on the device-plugin pattern: drivers serve ``api.proto`` on a
unix socket under the agent's ``volume-drivers/`` directory; the agent
consumes them through :class:`DriverRegistry` knowing only the wire
contract. ``checkpoint_driver`` is the shipped example (a
checkpoint-store mount for elastic training jobs).
"""
from .registry import DriverRegistry
from .service import (VolumeDriverClient, VolumeDriverServicer,
                      add_servicer_to_server, serve)

__all__ = ["DriverRegistry", "VolumeDriverClient", "VolumeDriverServicer",
           "add_servicer_to_server", "serve"]
