"""gRPC plumbing for the volume-driver API (CSI-analog seam).

Same approach as ``deviceplugin/service.py``: grpc_tools is not in the
image, so servicer/stub are written against grpc's generic handler API
with protoc-generated messages — wire-identical to generated
``*_pb2_grpc.py`` (method paths follow ``/package.Service/Method``),
so foreign gRPC drivers interoperate.

Reference seam: ``pkg/volume/csi/csi_client.go`` (the kubelet's CSI
node client) over ``pkg/volume/plugins.go:49``'s plugin boundary.
"""
from __future__ import annotations

import grpc

from . import api_pb2 as pb

SERVICE = "tpuvolumedriver.v1.VolumeDriver"


class VolumeDriverServicer:
    """Subclass and override; defaults reject (a driver that forgets a
    method must fail loudly, not no-op a mount)."""

    def GetDriverInfo(self, request: pb.Empty, context) -> pb.DriverInfo:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetDriverInfo")

    def NodeStageVolume(self, request: pb.StageRequest,
                        context) -> pb.StageResponse:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "NodeStageVolume")

    def NodePublishVolume(self, request: pb.PublishRequest,
                          context) -> pb.PublishResponse:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "NodePublishVolume")

    def NodeUnpublishVolume(self, request: pb.UnpublishRequest,
                            context) -> pb.UnpublishResponse:
        return pb.UnpublishResponse()

    def NodeUnstageVolume(self, request: pb.UnstageRequest,
                          context) -> pb.UnstageResponse:
        return pb.UnstageResponse()


def add_servicer_to_server(servicer: VolumeDriverServicer,
                           server: grpc.Server) -> None:
    handlers = {
        "GetDriverInfo": grpc.unary_unary_rpc_method_handler(
            servicer.GetDriverInfo,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.DriverInfo.SerializeToString),
        "NodeStageVolume": grpc.unary_unary_rpc_method_handler(
            servicer.NodeStageVolume,
            request_deserializer=pb.StageRequest.FromString,
            response_serializer=pb.StageResponse.SerializeToString),
        "NodePublishVolume": grpc.unary_unary_rpc_method_handler(
            servicer.NodePublishVolume,
            request_deserializer=pb.PublishRequest.FromString,
            response_serializer=pb.PublishResponse.SerializeToString),
        "NodeUnpublishVolume": grpc.unary_unary_rpc_method_handler(
            servicer.NodeUnpublishVolume,
            request_deserializer=pb.UnpublishRequest.FromString,
            response_serializer=pb.UnpublishResponse.SerializeToString),
        "NodeUnstageVolume": grpc.unary_unary_rpc_method_handler(
            servicer.NodeUnstageVolume,
            request_deserializer=pb.UnstageRequest.FromString,
            response_serializer=pb.UnstageResponse.SerializeToString),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),))


class VolumeDriverClient:
    """Agent-side stub over a driver's unix socket."""

    def __init__(self, socket_path: str, timeout: float = 10.0):
        self.socket_path = socket_path
        self.timeout = timeout
        self._channel = grpc.insecure_channel(f"unix://{socket_path}")

    def _call(self, method: str, request, response_cls):
        rpc = self._channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=type(request).SerializeToString,
            response_deserializer=response_cls.FromString)
        return rpc(request, timeout=self.timeout)

    def info(self) -> pb.DriverInfo:
        return self._call("GetDriverInfo", pb.Empty(), pb.DriverInfo)

    def stage(self, volume_id: str, staging_path: str,
              parameters: dict, read_only: bool) -> None:
        self._call("NodeStageVolume",
                   pb.StageRequest(volume_id=volume_id,
                                   staging_path=staging_path,
                                   parameters=parameters,
                                   read_only=read_only),
                   pb.StageResponse)

    def publish(self, volume_id: str, staging_path: str, target_path: str,
                pod_uid: str, parameters: dict, read_only: bool) -> str:
        resp = self._call(
            "NodePublishVolume",
            pb.PublishRequest(volume_id=volume_id, staging_path=staging_path,
                              target_path=target_path, pod_uid=pod_uid,
                              parameters=parameters, read_only=read_only),
            pb.PublishResponse)
        return resp.host_path or target_path

    def unpublish(self, volume_id: str, target_path: str,
                  pod_uid: str) -> None:
        self._call("NodeUnpublishVolume",
                   pb.UnpublishRequest(volume_id=volume_id,
                                       target_path=target_path,
                                       pod_uid=pod_uid),
                   pb.UnpublishResponse)

    def unstage(self, volume_id: str, staging_path: str) -> None:
        self._call("NodeUnstageVolume",
                   pb.UnstageRequest(volume_id=volume_id,
                                     staging_path=staging_path),
                   pb.UnstageResponse)

    def close(self) -> None:
        self._channel.close()


def serve(servicer: VolumeDriverServicer, socket_path: str) -> grpc.Server:
    """Start a driver server on a unix socket (driver-side helper)."""
    import os
    from concurrent import futures
    os.makedirs(os.path.dirname(socket_path), exist_ok=True)
    if os.path.exists(socket_path):
        os.unlink(socket_path)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    add_servicer_to_server(servicer, server)
    server.add_insecure_port(f"unix://{socket_path}")
    server.start()
    return server
