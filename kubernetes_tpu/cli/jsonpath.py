"""JSONPath subset for ``ktl get -o jsonpath`` / ``custom-columns`` /
``--sort-by``.

Reference: ``pkg/util/jsonpath`` (kubectl's template dialect, itself a
subset of JSONPath). Supported here — the constructs kubectl's docs
actually demonstrate:

- ``{.a.b.c}`` dotted field access (maps / object attributes)
- ``{.items[*].x}`` wildcard over lists, ``{.items[2].x}`` index,
  negative indices
- ``{range .items[*]}...{end}`` iteration with nested expressions
- ``{.a['b.c']}`` quoted key access (keys containing dots)
- plain text between expressions, ``\n`` / ``\t`` escapes
- top-level ``$`` is implicit and accepted

Filters (``?(@...)``), unions, and slices are not implemented; using
them raises with the offending token named.
"""
from __future__ import annotations

import re
from typing import Any


class JsonPathError(ValueError):
    pass


_SEG = re.compile(
    r"""
    \.(?P<field>[A-Za-z_][A-Za-z0-9_\-]*)      # .field
  | \[\s*'(?P<qkey>[^']*)'\s*\]                # ['key.with.dots']
  | \[\s*"(?P<dqkey>[^"]*)"\s*\]               # ["key"]
  | \[\s*(?P<index>-?\d+)\s*\]                 # [3] / [-1]
  | \[\s*(?P<star>\*)\s*\]                     # [*]
    """,
    re.VERBOSE,
)


def _parse_path(expr: str, source: str) -> list:
    """``.a.b[0][*]['k']`` -> segment list."""
    expr = expr.strip()
    # kubectl --sort-by/custom-columns accept both {.x} and .x forms.
    if expr.startswith("{") and expr.endswith("}"):
        expr = expr[1:-1].strip()
    if expr.startswith("$"):
        expr = expr[1:]
    segs: list = []
    pos = 0
    while pos < len(expr):
        m = _SEG.match(expr, pos)
        if not m:
            raise JsonPathError(
                f"{source}: unsupported jsonpath syntax at "
                f"{expr[pos:pos + 20]!r} (filters/unions/slices are not "
                f"implemented)")
        if m.group("field") is not None:
            segs.append(("key", m.group("field")))
        elif m.group("qkey") is not None:
            segs.append(("key", m.group("qkey")))
        elif m.group("dqkey") is not None:
            segs.append(("key", m.group("dqkey")))
        elif m.group("index") is not None:
            segs.append(("index", int(m.group("index"))))
        else:
            segs.append(("star", None))
        pos = m.end()
    return segs


def _get_one(obj: Any, kind: str, arg) -> list:
    """Apply one segment to one value -> list of results (missing
    fields vanish, matching kubectl's lenient lookups)."""
    if kind == "key":
        if isinstance(obj, dict):
            return [obj[arg]] if arg in obj else []
        if hasattr(obj, arg):
            return [getattr(obj, arg)]
        return []
    if kind == "index":
        if isinstance(obj, (list, tuple)):
            try:
                return [obj[arg]]
            except IndexError:
                return []
        return []
    # star
    if isinstance(obj, dict):
        return list(obj.values())
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return []


def eval_path(segs: list, data: Any) -> list:
    """Evaluate parsed segments against data -> flat result list."""
    current = [data]
    for kind, arg in segs:
        nxt: list = []
        for obj in current:
            nxt.extend(_get_one(obj, kind, arg))
        current = nxt
    return current


def find(expr: str, data: Any, source: str = "jsonpath") -> list:
    return eval_path(_parse_path(expr, source), data)


def _fmt(v: Any) -> str:
    if v is None:
        return "<none>"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (dict, list)):
        import json
        return json.dumps(v, separators=(",", ":"), default=str)
    return str(v)


_TOKEN = re.compile(r"\{([^{}]*)\}")


def render_template(template: str, data: Any) -> str:
    """kubectl ``-o jsonpath=`` template: text + {expr} + range/end."""
    template = template.replace("\\n", "\n").replace("\\t", "\t")
    tokens: list = []  # ("text", s) | ("expr", segs) | ("range", segs) | ("end",)
    pos = 0
    for m in _TOKEN.finditer(template):
        if m.start() > pos:
            tokens.append(("text", template[pos:m.start()]))
        body = m.group(1).strip()
        if ((body.startswith('"') and body.endswith('"'))
                or (body.startswith("'") and body.endswith("'"))):
            # kubectl's quoted-literal idiom: {range ...}{.x}{"\n"}{end}
            tokens.append(("text", body[1:-1]))
        elif body == "end":
            tokens.append(("end",))
        elif body.startswith("range"):
            tokens.append(("range", _parse_path(body[len("range"):],
                                                "jsonpath")))
        else:
            tokens.append(("expr", _parse_path(body, "jsonpath")))
        pos = m.end()
    if pos < len(template):
        tokens.append(("text", template[pos:]))

    def emit(toks: list, scope: Any) -> str:
        out: list[str] = []
        i = 0
        while i < len(toks):
            tok = toks[i]
            if tok[0] == "text":
                out.append(tok[1])
                i += 1
            elif tok[0] == "expr":
                out.append(" ".join(_fmt(v)
                                    for v in eval_path(tok[1], scope)))
                i += 1
            elif tok[0] == "range":
                depth, j = 1, i + 1
                while j < len(toks):
                    if toks[j][0] == "range":
                        depth += 1
                    elif toks[j][0] == "end":
                        depth -= 1
                        if depth == 0:
                            break
                    j += 1
                if j == len(toks):
                    raise JsonPathError("jsonpath: {range} without {end}")
                body = toks[i + 1:j]
                for item in eval_path(tok[1], scope):
                    out.append(emit(body, item))
                i = j + 1
            else:  # stray end
                raise JsonPathError("jsonpath: {end} without {range}")
        return "".join(out)

    return emit(tokens, data)


def sort_key(expr: str, data: Any):
    """--sort-by key: first match of expr, None sorts first. Mixed
    types fall back to string comparison (kubectl behavior)."""
    got = find(expr, data, source="--sort-by")
    return got[0] if got else None
