"""Table printers for ktl — the ``pkg/printers/`` analog.

One printer per kind (kubectl's human-readable tables); unknown kinds
fall back to NAME/AGE. ``-o json|yaml|wide`` handled by the CLI layer.
"""
from __future__ import annotations

import datetime
from typing import Any, Callable

from ..api import types as t
from ..api.meta import now


def age_seconds(secs: float) -> str:
    """Compact duration (``37s``/``5m``/``2h``/``3d``) — shared by
    object-age columns and the telemetry staleness columns."""
    secs = int(secs)
    if secs < 0:
        secs = 0
    for unit, span in (("d", 86400), ("h", 3600), ("m", 60)):
        if secs >= span:
            return f"{secs // span}{unit}"
    return f"{secs}s"


def age(meta) -> str:
    ts = meta.creation_timestamp
    if ts is None:
        return "<unknown>"
    return age_seconds((now() - ts).total_seconds())


def render_table(headers: list[str], rows: list[list[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(str(cell)))
    fmt = "   ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers)]
    lines += [fmt.format(*(str(c) for c in row)) for row in rows]
    return "\n".join(lines)


def _pod_ready(pod: t.Pod) -> str:
    total = len(pod.spec.containers)
    ready = sum(1 for c in pod.status.container_statuses if c.ready)
    return f"{ready}/{total}"


def _pod_status(pod: t.Pod) -> str:
    if pod.metadata.deletion_timestamp is not None:
        return "Terminating"
    if pod.status.reason:
        return pod.status.reason
    for cs in pod.status.container_statuses:
        if cs.state.waiting and cs.state.waiting.reason:
            return cs.state.waiting.reason
    return pod.status.phase or "Pending"


def pods_table(pods: list[t.Pod], wide: bool = False) -> str:
    headers = ["NAME", "READY", "STATUS", "RESTARTS", "AGE"]
    if wide:
        headers += ["NODE", "CHIPS"]
    rows = []
    for p in pods:
        restarts = sum(c.restart_count for c in p.status.container_statuses)
        row = [p.metadata.name, _pod_ready(p), _pod_status(p),
               restarts, age(p.metadata)]
        if wide:
            chips = ",".join(cid for r in p.spec.tpu_resources
                             for cid in r.assigned)
            row += [p.spec.node_name or "<none>", chips or "<none>"]
        rows.append(row)
    return render_table(headers, rows)


def nodes_table(nodes: list[t.Node], wide: bool = False) -> str:
    headers = ["NAME", "STATUS", "TPU", "AGE"]
    if wide:
        headers += ["SLICE", "MESH", "ADDRESS"]
    rows = []
    for n in nodes:
        cond = t.get_node_condition(n.status, t.NODE_READY)
        status = ("Ready" if cond and cond.status == "True" else "NotReady")
        if n.spec.unschedulable:
            status += ",SchedulingDisabled"
        tpu = int(n.status.capacity.get(t.RESOURCE_TPU, 0))
        row = [n.metadata.name, status, tpu or "<none>", age(n.metadata)]
        if wide:
            topo = n.status.tpu
            addr = n.status.addresses[0].address if n.status.addresses else ""
            row += [topo.slice_id if topo else "<none>",
                    "x".join(map(str, topo.mesh_shape)) if topo else "<none>",
                    addr]
        rows.append(row)
    return render_table(headers, rows)


def _replicas_table(objs: list, wide: bool) -> str:
    rows = [[o.metadata.name,
             f"{getattr(o.status, 'ready_replicas', 0)}/{o.spec.replicas}",
             getattr(o.status, "updated_replicas",
                     getattr(o.status, "replicas", 0)),
             getattr(o.status, "available_replicas", 0),
             age(o.metadata)] for o in objs]
    return render_table(["NAME", "READY", "UP-TO-DATE", "AVAILABLE", "AGE"], rows)


def _jobs_table(objs: list, wide: bool) -> str:
    rows = [[o.metadata.name,
             f"{getattr(o.status, 'succeeded', 0)}/{getattr(o.spec, 'completions', 1) or 1}",
             age(o.metadata)] for o in objs]
    return render_table(["NAME", "COMPLETIONS", "AGE"], rows)


def _elastic_size(pg) -> str:
    """current/min/max member target, or min_member for fixed gangs."""
    if not pg.spec.max_replicas:
        return str(pg.spec.min_member)
    cur = pg.status.replicas or pg.spec.max_replicas
    return f"{cur}/{pg.spec.min_replicas}..{pg.spec.max_replicas}"


def _podgroups_table(objs: list, wide: bool) -> str:
    headers = ["NAME", "MIN-MEMBER", "PHASE", "AGE"]
    if wide:
        headers += ["SIZE", "PREEMPTION", "CKPT-STEP", "MIGRATION"]
    rows = []
    for o in objs:
        row = [o.metadata.name, o.spec.min_member,
               getattr(o.status, "phase", ""), age(o.metadata)]
        if wide:
            st = o.status.preemption
            mig = getattr(o.status, "migration", None)
            row += [_elastic_size(o),
                    (st.phase or "<none>") if st else "<none>",
                    (st.checkpoint_step if st and st.checkpoint_step >= 0
                     else "<none>") if st else "<none>",
                    (mig.phase or mig.outcome or "<none>")
                    if mig else "<none>"]
        rows.append(row)
    return render_table(headers, rows)


def describe_podgroup(pg) -> str:
    """Gang summary: elastic size, graceful-preemption state, then the
    generic field dump."""
    lines = [f"Name: {pg.metadata.name}",
             f"Phase: {pg.status.phase or 'Pending'}",
             f"Members: {_elastic_size(pg)} (quorum {pg.spec.min_member})"]
    if pg.spec.queue:
        mode = pg.status.admission_mode or "<pending>"
        lines.append(f"Queue: {pg.spec.queue} "
                     f"(admitted={pg.status.admitted}, mode={mode})")
    ck = pg.spec.checkpoint
    if ck is not None:
        lines.append(f"Checkpoint: grace={ck.grace_seconds:g}s "
                     f"signal={ck.signal}")
    st = pg.status.preemption
    if st is not None:
        lines.append(f"Preemption: phase={st.phase or '<idle>'} "
                     f"rounds={st.rounds}"
                     + (f" outcome={st.outcome}" if st.outcome else ""))
        lines.append("Last checkpoint step: "
                     + (str(st.checkpoint_step)
                        if st.checkpoint_step >= 0 else "<none>"))
        if st.signaled:
            lines.append(f"Signaled: {len(st.checkpointed)}/"
                         f"{len(st.signaled)} members checkpointed")
    mig = getattr(pg.status, "migration", None)
    if mig is not None and (mig.phase or mig.outcome):
        line = (f"Migration: phase={mig.phase or '<idle>'} "
                f"rounds={mig.rounds}")
        if mig.reason:
            line += f" reason={mig.reason}"
        if mig.outcome:
            line += f" outcome={mig.outcome}"
        lines.append(line)
        if mig.target_slice:
            lines.append(f"Migration target: {mig.target_slice} "
                         f"({len(mig.target_cells)} chips on "
                         f"{len(mig.target_nodes)} nodes)")
    lines.append("")
    return "\n".join(lines) + _describe_fields(pg)


def _fmt_chips(amount) -> str:
    return f"{amount:g}" if amount else "0"


def _clusterqueues_table(objs: list, wide: bool) -> str:
    headers = ["NAME", "COHORT", "PENDING", "ADMITTED", "RECLAIMING",
               "BORROWED", "NOMINAL", "AGE"]
    rows = []
    for q in objs:
        rows.append([
            q.metadata.name, q.spec.cohort or "<none>",
            q.status.pending, q.status.admitted, q.status.reclaiming,
            _fmt_chips(q.status.borrowed.get(t.RESOURCE_TPU, 0.0)),
            _fmt_chips(q.spec.nominal_quota.get(t.RESOURCE_TPU, 0.0)),
            age(q.metadata)])
    return render_table(headers, rows)


def _localqueues_table(objs: list, wide: bool) -> str:
    rows = [[q.metadata.name, q.spec.cluster_queue,
             q.status.pending, q.status.admitted, age(q.metadata)]
            for q in objs]
    return render_table(
        ["NAME", "CLUSTERQUEUE", "PENDING", "ADMITTED", "AGE"], rows)


def describe_clusterqueue(cq) -> str:
    """Per-tenant usage vs quota, then the generic field dump."""
    lines = [f"Name: {cq.metadata.name}",
             f"Cohort: {cq.spec.cohort or '<none>'}",
             f"Pending: {cq.status.pending}",
             f"Admitted: {cq.status.admitted}",
             "Quota:"]
    for res in sorted(cq.spec.nominal_quota):
        used = cq.status.usage.get(res, 0.0)
        borrowed = cq.status.borrowed.get(res, 0.0)
        line = (f"  {res}: {used:g} used / "
                f"{cq.spec.nominal_quota[res]:g} nominal")
        if borrowed:
            line += f" (+{borrowed:g} borrowed)"
        lines.append(line)
    if cq.status.tenant_usage:
        lines.append("Tenants:")
        for tenant in sorted(cq.status.tenant_usage):
            usage = cq.status.tenant_usage[tenant]
            lines.append("  " + tenant + ": " + ", ".join(
                f"{res}={amt:g}" for res, amt in sorted(usage.items())))
    lines.append("")
    return "\n".join(lines) + _describe_fields(cq)


def _inferenceservices_table(objs: list, wide: bool) -> str:
    headers = ["NAME", "MODEL", "READY", "DESIRED", "WINDOW", "TOK/S",
               "UTIL", "AGE"]
    if wide:
        headers += ["CHIPS/REPLICA", "SLO-MS", "LAST-SCALE"]
    rows = []
    for o in objs:
        st, sp = o.status, o.spec
        row = [o.metadata.name, sp.model or "<none>",
               f"{st.ready_replicas}/{st.replicas}",
               st.desired_replicas,
               f"{sp.min_replicas}..{sp.max_replicas}",
               f"{st.tokens_per_sec:g}",
               f"{st.utilization:.2f}",
               age(o.metadata)]
        if wide:
            from ..api.serving import replica_chips
            row += [replica_chips(sp) or "<none>",
                    f"{sp.slo_target_ms:g}",
                    (st.last_scale_reason or "<none>")[:40]]
        rows.append(row)
    return render_table(headers, rows)


def describe_inferenceservice(isvc) -> str:
    """Serving summary: replica window + autoscaler state, then the
    generic field dump."""
    sp, st = isvc.spec, isvc.status
    lines = [f"Name: {isvc.metadata.name}",
             f"Model: {sp.model or '<none>'}",
             f"Replicas: {st.ready_replicas}/{st.replicas} ready "
             f"(desired {st.desired_replicas}, window "
             f"{sp.min_replicas}..{sp.max_replicas})",
             f"Per replica: {sp.chips_per_replica} chips"
             + (f" (shape {'x'.join(map(str, sp.slice_shape))})"
                if sp.slice_shape else ""),
             f"SLO: {sp.slo_target_ms:g}ms; rated "
             f"{sp.rated_tokens_per_sec:g} tok/s/replica; target "
             f"utilization {sp.target_utilization:g}",
             f"Observed: {st.tokens_per_sec:g} tok/s, utilization "
             f"{st.utilization:.2f}, snapshot age "
             f"{st.snapshot_age_seconds:g}s"]
    if st.last_scale_reason:
        lines.append(f"Last scale: {st.last_scale_reason}")
    lines.append("")
    return "\n".join(lines) + _describe_fields(isvc)


def _trainjobs_table(objs: list, wide: bool) -> str:
    headers = ["NAME", "MODEL", "WORKERS", "READY", "PHASE", "ROUNDS",
               "RESUMES", "CKPT-STEP", "AGE"]
    if wide:
        headers += ["CHIPS/WORKER", "QUEUE"]
    rows = []
    for o in objs:
        st, sp = o.status, o.spec
        row = [o.metadata.name, sp.model or "<none>",
               sp.num_workers,
               f"{st.ready_workers}/{sp.num_workers}",
               st.phase, st.restart_rounds, st.resumes,
               (st.last_checkpoint_step
                if st.last_checkpoint_step >= 0 else "<none>"),
               age(o.metadata)]
        if wide:
            from ..api.training import worker_chips
            row += [worker_chips(sp) or "<none>", sp.queue or "<none>"]
        rows.append(row)
    return render_table(headers, rows)


def describe_trainjob(tj) -> str:
    """Training summary: gang shape + round/resume/checkpoint state +
    the per-rank view, then the generic field dump."""
    sp, st = tj.spec, tj.status
    from ..api.training import worker_chips
    lines = [f"Name: {tj.metadata.name}",
             f"Model: {sp.model or '<none>'}",
             f"Workers: {st.ready_workers}/{sp.num_workers} ready "
             f"(phase {st.phase})",
             f"Per worker: {worker_chips(sp)} chips"
             + (f" (shape {'x'.join(map(str, sp.slice_shape))})"
                if sp.slice_shape else ""),
             f"Rounds: {st.restart_rounds} restarts, {st.resumes} "
             f"resumed from checkpoint",
             "Last checkpoint step: "
             + (str(st.last_checkpoint_step)
                if st.last_checkpoint_step >= 0 else "<none>")]
    if sp.checkpoint.pvc:
        from ..api.training import checkpoint_every
        lines.append(f"Checkpoint volume: pvc/{sp.checkpoint.pvc} "
                     f"(every {checkpoint_every(sp)} steps)")
    if sp.queue:
        lines.append(f"Queue: {sp.queue}")
    if st.worker_states:
        lines.append("Ranks:")
        for rank in sorted(st.worker_states, key=int):
            lines.append(f"  {rank}: {st.worker_states[rank]}")
    if st.message:
        lines.append(f"Message: {st.message}")
    lines.append("")
    return "\n".join(lines) + _describe_fields(tj)


def _services_table(objs: list, wide: bool) -> str:
    rows = [[o.metadata.name, o.spec.cluster_ip or "<none>",
             ",".join(f"{p.port}/{p.protocol or 'TCP'}"
                      for p in o.spec.ports) or "<none>",
             age(o.metadata)] for o in objs]
    return render_table(["NAME", "CLUSTER-IP", "PORTS", "AGE"], rows)


def _events_table(objs: list, wide: bool) -> str:
    rows = [[age(o.metadata), o.type, o.reason,
             f"{o.involved_object.kind}/{o.involved_object.name}",
             (o.message or "")[:80]] for o in objs]
    return render_table(["AGE", "TYPE", "REASON", "OBJECT", "MESSAGE"], rows)


def generic_table(objs: list, wide: bool = False) -> str:
    return render_table(["NAME", "AGE"],
                        [[o.metadata.name, age(o.metadata)] for o in objs])


PRINTERS: dict[str, Callable[[list, bool], str]] = {
    "pods": pods_table,
    "nodes": nodes_table,
    "deployments": _replicas_table,
    "replicasets": _replicas_table,
    "statefulsets": _replicas_table,
    "jobs": _jobs_table,
    "podgroups": _podgroups_table,
    "clusterqueues": _clusterqueues_table,
    "localqueues": _localqueues_table,
    "inferenceservices": _inferenceservices_table,
    "trainjobs": _trainjobs_table,
    "services": _services_table,
    "events": _events_table,
}


def print_objects(plural: str, objs: list, wide: bool = False) -> str:
    if not objs:
        return "No resources found."
    return PRINTERS.get(plural, generic_table)(objs, wide)


def describe(obj: Any) -> str:
    """kubectl describe analog: kind-specific summaries for queueing
    kinds (usage vs quota) and PodGroups (elastic size + preemption
    state), generic schema-driven dump otherwise."""
    if type(obj).__name__ == "ClusterQueue":
        return describe_clusterqueue(obj)
    if type(obj).__name__ == "PodGroup":
        return describe_podgroup(obj)
    if type(obj).__name__ == "InferenceService":
        return describe_inferenceservice(obj)
    if type(obj).__name__ == "TrainJob":
        return describe_trainjob(obj)
    return _describe_fields(obj)


def _describe_fields(obj: Any) -> str:
    """Indented field dump (schema-driven)."""
    from ..api.scheme import to_dict
    lines: list[str] = []

    def emit(key: str, value, indent: int) -> None:
        pad = "  " * indent
        if isinstance(value, dict):
            if not value:
                return
            lines.append(f"{pad}{key}:")
            for k, v in value.items():
                emit(str(k), v, indent + 1)
        elif isinstance(value, list):
            if not value:
                return
            lines.append(f"{pad}{key}:")
            for i, v in enumerate(value):
                emit(f"- [{i}]", v, indent + 1)
        else:
            if value in ("", None):
                return
            lines.append(f"{pad}{key}: {value}")

    for k, v in to_dict(obj).items():
        emit(k, v, 0)
    return "\n".join(lines)
