"""ktl — the kubectl analog.

Reference: ``pkg/kubectl/cmd/cmd.go:216 NewKubectlCommand`` (command
tree) and ``pkg/kubectl/resource/builder.go:934`` (manifest -> typed
objects via the scheme). Commands::

    ktl up [--nodes N] [--tpu-chips N] [--real-tpu] [--durable] ...
    ktl get <resource> [name] [-n ns] [-l sel] [-o wide|json|yaml]
    ktl describe <resource> <name> [-n ns]
    ktl apply -f file.yaml          (create-or-update, multi-doc)
    ktl delete <resource> <name> | -f file.yaml
    ktl logs <pod> [-c container] [--tail N] [-n ns]
    ktl scale <resource> <name> --replicas N
    ktl cordon/uncordon/drain <node>
    ktl top [nodes|pods|<node>]     (summary-API scrape incl. chips;
                                     'nodes'/'pods' = TPU telemetry views)
    ktl trace pod|gang <name>       (ktrace lifecycle timeline + events)
    ktl api-resources | version

Server discovery: ``--server`` > ``$KTL_SERVER`` > the file written by
``ktl up`` (``$KTL_CONFIG``, default ``~/.ktl/config``).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import re
import signal
import sys
from typing import Any, Optional

from ..api import errors, types as t
from ..api.meta import ObjectMeta
from ..api.scheme import DEFAULT_SCHEME, to_dict
from ..client.rest import RESTClient
from . import printers

DEFAULT_CONFIG = os.path.expanduser(
    os.environ.get("KTL_CONFIG", "~/.ktl/config"))

#: Short aliases (kubectl's singular/abbreviated names).
ALIASES = {
    "pod": "pods", "po": "pods",
    "node": "nodes", "no": "nodes",
    "deployment": "deployments", "deploy": "deployments",
    "replicaset": "replicasets", "rs": "replicasets",
    "statefulset": "statefulsets", "sts": "statefulsets",
    "daemonset": "daemonsets", "ds": "daemonsets",
    "job": "jobs", "cronjob": "cronjobs", "cj": "cronjobs",
    "service": "services", "svc": "services",
    "namespace": "namespaces", "ns": "namespaces",
    "configmap": "configmaps", "cm": "configmaps",
    "secret": "secrets",
    "podgroup": "podgroups", "pg": "podgroups",
    "clusterqueue": "clusterqueues", "cq": "clusterqueues",
    "localqueue": "localqueues", "lq": "localqueues",
    "inferenceservice": "inferenceservices", "isvc": "inferenceservices",
    "trainjob": "trainjobs", "tj": "trainjobs",
    "event": "events", "ev": "events",
    "quota": "resourcequotas", "resourcequota": "resourcequotas",
    "hpa": "horizontalpodautoscalers",
    "pdb": "poddisruptionbudgets",
    "endpoints": "endpoints", "ep": "endpoints",
    "lease": "leases",
    "pv": "persistentvolumes", "persistentvolume": "persistentvolumes",
    "pvc": "persistentvolumeclaims",
    "persistentvolumeclaim": "persistentvolumeclaims",
    "sc": "storageclasses", "storageclass": "storageclasses",
    "crd": "customresourcedefinitions", "crds": "customresourcedefinitions",
    "role": "roles", "clusterrole": "clusterroles",
    "rolebinding": "rolebindings", "clusterrolebinding": "clusterrolebindings",
}


def resolve_plural(name: str) -> str:
    return ALIASES.get(name, name)


def load_server(args) -> str:
    if getattr(args, "server", ""):
        return args.server
    if os.environ.get("KTL_SERVER"):
        return os.environ["KTL_SERVER"]
    try:
        with open(DEFAULT_CONFIG) as f:
            return json.load(f)["server"]
    except (OSError, KeyError, json.JSONDecodeError):
        pass
    raise SystemExit("ktl: no server — run `ktl up`, set $KTL_SERVER, "
                     "or pass --server URL")


def load_token(args) -> str:
    if os.environ.get("KTL_TOKEN"):
        return os.environ["KTL_TOKEN"]
    # Only trust the recorded token for the recorded server.
    try:
        with open(DEFAULT_CONFIG) as f:
            cfg = json.load(f)
        if cfg.get("token") and cfg.get("server") == load_server(args):
            return cfg["token"]
    except (OSError, json.JSONDecodeError, SystemExit):
        pass
    return ""


def load_tls(args) -> dict:
    """{ca, client_cert, client_key} for the recorded server (the
    admin.conf role: ktl up writes them, every command trusts them)."""
    out = {"ca_file": os.environ.get("KTL_CA", "")}
    try:
        with open(DEFAULT_CONFIG) as f:
            cfg = json.load(f)
        if cfg.get("server") == load_server(args):
            out["ca_file"] = out["ca_file"] or cfg.get("ca", "")
            out["client_cert"] = cfg.get("client_cert", "")
            out["client_key"] = cfg.get("client_key", "")
    except (OSError, json.JSONDecodeError, SystemExit):
        pass
    return out


def make_client(args) -> RESTClient:
    groups = tuple(getattr(args, "as_group", None) or ()) + tuple(
        getattr(args, "as_group_sub", None) or ())
    return RESTClient(load_server(args), token=load_token(args),
                      impersonate_user=getattr(args, "as_user", "") or "",
                      impersonate_groups=groups,
                      **load_tls(args))


# -- manifest loading (resource/builder.go analog) -------------------------

def load_manifests(path: str) -> list[Any]:
    import yaml
    if path == "-":
        raw = sys.stdin.read()
    else:
        with open(path) as f:
            raw = f.read()
    objs = []
    for doc in yaml.safe_load_all(raw):
        if not doc:
            continue
        if "kind" not in doc:
            raise SystemExit(f"ktl: manifest document missing 'kind': {doc}")
        if not doc.get("api_version") and not doc.get("apiVersion"):
            # Friendly default: infer the group from the kind.
            from ..client.rest import _BY_KIND, _BY_PLURAL
            plural = _BY_KIND.get(doc["kind"])
            if plural:
                doc["api_version"] = _BY_PLURAL[plural][0]
        from ..client.rest import decode_obj
        objs.append(decode_obj(doc))
    return objs


# -- commands --------------------------------------------------------------

async def cmd_get(args) -> int:
    if getattr(args, "watch", False) and (
            args.output.startswith("jsonpath=")
            or args.output.startswith("custom-columns=")):
        # Rejected before ANY fetch: valid-looking output followed by
        # a late failure is worse for scripts than an up-front error.
        print("Error: -w with jsonpath/custom-columns output is not "
              "supported (the stream would mix formats)", file=sys.stderr)
        return 1
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        if args.name:
            objs = [await client.get(plural, args.namespace, args.name)]
        else:
            objs, rev = await client.list(plural, args.namespace,
                                        label_selector=args.selector)
        if getattr(args, "sort_by", ""):
            from .jsonpath import sort_key
            vals = [sort_key(args.sort_by, to_dict(o)) for o in objs]
            # Homogeneous numbers sort numerically (kubectl); anything
            # mixed falls back to strings. None always sorts first.
            numeric = all(isinstance(v, (int, float))
                          and not isinstance(v, bool)
                          for v in vals if v is not None)
            def _key(pair):
                v = pair[0]
                if v is None:
                    return (0, 0.0, "")
                return (1, float(v), "") if numeric else (1, 0.0, str(v))
            objs = [o for _v, o in sorted(zip(vals, objs), key=_key)]
        if args.output.startswith("jsonpath="):
            from .jsonpath import render_template
            template = args.output[len("jsonpath="):]
            data = (to_dict(objs[0]) if args.name
                    else {"items": [to_dict(o) for o in objs]})
            sys.stdout.write(render_template(template, data))
            sys.stdout.flush()
        elif args.output.startswith("custom-columns="):
            from .jsonpath import _fmt, find
            cols = []
            for part in args.output[len("custom-columns="):].split(","):
                header, _, expr = part.partition(":")
                if not header or not expr:
                    raise errors.BadRequestError(
                        f"custom-columns: want HEADER:jsonpath, got "
                        f"{part!r}")
                cols.append((header, expr))
            rows = []
            for o in objs:
                d = to_dict(o)
                row = []
                for _h, expr in cols:
                    got = find(expr, d, source="custom-columns")
                    row.append(_fmt(got[0]) if got else "<none>")
                rows.append(row)
            print(printers.render_table([h for h, _ in cols], rows))
        elif args.output == "json":
            out = [to_dict(o) for o in objs]
            print(json.dumps(out[0] if args.name else out, indent=2,
                             default=str))
        elif args.output == "yaml":
            import yaml
            out = [to_dict(o) for o in objs]
            print(yaml.safe_dump(out[0] if args.name else out,
                                 sort_keys=False))
        elif args.output in ("", "wide"):
            print(printers.print_objects(plural, objs,
                                         wide=args.output == "wide"))
        else:
            # -o lost its argparse choices= when jsonpath=/custom-
            # columns= arrived; unknown formats must still be loud.
            raise errors.BadRequestError(
                f"unknown output format {args.output!r} (want wide, "
                f"json, yaml, jsonpath=..., custom-columns=...)")
        if getattr(args, "watch", False) and not args.name:
            # kubectl get -w: stream changes after the initial table,
            # one re-printed row per event, until interrupted.
            stream = await client.watch(plural, args.namespace, rev,
                                        label_selector=args.selector)
            try:
                while True:
                    ev = await stream.next()
                    if ev is None or ev[0] == "CLOSED":
                        break
                    ev_type, obj = ev
                    if ev_type == "BOOKMARK":
                        continue
                    row = printers.print_objects(plural, [obj],
                                                 wide=args.output == "wide")
                    body = row.splitlines()[1:] or [""]  # drop the header
                    marker = "- " if ev_type == "DELETED" else "  "
                    print(marker + "\n".join(body))
                    sys.stdout.flush()
            except (KeyboardInterrupt, asyncio.CancelledError):
                pass
            finally:
                stream.cancel()
        return 0
    finally:
        await client.close()


async def cmd_describe(args) -> int:
    client = make_client(args)
    try:
        obj = await client.get(resolve_plural(args.resource),
                               args.namespace, args.name)
        print(printers.describe(obj))
        return 0
    finally:
        await client.close()


def _stamp_age(ts) -> str:
    if ts is None:
        return "-"
    from ..api.meta import now as _meta_now
    return printers.age_seconds((_meta_now() - ts).total_seconds())


async def cmd_migrations(args) -> int:
    """``ktl migrations`` — live gang-migration rounds and recent
    outcomes (``status.migration`` across PodGroups): the operator's
    one-stop answer to "is the fleet moving gangs right now, and why"."""
    client = make_client(args)
    try:
        groups, _ = await client.list("podgroups", args.namespace)
        rows = []
        for g in sorted(groups, key=lambda g: (g.metadata.namespace,
                                               g.metadata.name)):
            mig = g.status.migration
            if mig is None or (not mig.phase and not mig.outcome):
                continue
            phase = mig.phase or "Idle"
            target = (f"{mig.target_slice}"
                      f"[{len(mig.target_cells)} chips]"
                      if mig.target_slice else "-")
            rows.append([
                g.metadata.namespace, g.metadata.name, phase,
                mig.reason or "-", target,
                mig.outcome or "-", str(mig.rounds),
                _stamp_age(mig.finished_time or mig.started_time)])
        if not rows:
            print("No migration activity found.")
            return 0
        print(printers.render_table(
            ["NAMESPACE", "GANG", "PHASE", "REASON", "TARGET",
             "LAST-OUTCOME", "ROUNDS", "AGE"], rows))
        return 0
    finally:
        await client.close()


#: Marks objects as ktl-applied; prune only ever deletes objects
#: carrying it (reference: kubectl.kubernetes.io/last-applied-
#: configuration gating apply --prune).
LAST_APPLIED = "ktl.tpu/last-applied"

#: Types apply --prune sweeps even when the file set no longer
#: contains any object of that type (kubectl's default prune
#: whitelist — without it, deleting the last Service from the
#: directory would never prune the live one).
PRUNE_TYPES = ["configmaps", "secrets", "services", "deployments",
               "replicasets", "statefulsets", "daemonsets", "jobs",
               "cronjobs", "pods", "persistentvolumeclaims", "podgroups"]


async def cmd_apply(args) -> int:
    client = make_client(args)
    prune = getattr(args, "prune", False)
    selector = getattr(args, "selector", "")
    if prune and not selector:
        print("Error: --prune requires -l/--selector (it bounds the "
              "sweep; pruning everything ever applied is never what "
              "you want)", file=sys.stderr)
        return 1
    applied: set[tuple[str, str, str]] = set()  # (plural, ns, name)
    try:
        for obj in load_manifests(args.filename):
            if not obj.metadata.namespace and _namespaced(obj):
                obj.metadata.namespace = args.namespace
            kind = obj.kind or type(obj).__name__
            if obj.metadata.annotations is None:  # explicit JSON null
                obj.metadata.annotations = {}
            # The stamp records the APPLIED manifest (pre-defaulting),
            # compact JSON like the reference annotation.
            obj.metadata.annotations[LAST_APPLIED] = json.dumps(
                to_dict(obj), separators=(",", ":"), default=str)
            plural = _plural_of(obj)
            ns = obj.metadata.namespace if _namespaced(obj) else ""
            applied.add((plural, ns, obj.metadata.name))
            try:
                created = await client.create(obj)
                print(f"{kind.lower()}/{created.metadata.name} created")
            except errors.AlreadyExistsError:
                cur = await client.get(plural, obj.metadata.namespace,
                                       obj.metadata.name)
                obj.metadata.resource_version = cur.metadata.resource_version
                obj.metadata.uid = cur.metadata.uid
                updated = await client.update(obj)
                print(f"{kind.lower()}/{updated.metadata.name} configured")
        if prune:
            sweep = set(PRUNE_TYPES) | {p for p, _ns, _n in applied}
            for plural in sorted(sweep):
                from ..client.rest import _BY_PLURAL
                if plural not in _BY_PLURAL:
                    continue
                namespaced = _BY_PLURAL[plural][1]
                ns = args.namespace if namespaced else ""
                objs, _rev = await client.list(plural, ns,
                                               label_selector=selector)
                for live in objs:
                    if LAST_APPLIED not in (live.metadata.annotations or {}):
                        continue  # never applied by ktl: not ours to prune
                    key = (plural, ns if namespaced else "",
                           live.metadata.name)
                    if key in applied:
                        continue
                    await client.delete(plural, key[1], live.metadata.name)
                    print(f"{live.kind.lower()}/{live.metadata.name} pruned")
        return 0
    finally:
        await client.close()


async def cmd_edit(args) -> int:
    """kubectl edit: fetch -> $EDITOR -> CAS update. The buffer carries
    the live resource_version, so a concurrent writer surfaces as a
    conflict instead of a silent overwrite (reference:
    pkg/kubectl/cmd/edit.go)."""
    import subprocess
    import tempfile

    import yaml
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        ns = args.namespace
        cur = await client.get(plural, ns, args.name)
        cur_dict = to_dict(cur)
        # Decoded objects may carry empty TypeMeta (the wire stamps it,
        # the dataclass default is "") — without kind in the buffer the
        # re-decode would fall back to CustomResource.
        if not cur_dict.get("kind") or not cur_dict.get("api_version"):
            av, kind = DEFAULT_SCHEME.gvk_for(cur)
            cur_dict.setdefault("kind", kind)
            cur_dict.setdefault("api_version", av)
            cur_dict = {"kind": cur_dict.pop("kind"),
                        "api_version": cur_dict.pop("api_version"),
                        **cur_dict}
        doc = yaml.safe_dump(cur_dict, sort_keys=False)
        editor = (os.environ.get("KTL_EDITOR")
                  or os.environ.get("EDITOR") or "vi")
        with tempfile.NamedTemporaryFile(
                "w+", suffix=".yaml", prefix=f"ktl-edit-{args.name}-",
                delete=False) as f:
            f.write(f"# Editing {plural}/{args.name}. Lines starting "
                    f"with '#' are ignored; an empty file aborts.\n")
            f.write(doc)
            path = f.name
        try:
            import shlex
            # editor stays unquoted (EDITOR may carry flags); the path
            # must be quoted or a TMPDIR with spaces word-splits it.
            rc = await asyncio.to_thread(
                subprocess.call, f"{editor} {shlex.quote(path)}",
                shell=True)
            if rc != 0:
                print(f"Error: editor exited {rc}; edit aborted "
                      f"(buffer kept at {path})", file=sys.stderr)
                return 1
            with open(path) as f:
                text = "\n".join(ln for ln in f.read().splitlines()
                                 if not ln.lstrip().startswith("#"))
            if not text.strip():
                print("Edit cancelled (empty file).")
                return 0
            raw = yaml.safe_load(text)
            if not isinstance(raw, dict):
                print(f"Error: buffer must be a YAML mapping, got "
                      f"{type(raw).__name__} (kept at {path})",
                      file=sys.stderr)
                return 1
            if cur_dict == raw:
                print("Edit cancelled, no changes made.")
                return 0
            from ..client.rest import decode_obj
            if (raw.get("kind") != cur_dict["kind"]
                    or raw.get("api_version") != cur_dict["api_version"]):
                # Editing identity is not editing the object; an
                # unregistered kind would otherwise decode into the
                # CustomResource fallback and fail later with a
                # confusing scheme error.
                print(f"Error: kind/api_version may not be changed by "
                      f"edit (buffer kept at {path})", file=sys.stderr)
                return 1
            edited = decode_obj(raw)
            # Keep the fetched CAS token even if the user deleted the
            # metadata block; a user-edited one is respected (it's how
            # you deliberately force a conflict check against older).
            if not edited.metadata.resource_version:
                edited.metadata.resource_version = \
                    cur.metadata.resource_version
            try:
                await client.update(edited)
            except errors.ConflictError:
                print(f"Error: {plural}/{args.name} changed while you "
                      f"were editing; re-run ktl edit (your buffer is "
                      f"kept at {path})", file=sys.stderr)
                return 1
            except errors.StatusError as e:
                print(f"Error: {e} (your buffer is kept at {path})",
                      file=sys.stderr)
                return 1
            print(f"{edited.kind.lower()}/{args.name} edited")
            os.unlink(path)
            return 0
        except yaml.YAMLError as e:
            print(f"Error: buffer is not valid YAML: {e} (kept at "
                  f"{path})", file=sys.stderr)
            return 1
    finally:
        await client.close()


def _plural_of(obj) -> str:
    from ..client.rest import _BY_KIND
    return _BY_KIND[DEFAULT_SCHEME.gvk_for(obj)[1]]


def _namespaced(obj) -> bool:
    from ..client.rest import _BY_PLURAL
    return _BY_PLURAL[_plural_of(obj)][1]


#: --cascade spelling -> DeleteOptions propagationPolicy.
_CASCADE = {"background": "Background", "foreground": "Foreground",
            "orphan": "Orphan"}


async def cmd_delete(args) -> int:
    client = make_client(args)
    policy = _CASCADE.get(getattr(args, "cascade", "background"), "")
    try:
        if args.filename:
            for obj in load_manifests(args.filename):
                ns = obj.metadata.namespace or args.namespace
                plural = _plural_of(obj)
                try:
                    await client.delete(plural, ns if _namespaced(obj) else "",
                                        obj.metadata.name,
                                        propagation_policy=policy)
                    print(f"{obj.kind.lower()}/{obj.metadata.name} deleted")
                except errors.NotFoundError:
                    print(f"{obj.kind.lower()}/{obj.metadata.name} not found")
            return 0
        plural = resolve_plural(args.resource)
        await client.delete(plural, args.namespace, args.name,
                            propagation_policy=policy)
        print(f"{plural}/{args.name} deleted")
        return 0
    finally:
        await client.close()


# Node agent resolution is shared with every other node-server
# consumer (HPA scraping etc.) — client/nodeaccess.py is the one
# implementation of the DaemonEndpoints protocol.
from ..client.nodeaccess import resolve_node_agent as _node_daemon_base  # noqa: E402
from ..client.nodeaccess import ssl_kw as _ssl_kw  # noqa: E402


async def cmd_logs(args) -> int:
    client = make_client(args)
    try:
        base, node_ssl = await _resolve_exec(client, args.namespace,
                                             args.pod)
        container = args.container or "-"
        import aiohttp
        params = {"tail": str(args.tail)} if args.tail else {}
        follow = getattr(args, "follow", False)
        if follow:
            params["follow"] = "1"
        if getattr(args, "previous", False):
            if follow:
                print("Error: --previous cannot follow (the instance "
                      "already exited)", file=sys.stderr)
                return 1
            params["previous"] = "1"
        # Unbounded timeout ONLY for follow (the stream lives as long
        # as the container); plain fetches keep aiohttp's default so a
        # wedged agent errors instead of hanging the CLI.
        timeout = aiohttp.ClientTimeout(total=None) if follow else None
        async with aiohttp.ClientSession() as s:
            url = f"{base}/logs/{args.namespace}/{args.pod}/{container}"
            async with s.get(url, params=params, timeout=timeout,
                             **_ssl_kw(node_ssl)) as r:
                if r.status != 200:
                    raise SystemExit(f"ktl: {(await r.text()).strip()}")
                out_buf = getattr(sys.stdout, "buffer", None)
                # Incremental decoder for text-only stdout (tests,
                # redirects): chunk boundaries may split multi-byte
                # characters, so never decode chunks independently.
                import codecs
                dec = codecs.getincrementaldecoder("utf-8")("replace")
                async for chunk in r.content.iter_any():
                    if out_buf is not None:
                        out_buf.write(chunk)  # raw bytes to the terminal
                        out_buf.flush()
                    else:
                        sys.stdout.write(dec.decode(chunk))
                        sys.stdout.flush()
        return 0
    finally:
        await client.close()


async def exec_interactive(base: str, namespace: str, pod: str,
                           container: str, argv: list[str],
                           stdin_source=None, out=None,
                           timeout: float = 3600.0, ssl_ctx=None) -> int:
    """Drive the node server's WebSocket exec stream: binary frames are
    stdio; the closing text frame carries the exit code. Reusable by
    tests (stdin_source: async iterator of bytes; None = process stdin)."""
    import aiohttp
    out = out or (lambda b: (sys.stdout.write(
        b.decode(errors="replace")), sys.stdout.flush()))
    from urllib.parse import quote
    url = (f"{base}/exec/{namespace}/{pod}/{container}/stream"
           f"?timeout={timeout}"
           + "".join(f"&command={quote(a)}" for a in argv))
    exit_code = 1
    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=timeout + 30)) as s:
        async with s.ws_connect(url, **_ssl_kw(ssl_ctx)) as ws:
            async def feed():
                try:
                    if stdin_source is None:
                        # A DAEMON thread reads local stdin: blocked
                        # readline threads from run_in_executor are
                        # joined at interpreter exit and would hang
                        # ktl after the remote command finishes.
                        import queue as queuelib
                        import threading
                        q: asyncio.Queue = asyncio.Queue()
                        loop = asyncio.get_running_loop()

                        def pump():
                            for line in sys.stdin:
                                loop.call_soon_threadsafe(
                                    q.put_nowait, line.encode())
                            loop.call_soon_threadsafe(q.put_nowait, None)
                        threading.Thread(target=pump, daemon=True).start()
                        while True:
                            chunk = await q.get()
                            if chunk is None:
                                break
                            await ws.send_bytes(chunk)
                    else:
                        async for chunk in stdin_source:
                            await ws.send_bytes(chunk)
                    await ws.send_str("EOF")
                except (ConnectionResetError, asyncio.CancelledError):
                    pass
            feeder = asyncio.get_running_loop().create_task(feed())
            try:
                async for msg in ws:
                    if msg.type == aiohttp.WSMsgType.BINARY:
                        out(msg.data)
                    elif msg.type == aiohttp.WSMsgType.TEXT:
                        body = json.loads(msg.data)
                        if "exit_code" in body:
                            exit_code = int(body["exit_code"])
                        if body.get("error"):
                            print(f"ktl: {body['error']}", file=sys.stderr)
                        break
            finally:
                feeder.cancel()
    return exit_code


async def _resolve_exec(client, namespace: str, pod_name: str):
    """-> (node base URL, ssl ctx) for a scheduled pod's agent server.
    The one copy of the exec endpoint resolution (cp's chunk loop and
    exec both ride it)."""
    pod = await client.get("pods", namespace, pod_name)
    if not pod.spec.node_name:
        raise SystemExit(f"ktl: pod {pod_name} is not scheduled yet")
    conn = await _node_daemon_base(client, pod.spec.node_name)
    if conn is None:
        raise SystemExit(f"ktl: node {pod.spec.node_name} has no "
                         "reachable agent server")
    return conn


async def _exec_on(session, base: str, node_ssl, namespace: str,
                   pod_name: str, container: str, cmd: list[str],
                   timeout: float = 60.0) -> tuple[int, str]:
    url = f"{base}/exec/{namespace}/{pod_name}/{container or '-'}"
    async with session.post(url, json={"command": cmd,
                                       "timeout": timeout},
                            **_ssl_kw(node_ssl)) as r:
        if r.status != 200:
            raise SystemExit(f"ktl: {(await r.text()).strip()}")
        body = await r.json()
    return int(body["exit_code"]), body["output"]


async def cmd_attach(args) -> int:
    """``ktl attach POD`` — stream a running container's output
    (kubectl attach analog over the node server's WebSocket attach
    stream; Ctrl-C detaches, the container keeps running)."""
    import aiohttp
    client = make_client(args)
    try:
        base, node_ssl = await _resolve_exec(client, args.namespace,
                                             args.pod)
        container = args.container or "-"
        url = f"{base}/attach/{args.namespace}/{args.pod}/{container}/stream"
        out_buf = getattr(sys.stdout, "buffer", None)
        import codecs
        # Incremental decoder for text-only stdout: frame boundaries
        # may split multi-byte characters (same fix as cmd_logs).
        dec = codecs.getincrementaldecoder("utf-8")("replace")
        try:
            async with aiohttp.ClientSession() as s:
                async with s.ws_connect(url, **_ssl_kw(node_ssl)) as ws:
                    print(f"attached to {args.pod} (Ctrl-C detaches)",
                          file=sys.stderr)
                    async for msg in ws:
                        if msg.type == aiohttp.WSMsgType.BINARY:
                            if out_buf is not None:
                                out_buf.write(msg.data)
                                out_buf.flush()
                            else:
                                sys.stdout.write(dec.decode(msg.data))
                                sys.stdout.flush()
                        elif msg.type in (aiohttp.WSMsgType.CLOSE,
                                          aiohttp.WSMsgType.ERROR):
                            break
        except aiohttp.WSServerHandshakeError as e:
            # The server's rejection text (e.g. "pick one" with the
            # container list) is the actionable part — not a traceback.
            print(f"ktl: attach refused ({e.status}): "
                  f"{e.message or e.headers}", file=sys.stderr)
            return 1
        except (KeyboardInterrupt, asyncio.CancelledError):
            return 0  # detach, never kill
        return 0
    finally:
        await client.close()


async def cmd_cp(args) -> int:
    """``ktl cp pod:path local`` / ``ktl cp local pod:path`` — file and
    directory copy over the exec seam (reference: kubectl cp, which
    tunnels tar through exec streams; the one-shot exec here is text,
    so payloads ride base64 — chunked on upload to stay under argv
    limits)."""
    def parse(side: str):
        pod, sep, path = side.partition(":")
        return (pod, path) if sep else (None, side)

    src_pod, src_path = parse(args.src)
    dst_pod, dst_path = parse(args.dst)
    if (src_pod is None) == (dst_pod is None):
        print("Error: exactly one of src/dst must be pod:path",
              file=sys.stderr)
        return 1
    client = make_client(args)
    c = args.container
    pod_name = src_pod or dst_pod
    try:
        import aiohttp
        base, node_ssl = await _resolve_exec(client, args.namespace,
                                             pod_name)
        timeout = aiohttp.ClientTimeout(total=300)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            async def run(cmd, timeout=240.0):
                # Long transfer steps (multi-GB base64 passes) must fit
                # inside the session's 300s budget, not the 60s default.
                return await _exec_on(s, base, node_ssl, args.namespace,
                                      pod_name, c, cmd, timeout=timeout)
            if src_pod is not None:
                return await _cp_download(run, src_pod, src_path,
                                          dst_path)
            return await _cp_upload(run, args.src, dst_pod, dst_path)
    finally:
        await client.close()


async def _cp_download(run, src_pod: str, src_path: str,
                       dst_path: str) -> int:
    import base64
    import shlex
    q = shlex.quote(src_path)
    # Explicit dir probe — sniffing tar magic in the payload would
    # misread a copied .tar FILE as a directory and explode it.
    rc, _out = await run(["sh", "-c", f"test -d {q}"])
    is_dir = rc == 0
    if is_dir:
        # tar's status must fail the copy (a pipeline returns base64's
        # exit code) and its stderr must stay OUT of the payload (the
        # runtime merges stderr into stdout, which would corrupt the
        # base64 stream): stage the archive, then encode it.
        cmd = (f"t=$(mktemp) && tar -C \"$(dirname {q})\" -cf \"$t\" "
               f"\"$(basename {q})\" 2>&1 >/dev/null && "
               f"base64 < \"$t\"; rc=$?; rm -f \"$t\"; exit $rc")
    else:
        cmd = f"base64 < {q}"
    rc, out = await run(["sh", "-c", cmd])
    if rc != 0:
        print(f"Error: reading {src_pod}:{src_path} failed "
              f"({out.strip()})", file=sys.stderr)
        return 1
    data = base64.b64decode(out)
    if is_dir:
        import io
        import tarfile
        os.makedirs(dst_path, exist_ok=True)
        with tarfile.open(fileobj=io.BytesIO(data)) as tf:
            tf.extractall(dst_path, filter="data")
        print(f"copied {src_pod}:{src_path} -> {dst_path}/")
    else:
        if os.path.isdir(dst_path):
            dst_path = os.path.join(dst_path, os.path.basename(src_path))
        with open(dst_path, "wb") as f:
            f.write(data)
        print(f"copied {src_pod}:{src_path} -> {dst_path}")
    return 0


async def _cp_upload(run, local_src: str, dst_pod: str,
                     dst_path: str) -> int:
    import base64
    import shlex
    if os.path.isdir(local_src):
        print("Error: directory upload not supported (tar locally and "
              "copy the archive)", file=sys.stderr)
        return 1
    with open(local_src, "rb") as f:
        payload = base64.b64encode(f.read()).decode()
    qd = shlex.quote(dst_path)
    qtmp = shlex.quote(dst_path + ".b64")

    async def fail(msg, out):
        print(f"Error: {msg} ({out.strip()})", file=sys.stderr)
        # Best-effort: don't strand a partial temp file in the pod.
        await run(["sh", "-c", f"rm -f {qtmp}"])
        return 1

    rc, out = await run(["sh", "-c", f": > {qtmp}"])
    if rc != 0:
        print(f"Error: cannot write in {dst_pod} ({out.strip()})",
              file=sys.stderr)
        return 1
    CHUNK = 48 * 1024
    for i in range(0, len(payload) or 1, CHUNK):
        chunk = payload[i:i + CHUNK]  # base64 alphabet: shell-inert
        rc, out = await run(["sh", "-c",
                             f"printf %s {chunk} >> {qtmp}"])
        if rc != 0:
            return await fail("upload chunk failed", out)
    rc, out = await run(["sh", "-c",
                         f"base64 -d < {qtmp} > {qd} && rm {qtmp}"])
    if rc != 0:
        return await fail("decode failed", out)
    print(f"copied {local_src} -> {dst_pod}:{dst_path}")
    return 0


async def cmd_exec(args) -> int:
    """Run a command in a running container (kubectl exec analog);
    ``-i`` switches to the interactive WebSocket stream."""
    client = make_client(args)
    try:
        base, node_ssl = await _resolve_exec(client, args.namespace,
                                             args.pod)
        container = args.container or "-"
        if getattr(args, "stdin", False):
            # Interactive sessions outlive the one-shot default; an
            # EXPLICIT --timeout always wins (None = flag omitted).
            timeout = args.timeout if args.timeout is not None else 3600.0
            return await exec_interactive(
                base, args.namespace, args.pod, container, args.cmd,
                timeout=timeout, ssl_ctx=node_ssl)
        import aiohttp
        # The HTTP call must outlive the exec's own timeout (aiohttp's
        # default 300s total would abort long execs client-side).
        one_shot_timeout = (args.timeout if args.timeout is not None
                            else 30.0)
        client_timeout = aiohttp.ClientTimeout(total=one_shot_timeout + 30)
        async with aiohttp.ClientSession(timeout=client_timeout) as s:
            code, output = await _exec_on(
                s, base, node_ssl, args.namespace, args.pod, container,
                args.cmd, timeout=one_shot_timeout)
        sys.stdout.write(output)
        return code
    finally:
        await client.close()


async def forward_port(base: str, namespace: str, pod: str,
                       local_port: int, remote_port: int,
                       ready: Optional[asyncio.Event] = None,
                       stop: Optional[asyncio.Event] = None,
                       on_bound=None, ssl_ctx=None) -> int:
    """Listen on 127.0.0.1:local_port; tunnel each connection through
    the node server's port-forward WebSocket to the pod's remote_port.
    Runs until ``stop`` (or forever). Returns the bound local port."""
    import aiohttp

    async def handle(reader, writer):
        url = f"{base}/portforward/{namespace}/{pod}/{remote_port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.ws_connect(url, **_ssl_kw(ssl_ctx)) as ws:
                    async def ws_to_tcp():
                        try:
                            async for msg in ws:
                                if msg.type == aiohttp.WSMsgType.BINARY:
                                    writer.write(msg.data)
                                    await writer.drain()
                        except (ConnectionResetError,
                                asyncio.CancelledError):
                            pass
                        finally:
                            writer.close()
                    pump = asyncio.get_running_loop().create_task(ws_to_tcp())
                    try:
                        while True:
                            data = await reader.read(65536)
                            if not data:
                                break
                            await ws.send_bytes(data)
                    finally:
                        pump.cancel()
                        await ws.close()
        except aiohttp.ClientError as e:
            print(f"ktl: port-forward stream failed: {e}", file=sys.stderr)
            writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", local_port)
    bound = server.sockets[0].getsockname()[1]
    if on_bound is not None:
        on_bound(bound)
    if ready is not None:
        ready.set()
    try:
        if stop is None:
            await asyncio.Event().wait()  # forever (SIGINT exits)
        else:
            await stop.wait()
    finally:
        server.close()
        await server.wait_closed()
    return bound


async def cmd_port_forward(args) -> int:
    """kubectl port-forward analog: LOCAL:REMOTE over the node server's
    WebSocket tunnel."""
    client = make_client(args)
    try:
        pod = await client.get("pods", args.namespace, args.pod)
        if not pod.spec.node_name:
            raise SystemExit(f"ktl: pod {args.pod} is not scheduled yet")
        conn = await _node_daemon_base(client, pod.spec.node_name)
        if conn is None:
            raise SystemExit(f"ktl: node {pod.spec.node_name} has no "
                             "reachable agent server")
        base, node_ssl = conn
    finally:
        await client.close()
    local_s, _, remote_s = args.ports.partition(":")
    local = int(local_s)
    remote = int(remote_s) if remote_s else local
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            signal.signal(sig, lambda *_: stop.set())
    await forward_port(
        base, args.namespace, args.pod, local, remote, stop=stop,
        ssl_ctx=node_ssl,
        on_bound=lambda p: print(f"forwarding 127.0.0.1:{p} -> "
                                 f"{args.pod}:{remote} (Ctrl-C to stop)",
                                 flush=True))
    return 0


async def cmd_scale(args) -> int:
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        await client.patch(plural, args.namespace, args.name,
                           {"spec": {"replicas": args.replicas}})
        print(f"{plural}/{args.name} scaled to {args.replicas}")
        return 0
    finally:
        await client.close()


async def _set_unschedulable(args, value: bool, verb: str) -> int:
    client = make_client(args)
    try:
        await client.patch("nodes", "", args.node,
                           {"spec": {"unschedulable": value}})
        print(f"node/{args.node} {verb}")
        return 0
    finally:
        await client.close()


async def cmd_patch(args) -> int:
    """``ktl patch`` (reference: ``pkg/kubectl/cmd/patch.go``) — the
    three patch flavors over the existing merge engines: strategic
    (api/patch.py:77), RFC 7386 merge, RFC 6902 json."""
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        try:
            body = json.loads(args.patch)
        except json.JSONDecodeError as e:
            print(f"error: -p is not valid JSON: {e}", file=sys.stderr)
            return 1
        if args.type == "json" and not isinstance(body, list):
            print("error: --type json expects an array of RFC 6902 ops",
                  file=sys.stderr)
            return 1
        if args.type != "json" and not isinstance(body, dict):
            print(f"error: --type {args.type} expects a JSON object",
                  file=sys.stderr)
            return 1
        await client.patch(plural, args.namespace, args.name, body,
                           strategic=(args.type == "strategic"))
        print(f"{plural}/{args.name} patched")
        return 0
    finally:
        await client.close()


def _parse_kv_edits(pairs: list[str], what: str) -> dict:
    """kubectl's edit syntax: ``k=v`` sets, ``k-`` removes. Returns
    key -> value-or-None (None = remove; a merge patch treats null as
    delete, RFC 7386)."""
    out: dict = {}
    for p in pairs:
        if p.endswith("-") and "=" not in p:
            out[p[:-1]] = None
        elif "=" in p:
            k, _, v = p.partition("=")
            out[k] = v
        else:
            raise ValueError(
                f"invalid {what} {p!r}: use key=value to set, key- to remove")
    return out


async def _metadata_edit(args, field: str) -> int:
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        try:
            edits = _parse_kv_edits(args.pairs, field[:-1])
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if not args.overwrite:
            cur = await client.get(plural, args.namespace, args.name)
            existing = getattr(cur.metadata, field)
            clash = [k for k, v in edits.items()
                     if v is not None and k in existing
                     and existing[k] != v]
            if clash:
                print(f"error: {field} {clash} already set; use "
                      f"--overwrite to replace", file=sys.stderr)
                return 1
        await client.patch(plural, args.namespace, args.name,
                           {"metadata": {field: edits}})
        verbed = "labeled" if field == "labels" else "annotated"
        print(f"{plural}/{args.name} {verbed}")
        return 0
    finally:
        await client.close()


async def cmd_label(args) -> int:
    return await _metadata_edit(args, "labels")


async def cmd_annotate(args) -> int:
    return await _metadata_edit(args, "annotations")


async def cmd_auth_can_i(args) -> int:
    """``ktl auth can-i VERB RESOURCE [NAME]`` (reference:
    ``pkg/kubectl/cmd/auth/cani.go``) — SelfSubjectAccessReview, so
    ``--as``/``--as-group`` answer for the impersonated identity.
    Exit 0 = yes, 1 = no (scriptable, like kubectl)."""
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        allowed, reason = await client.access_review(
            args.verb, plural, namespace=args.namespace,
            name=args.name)
        print("yes" if allowed else "no")
        if not allowed and reason and not args.quiet:
            print(reason, file=sys.stderr)
        return 0 if allowed else 1
    finally:
        await client.close()


def _condition_met(obj, want_type: str, want_status: str) -> bool:
    conds = getattr(getattr(obj, "status", None), "conditions", None) or []
    return any(c.type == want_type and c.status == want_status
               for c in conds)


async def cmd_wait(args) -> int:
    """``ktl wait RESOURCE NAME --for condition=Type[=Status] | delete``
    (reference: ``pkg/kubectl/cmd/wait``). Watch-driven: takes the
    list's resourceVersion, then blocks on the watch stream instead of
    polling."""
    import time
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        target = args.wait_for
        if target == "delete":
            want_type = want_status = ""
        elif target.startswith("condition="):
            rest = target[len("condition="):]
            want_type, _, want_status = rest.partition("=")
            want_status = want_status or "True"
        else:
            print("error: --for must be condition=Type[=Status] or "
                  "delete", file=sys.stderr)
            return 1
        deadline = time.monotonic() + args.timeout

        def satisfied(obj) -> bool:
            return _condition_met(obj, want_type, want_status)

        async def check_current() -> tuple[Optional[int], int]:
            """(exit code or None, list RV) from a fresh list — the
            startup check and every CLOSED-reconnect use the same
            logic, and the RV pins the watch so no transition can slip
            between the list and the stream."""
            items, rev, _ = await client.list_page(plural, args.namespace)
            current = {o.metadata.name: o for o in items}
            if target == "delete" and args.name not in current:
                print(f"{plural}/{args.name} deleted")
                return 0, rev
            if target != "delete" and args.name in current \
                    and satisfied(current[args.name]):
                print(f"{plural}/{args.name} condition met")
                return 0, rev
            return None, rev

        # Initial state first — the condition may already hold (or the
        # object may already be gone).
        done, rev = await check_current()
        if done is not None:
            return done
        w = await client.watch(plural, args.namespace, resource_version=rev)
        try:
            while True:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    print(f"error: timed out waiting for {target} on "
                          f"{plural}/{args.name}", file=sys.stderr)
                    return 1
                ev = await w.next(timeout=min(remain, 5.0))
                if ev is None:
                    continue
                etype, obj = ev
                if etype == "CLOSED":
                    # Stream ended (apiserver restart / compaction):
                    # reconnect from a fresh list rather than failing.
                    done, rev = await check_current()
                    if done is not None:
                        return done
                    w.cancel()
                    w = await client.watch(plural, args.namespace,
                                           resource_version=rev)
                    continue
                if etype not in ("ADDED", "MODIFIED", "DELETED"):
                    continue
                if obj.metadata.name != args.name:
                    continue
                if target == "delete":
                    if etype == "DELETED":
                        print(f"{plural}/{args.name} deleted")
                        return 0
                elif etype == "DELETED":
                    # kubectl wait errors out immediately here — the
                    # condition can never come true on a gone object.
                    print(f"error: {plural}/{args.name} was deleted "
                          f"while waiting for {target}", file=sys.stderr)
                    return 1
                elif satisfied(obj):
                    print(f"{plural}/{args.name} condition met")
                    return 0
        finally:
            w.cancel()
    finally:
        await client.close()


async def cmd_taint(args) -> int:
    """``ktl taint nodes NAME key=value:Effect`` / ``key:Effect-`` to
    remove (kubectl taint analog; reference pkg/kubectl/cmd/taint.go).
    Conflict-retried read-modify-write like the other node mutations."""
    client = make_client(args)
    try:
        spec = args.taint
        remove = spec.endswith("-")
        if remove:
            spec = spec[:-1]
        if ":" in spec:
            kv, _, effect = spec.rpartition(":")
        else:
            kv, effect = spec, ""  # key- removal form
        if not kv or (not effect and not remove):
            print("Error: want key=value:Effect (or key:Effect- / "
                  "key- to remove)", file=sys.stderr)
            return 1
        key, _, value = kv.partition("=")
        if not remove and effect not in (
                t.TAINT_NO_SCHEDULE, t.TAINT_PREFER_NO_SCHEDULE,
                t.TAINT_NO_EXECUTE):
            print(f"Error: effect must be one of NoSchedule, "
                  f"PreferNoSchedule, NoExecute; got {effect!r}",
                  file=sys.stderr)
            return 1
        for attempt in range(20):
            node = await client.get("nodes", "", args.node)
            taints = list(node.spec.taints)
            if remove:
                kept = [x for x in taints
                        if not (x.key == key
                                and (not effect or x.effect == effect))]
                if len(kept) == len(taints):
                    print(f"Error: node {args.node!r} has no taint "
                          f"{key!r}", file=sys.stderr)
                    return 1
                node.spec.taints = kept
                verb = "untainted"
            else:
                replaced = False
                for x in taints:
                    if x.key == key and x.effect == effect:
                        if x.value == value and not args.overwrite:
                            print(f"node/{args.node} already has taint "
                                  f"{spec}")
                            return 0
                        if not args.overwrite:
                            print(f"Error: taint {key}:{effect} exists "
                                  f"with value {x.value!r}; pass "
                                  f"--overwrite", file=sys.stderr)
                            return 1
                        x.value = value
                        replaced = True
                if not replaced:
                    taints.append(t.Taint(key=key, value=value,
                                          effect=effect))
                node.spec.taints = taints
                verb = "tainted"
            try:
                await client.update(node)
                print(f"node/{args.node} {verb}")
                return 0
            except errors.ConflictError:
                if attempt == 19:
                    raise
                await asyncio.sleep(0.05)
        return 1
    finally:
        await client.close()


async def cmd_set_image(args) -> int:
    """``ktl set image deployment/NAME container=image`` (kubectl set
    image analog) — the rollout-triggering one-liner."""
    client = make_client(args)
    try:
        kind, _, name = args.target.partition("/")
        plural = resolve_plural(kind)
        if plural not in ("deployments", "statefulsets", "daemonsets",
                          "replicasets", "pods"):
            print(f"Error: set image supports workload kinds, "
                  f"got {kind!r}", file=sys.stderr)
            return 1
        updates = {}
        for spec in args.images:
            cname, eq, image = spec.partition("=")
            if not eq or not cname or not image:
                print(f"Error: want container=image, got {spec!r}",
                      file=sys.stderr)
                return 1
            updates[cname] = image
        for attempt in range(20):
            obj = await client.get(plural, args.namespace, name)
            containers = (obj.spec.containers if plural == "pods"
                          else obj.spec.template.spec.containers)
            missing = set(updates) - {c.name for c in containers}
            if missing:
                print(f"Error: no container(s) {sorted(missing)} in "
                      f"{args.target}", file=sys.stderr)
                return 1
            for cont in containers:
                if cont.name in updates:
                    cont.image = updates[cont.name]
            try:
                await client.update(obj)
                for cname, image in updates.items():
                    print(f"{args.target} container {cname} image "
                          f"set to {image}")
                return 0
            except errors.ConflictError:
                if attempt == 19:
                    raise
                await asyncio.sleep(0.05)
        return 1
    finally:
        await client.close()


async def cmd_cordon(args) -> int:
    return await _set_unschedulable(args, True, "cordoned")


async def cmd_uncordon(args) -> int:
    return await _set_unschedulable(args, False, "uncordoned")


async def cmd_drain(args) -> int:
    """Cordon + evict every pod on the node through the PDB-gated
    Eviction subresource (kubectl drain analog). Filters match
    kubectl: DaemonSet pods abort the drain unless --ignore-daemonsets
    (then they are skipped — their controller would recreate them
    here anyway); controller-less pods abort unless --force. A pod
    whose PodDisruptionBudget allows no disruption makes the server
    answer 429; drain retries until --timeout, then reports which
    pods blocked — it NEVER deletes around the budget unless
    --disable-eviction explicitly asks for raw deletes."""
    import time as timelib
    client = make_client(args)
    try:
        await client.patch("nodes", "", args.node,
                           {"spec": {"unschedulable": True}})
        print(f"node/{args.node} cordoned")
        pods, _ = await client.list("pods")
        on_node = [p for p in pods if p.spec.node_name == args.node
                   and t.is_pod_active(p)]

        def has_owner(pod, kind=""):
            return any(not kind or ref.kind == kind
                       for ref in pod.metadata.owner_references)

        ds_pods = [p for p in on_node if has_owner(p, "DaemonSet")]
        if ds_pods and not args.ignore_daemonsets:
            names = ", ".join(f"{p.metadata.namespace}/{p.metadata.name}"
                              for p in ds_pods)
            print(f"ktl: cannot drain: DaemonSet-managed pods present "
                  f"({names}); use --ignore-daemonsets", file=sys.stderr)
            return 1
        unmanaged = [p for p in on_node if not has_owner(p)]
        if unmanaged and not args.force:
            names = ", ".join(f"{p.metadata.namespace}/{p.metadata.name}"
                              for p in unmanaged)
            print(f"ktl: cannot drain: pods without a controller would "
                  f"not be rescheduled ({names}); use --force",
                  file=sys.stderr)
            return 1
        victims = [p for p in on_node if p not in ds_pods]

        deadline = timelib.monotonic() + args.timeout
        blocked: dict[str, str] = {}
        evicted = 0
        pending = list(victims)
        while pending:
            still = []
            for pod in pending:
                ref = f"{pod.metadata.namespace}/{pod.metadata.name}"
                try:
                    if args.disable_eviction:
                        await client.delete(
                            "pods", pod.metadata.namespace,
                            pod.metadata.name,
                            grace_period_seconds=args.grace_period)
                    else:
                        await client.evict(
                            pod.metadata.namespace, pod.metadata.name,
                            t.Eviction(
                                grace_period_seconds=args.grace_period))
                    print(f"pod/{ref} evicted")
                    evicted += 1
                    blocked.pop(ref, None)
                except errors.NotFoundError:
                    evicted += 1
                    blocked.pop(ref, None)
                except errors.TooManyRequestsError as e:
                    blocked[ref] = str(e)
                    still.append(pod)
                except errors.StatusError as e:
                    # Per-pod failure (e.g. ambiguous multi-PDB 503):
                    # report and move on like kubectl — one bad pod
                    # must not strand the rest of the drain.
                    print(f"ktl: pod/{ref} eviction failed: {e}",
                          file=sys.stderr)
                    blocked[ref] = str(e)
            pending = still
            if pending:
                if timelib.monotonic() >= deadline:
                    for ref, why in blocked.items():
                        print(f"ktl: pod/{ref} not evicted: {why}",
                              file=sys.stderr)
                    print(f"ktl: drain timed out with "
                          f"{len(pending)} pods blocked by disruption "
                          f"budgets", file=sys.stderr)
                    return 1
                await asyncio.sleep(1.0)
        if blocked:  # permanent per-pod failures (already reported)
            print(f"ktl: drain incomplete: {len(blocked)} pods failed "
                  f"to evict", file=sys.stderr)
            return 1
        print(f"node/{args.node} drained ({evicted} pods)")
        return 0
    finally:
        await client.close()


async def _node_summaries(client, only: str = "") -> list[tuple]:
    """(node, /stats/summary JSON or None) per node — the scrape the
    ``ktl top`` family and the cluster monitor share the shape of.
    Concurrent over one shared session (like ClusterMonitor.sweep):
    sequential 5s timeouts across a fleet with a few dead node agents
    would stall the command for minutes."""
    import aiohttp
    nodes, _ = await client.list("nodes")
    if only:
        nodes = [n for n in nodes if n.metadata.name == only]
        if not nodes:
            raise SystemExit(f"ktl: node {only!r} not found")

    async def scrape(node, session):
        conn = await _node_daemon_base(client, node.metadata.name)
        if conn is None:
            return (node, None)
        base, node_ssl = conn
        try:
            async with session.get(f"{base}/stats/summary",
                                   timeout=aiohttp.ClientTimeout(total=5),
                                   **_ssl_kw(node_ssl)) as r:
                return (node, await r.json())
        except Exception:  # noqa: BLE001 — node down: show unreachable
            return (node, None)

    async with aiohttp.ClientSession() as session:
        return list(await asyncio.gather(
            *(scrape(node, session) for node in nodes)))


async def _stale_node_aggregates(client) -> dict:
    """Last-known ``tpu_node_*`` points from the kmon TSDB (range
    queries over /debug/v1/query) for nodes that cannot be scraped live
    — ``{node: {field: value, "age": seconds}}``. Empty when the
    ClusterMetricsPipeline gate is off (404) or unreachable: callers
    then render 'unreachable' exactly as before the pipeline existed."""
    import time
    out: dict = {}
    now = time.time()
    families = {
        "tpu_node_chips": None,  # state label fans out below
        "tpu_node_duty_cycle_avg_pct": "duty_avg_pct",
        "tpu_node_hbm_used_bytes": "hbm_used_bytes",
        "tpu_node_hbm_total_bytes": "hbm_total_bytes",
        "tpu_node_tokens_per_sec": "tokens_per_sec",
    }

    async def instant(expr: str):
        async with client._sess().get(
                f"{client.base_url}/debug/v1/query",
                params={"query": expr}) as r:
            if r.status != 200:
                return None
            return (await r.json())["data"].get("result", [])

    # All 10 queries in flight at once (two per family): a dead node
    # already cost this command a scrape timeout; serializing debug
    # round-trips on top would be the _node_summaries mistake again.
    try:
        results = await asyncio.gather(*(
            instant(expr) for family in families
            for expr in (f"last_over_time({family}[15m])",
                         f"timestamp(last_over_time({family}[15m]))")))
    except Exception:  # noqa: BLE001 — old server / no pipeline
        return {}
    for i, (family, field) in enumerate(families.items()):
        values, stamps = results[2 * i], results[2 * i + 1]
        if values is None or stamps is None:
            return {}
        ts_by_key = {tuple(sorted(e["metric"].items())): e["value"][1]
                     for e in stamps}
        for e in values:
            labels = e["metric"]
            node = labels.get("node", "")
            if not node:
                continue
            ts = ts_by_key.get(tuple(sorted(labels.items())))
            if ts is None:
                continue
            rec = out.setdefault(node, {"age": now - ts})
            rec["age"] = min(rec["age"], now - ts)
            if family == "tpu_node_chips":
                rec[f"chips_{labels.get('state', '')}"] = e["value"][1]
            else:
                rec[field] = e["value"][1]
    return out


async def _top_nodes(client) -> int:
    """``ktl top nodes`` — per-node TPU telemetry rollup (the
    aggregator's tpu_node_* view, computed from the same scrapes).
    Unscrapable nodes fall back to the kmon TSDB's last-known
    aggregate, marked with a trailing ``*`` and a real AGE — a dead
    node must read as stale data, never as silently fresh."""
    from ..monitoring.aggregator import ClusterMonitor
    rows = []
    per_pod: dict = {}
    fresh_aggs: dict = {}
    summaries = await _node_summaries(client)
    stale_info: dict = {}
    if any(summary is None for _node, summary in summaries):
        stale_info = await _stale_node_aggregates(client)
    for node, summary in summaries:
        name = node.metadata.name
        if summary is None:
            info = stale_info.get(name)
            if not info:
                rows.append([name, "-", "-", "-", "-", "-", "-", "-",
                             "unreachable"])
                continue
            total = int(info.get("chips_total", 0))
            hbm_total = info.get("hbm_total_bytes", 0.0)
            tokens = info.get("tokens_per_sec", 0.0)
            rows.append([
                f"{name}*",
                str(total),
                str(int(info.get("chips_healthy", 0))),
                str(int(info.get("chips_assigned", 0))),
                (f"{info.get('duty_avg_pct', 0.0):.1f}%"
                 if total else "-"),
                (f"{info.get('hbm_used_bytes', 0.0) / 2**30:.1f}Gi/"
                 f"{hbm_total / 2**30:.1f}Gi" if hbm_total else "-"),
                f"{tokens:.0f}" if tokens else "-",
                printers.age_seconds(info["age"]),
                "stale"])
            continue
        agg = ClusterMonitor._aggregate_node(name, summary, per_pod)
        fresh_aggs[name] = agg
        rows.append([
            name,
            str(agg["chips"]),
            str(agg["healthy"]),
            str(agg["assigned"]),
            f"{agg['duty_avg_pct']:.1f}%" if agg["chips"] else "-",
            (f"{agg['hbm_used_bytes'] / 2**30:.1f}Gi/"
             f"{agg['hbm_total_bytes'] / 2**30:.1f}Gi"
             if agg["hbm_total_bytes"] else "-"),
            (f"{agg['tokens_per_sec']:.0f}"
             if agg["tokens_per_sec"] else "-"),
            "0s",
            f"{agg['pods']} pods"])
    print(printers.render_table(
        ["NODE", "CHIPS", "HEALTHY", "ASSIGNED", "DUTY", "HBM",
         "TOK/S", "AGE", "WORKLOAD"], rows))
    # Per-slice fragmentation footer — the same rollup the aggregator
    # exports as tpu_slice_fragmentation and the defrag planner scores
    # moves with (stale/unreachable nodes' chips are absent here, so a
    # half-scraped fleet reads "-" rather than a wrong number).
    frag = ClusterMonitor._fragmentation(fresh_aggs)
    if frag["slices"]:
        frows = [[sid, str(rec["free_chips"]),
                  str(rec["largest_free_box"]),
                  f"{rec['fragmentation']:.2f}"]
                 for sid, rec in frag["slices"].items()]
        if len(frag["slices"]) > 1:
            frows.append(["(cluster)", str(frag["free_chips"]),
                          str(frag["largest_free_box"]),
                          f"{frag['cluster']:.2f}"])
        print()
        print(printers.render_table(
            ["SLICE", "FREE", "LARGEST-BOX", "FRAG"], frows))
    return 0


async def _top_pods(client) -> int:
    """``ktl top pods`` — per-pod chip attribution + live telemetry
    (duty cycle, HBM, tokens/s, MFU) across the fleet."""
    from ..monitoring.aggregator import ClusterMonitor
    per_pod: dict = {}
    for node, summary in await _node_summaries(client):
        if summary is not None:
            ClusterMonitor._aggregate_node(
                node.metadata.name, summary, per_pod)
    rows = []
    for pkey in sorted(per_pod):
        rec = per_pod[pkey]
        rows.append([
            pkey, rec.get("node", "-"),
            str(rec.get("chips", 0)),
            (f"{rec['duty_avg_pct']:.1f}%"
             if rec.get("chips") else "-"),
            (f"{rec['hbm_used_bytes'] / 2**30:.1f}Gi"
             if rec.get("hbm_used_bytes") else "-"),
            (f"{rec['tokens_per_sec']:.0f}"
             if "tokens_per_sec" in rec else "-"),
            (f"{rec['mfu'] * 100:.2f}%" if "mfu" in rec else "-"),
            (f"{rec['memory_rss_bytes'] / 2**20:.0f}Mi"
             if rec.get("memory_rss_bytes") else "-")])
    print(printers.render_table(
        ["POD", "NODE", "CHIPS", "DUTY", "HBM", "TOK/S", "MFU",
         "MEMORY"], rows))
    return 0


async def cmd_top(args) -> int:
    """Scrape /stats/summary — ``ktl top`` (legacy chip view),
    ``ktl top nodes`` / ``ktl top pods`` (TPU telemetry rollups), or
    ``ktl top <node>`` (one node's chip view)."""
    client = make_client(args)
    try:
        if args.node == "nodes":
            return await _top_nodes(client)
        if args.node == "pods":
            return await _top_pods(client)
        nodes, _ = await client.list("nodes")
        if args.node:
            nodes = [n for n in nodes if n.metadata.name == args.node]
            if not nodes:
                raise SystemExit(f"ktl: node {args.node!r} not found")
        import aiohttp
        rows, chip_rows = [], []
        for node in nodes:
            conn = await _node_daemon_base(client, node.metadata.name)
            if conn is None:
                rows.append([node.metadata.name, "-", "-", "unreachable"])
                continue
            base, node_ssl = conn
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{base}/stats/summary",
                                 **_ssl_kw(node_ssl)) as r:
                    summary = await r.json()
            mem = summary["node"]["memory"]
            rows.append([
                node.metadata.name,
                f"{summary['node']['cpu']['load1']:.2f}",
                f"{mem['used_bytes'] / 2**30:.1f}Gi/{mem['total_bytes'] / 2**30:.1f}Gi",
                f"{len(summary['pods'])} pods"])
            for chip in summary.get("tpu", {}).get("chips", []):
                owner = chip.get("assigned_to")
                hbm = chip.get("hbm_used_bytes")
                chip_rows.append([
                    node.metadata.name, chip["id"], chip["health"],
                    ",".join(map(str, chip["coords"])),
                    f"{owner['namespace']}/{owner['pod']}" if owner else "<idle>",
                    (f"{chip['mfu'] * 100:.2f}%" if "mfu" in chip else "-"),
                    (f"{chip['tokens_per_sec']:.0f}"
                     if "tokens_per_sec" in chip else "-"),
                    (f"{hbm / 2**30:.1f}Gi" if hbm is not None else "-")])
        print(printers.render_table(["NODE", "LOAD1", "MEMORY", "WORKLOAD"], rows))
        if chip_rows:
            print()
            print(printers.render_table(
                ["NODE", "CHIP", "HEALTH", "COORDS", "ASSIGNED-TO",
                 "MFU", "TOK/S", "HBM"], chip_rows))
        return 0
    finally:
        await client.close()


async def _fetch_trace_spans(client, trace_id: str = "",
                             pod: str = "") -> list:
    """Spans from the apiserver's /debug/v1/traces surface (the
    client's own session carries CA trust + credentials)."""
    params = {}
    if trace_id:
        params["trace_id"] = trace_id
    if pod:
        params["pod"] = pod
    async with client._sess().get(f"{client.base_url}/debug/v1/traces",
                                  params=params) as r:
        if r.status != 200:
            raise SystemExit(f"ktl: /debug/v1/traces answered {r.status}")
        data = await r.json()
    return data.get("spans", [])


async def _pod_events(client, namespace: str, pod, trace_id: str,
                      events: Optional[list] = None) -> list:
    """(epoch ts, text, in_trace) for the pod's Events — interleaved
    into the trace rendering; ``in_trace`` marks events whose
    trace.tpu/trace-id annotation matches (the recorder's breadcrumb).
    ``events``: a pre-fetched namespace event list to filter instead
    of LISTing again (the gang path shares one fetch)."""
    from .. import tracing
    if events is None:
        try:
            events, _ = await client.list("events", namespace)
        except errors.StatusError:
            return []
    out = []
    for ev in events:
        ref = ev.involved_object
        if ref.name != pod.metadata.name \
                or (ref.uid and ref.uid != pod.metadata.uid):
            continue
        ts = ev.first_timestamp
        epoch = ts.timestamp() if ts is not None else 0.0
        tagged = ev.metadata.annotations.get(
            tracing.TRACE_ID_ANNOTATION, "")
        out.append((epoch, f"{ev.type} {ev.reason}: {ev.message}",
                    bool(trace_id) and tagged == trace_id))
    out.sort()
    return out


def _gang_round_timeline(group, members: list, events: list) -> list:
    """(epoch, text) rows reconstructing the gang's kill -> recover ->
    resume history from durable state: ``status.preemption`` round
    transitions interleaved with the restart/create/delete Events of
    the group, its member pods, and its controller owner (TrainJob or
    Job) — so the whole timeline reads from one command even when the
    members themselves are untraced."""
    rows = []
    st = group.status.preemption
    if st is not None:
        if st.signaled_time is not None:
            rows.append((st.signaled_time.timestamp(),
                         f"preemption round signaled "
                         f"({len(st.signaled)} members, "
                         f"{len(st.checkpointed)} checkpointed)"))
        if st.requeued_time is not None:
            step = (f" checkpoint_step={st.checkpoint_step}"
                    if st.checkpoint_step >= 0 else "")
            rows.append((st.requeued_time.timestamp(),
                         f"preemption round requeued "
                         f"outcome={st.outcome or '<none>'}{step} "
                         f"(rounds={st.rounds})"))
    names = {group.metadata.name} | {p.metadata.name for p in members}
    # Prior-round members are deleted, so their kill/failure Events
    # can't be matched by the CURRENT pod list — match Pod events by
    # the controllers' exact generated shape `<owner>-<rank>-<hex6>`
    # instead (anchored: a SIBLING job named `<owner>-2` generates
    # `<owner>-2-<rank>-<hex6>`, which must not leak into this view).
    member_pats = []
    for ref in group.metadata.owner_references:
        if ref.controller:
            names.add(ref.name)
            member_pats.append(re.compile(
                rf"^{re.escape(ref.name)}-\d+-[0-9a-f]{{6}}$"))
    for p in members:
        ts = p.metadata.creation_timestamp
        if ts is not None:
            rank = p.metadata.labels.get("training.tpu/rank", "")
            rank_note = f" rank={rank}" if rank else ""
            rows.append((ts.timestamp(),
                         f"member {p.metadata.name} created"
                         f"{rank_note} (phase {p.status.phase})"))
    for ev in events:
        ref = ev.involved_object
        if ref.name not in names and not (
                ref.kind == "Pod"
                and any(p.match(ref.name) for p in member_pats)):
            continue
        ts = ev.first_timestamp
        if ts is None:
            # No orderable time: a 0.0 epoch would become the t0
            # anchor and turn every printed offset into epoch scale.
            continue
        rows.append((ts.timestamp(),
                     f"{ev.type} {ev.reason} "
                     f"[{ev.involved_object.kind}/"
                     f"{ev.involved_object.name}]: {ev.message}"))
    rows.sort()
    return rows


def _render_trace(title: str, trace_id: str, spans: list,
                  events: list) -> str:
    """One pod's trace: stage breakdown table, then the span tree with
    Events interleaved in time order."""
    from ..tracing import timeline as tlmod
    lines = [f"TRACE {trace_id}  {title}"]
    tline = tlmod.pod_timeline(spans)
    if tline is not None:
        lines.append(f"  e2e {tline['e2e_ms']:.1f}ms  "
                     f"complete={str(tline['complete']).lower()}")
        rows = [[st["stage"], f"+{st['start_ms']:.1f}ms",
                 f"{st['duration_ms']:.1f}ms",
                 f"{st['share'] * 100:.1f}%"]
                for st in tline["stages"]]
        lines.append(printers.render_table(
            ["STAGE", "START", "DURATION", "SHARE"], rows))
    by_id = {s.get("span_id"): s for s in spans}

    def depth(s) -> int:
        d, cur = 0, s
        while d < 16:
            parent = by_id.get(cur.get("parent_id") or "")
            if parent is None:
                return d
            d, cur = d + 1, parent
        return d

    t0 = min(s.get("start", 0.0) for s in spans) if spans else 0.0
    items = []
    for s in spans:
        extra = ""
        attrs = s.get("attrs") or {}
        notes = [f"{k}={v}" for k, v in sorted(attrs.items())
                 if k not in ("pod", "gang")]
        if notes:
            extra = "  [" + " ".join(notes) + "]"
        items.append((s.get("start", 0.0), 0, (
            f"{1e3 * (s.get('start', 0.0) - t0):8.1f}ms "
            f"{'  ' * depth(s)}{s.get('name')} "
            f"({s.get('component')}) {s.get('duration_ms', 0.0):.1f}ms"
            f"{extra}")))
        for ts, msg in s.get("events") or []:
            items.append((ts, 1, (f"{1e3 * (ts - t0):8.1f}ms "
                                  f"{'  ' * (depth(s) + 1)}- {msg}")))
    for epoch, text, in_trace in events:
        mark = "*" if in_trace else " "
        items.append((epoch, 2,
                      f"{1e3 * (epoch - t0):8.1f}ms {mark} event {text}"))
    items.sort(key=lambda it: (it[0], it[1]))
    lines.extend(text for _ts, _k, text in items)
    return "\n".join(lines)


async def cmd_trace(args) -> int:
    """``ktl trace pod <name>`` / ``ktl trace gang <group>`` — render
    the ktrace lifecycle timeline (create -> queue -> schedule -> bind
    -> start -> ready) with per-stage durations and Events interleaved.
    Requires tracing armed at creation time (KTPU_TRACE; see README
    "Tracing & TPU telemetry")."""
    from .. import tracing
    client = make_client(args)
    try:
        if args.kind == "pod":
            pod = await client.get("pods", args.namespace, args.name)
            ctx = tracing.context_of(pod)
            if ctx is None:
                raise SystemExit(
                    f"ktl: pod {args.namespace}/{args.name} carries no "
                    f"trace annotation — arm tracing (KTPU_TRACE=1.0) "
                    f"before creating it")
            spans = await _fetch_trace_spans(client, trace_id=ctx.trace_id)
            if not spans:
                raise SystemExit(
                    f"ktl: no spans collected for trace {ctx.trace_id} "
                    f"(collector bounded/rotated, or components run "
                    f"out-of-process without span push)")
            events = await _pod_events(client, args.namespace, pod,
                                       ctx.trace_id)
            if args.output == "json":
                from ..tracing import timeline as tlmod
                print(json.dumps({
                    "pod": f"{args.namespace}/{args.name}",
                    "trace_id": ctx.trace_id,
                    "timeline": tlmod.pod_timeline(spans),
                    "spans": spans,
                }, default=str))
            else:
                print(_render_trace(f"pod {args.namespace}/{args.name}",
                                    ctx.trace_id, spans, events))
            return 0
        # gang: per-member stage summary + the slowest member's detail.
        from ..tracing import timeline as tlmod
        pods, _ = await client.list("pods", args.namespace)
        members = sorted((p for p in pods if p.spec.gang == args.name),
                         key=lambda p: p.metadata.name)
        try:
            group = await client.get("podgroups", args.namespace,
                                     args.name)
        except errors.NotFoundError:
            # A queued gang's PodGroup is DELETED at terminal (the
            # quota-release rule) while the member pods survive — the
            # timeline must still render. Synthesize a shell group and
            # graft the controller owner from a member so the Events
            # filter keeps working; with no members either, there is
            # genuinely nothing to show.
            if not members:
                raise SystemExit(
                    f"ktl: gang {args.namespace}/{args.name} not found "
                    f"(no PodGroup and no member pods)")
            from ..api import types as _t
            from ..api.meta import get_controller_of
            group = _t.PodGroup(metadata=_t.ObjectMeta(
                name=args.name, namespace=args.namespace))
            owner = get_controller_of(members[0])
            if owner is not None:
                group.metadata.owner_references = [owner]
            group.status.phase = "<released>"
        # Zero members is a REAL state worth rendering — a recovery
        # round's teardown window, or a cleaned-up finished gang: the
        # ROUNDS timeline below still reconstructs the history from
        # status.preemption and the surviving Events.
        rows, timelines = [], {}
        for p in members:
            ctx = tracing.context_of(p)
            if ctx is None:
                rows.append([p.metadata.name, "<untraced>", "-", "-",
                             "-", "-", "-"])
                continue
            spans = await _fetch_trace_spans(client,
                                             trace_id=ctx.trace_id)
            tline = tlmod.pod_timeline(spans)
            if tline is None:
                rows.append([p.metadata.name, ctx.trace_id[:16], "-",
                             "-", "-", "-", "-"])
                continue
            timelines[p.metadata.name] = (ctx, spans, tline)
            dur = {st["stage"]: st["duration_ms"]
                   for st in tline["stages"]}
            rows.append([
                p.metadata.name, ctx.trace_id[:16],
                f"{tline['e2e_ms']:.1f}ms",
                f"{dur.get('queue', 0.0):.1f}ms",
                f"{dur.get('schedule', 0.0):.1f}ms",
                f"{dur.get('bind', 0.0):.1f}ms",
                f"{dur.get('start', 0.0):.1f}ms"])
        # One event fetch for the whole command: the ROUNDS timeline
        # and the slowest-member detail filter the same list.
        try:
            ns_events, _ = await client.list("events", args.namespace)
        except errors.StatusError:
            ns_events = []
        rounds = _gang_round_timeline(group, members, ns_events)
        if args.output == "json":
            st = group.status.preemption
            print(json.dumps({
                "gang": f"{args.namespace}/{args.name}",
                "phase": group.status.phase,
                "members": {name: tline
                            for name, (_c, _s, tline)
                            in timelines.items()},
                "rounds": [{"time": ts, "what": text}
                           for ts, text in rounds],
                "preemption": None if st is None else {
                    "phase": st.phase, "rounds": st.rounds,
                    "outcome": st.outcome,
                    "checkpoint_step": st.checkpoint_step},
            }, default=str))
            return 0
        print(f"GANG {args.namespace}/{args.name}  "
              f"phase={group.status.phase}  members={len(members)}")
        print(printers.render_table(
            ["POD", "TRACE", "E2E", "QUEUE", "SCHEDULE", "BIND",
             "START"], rows))
        if rounds:
            # The kill -> recover -> resume history: preemption round
            # transitions + restart events, one time-ordered view.
            t0 = rounds[0][0]
            print("\nROUNDS")
            for ts, text in rounds:
                print(f"  {1e3 * (ts - t0):10.1f}ms  {text}")
        if timelines:
            slowest = max(timelines.items(),
                          key=lambda kv: kv[1][2]["e2e_ms"])
            name, (ctx, spans, _tline) = slowest
            print(f"\nslowest member: {name}")
            events = await _pod_events(
                client, args.namespace,
                next(p for p in members if p.metadata.name == name),
                ctx.trace_id, events=ns_events)
            print(_render_trace(f"pod {args.namespace}/{name}",
                                ctx.trace_id, spans, events))
        return 0
    finally:
        await client.close()


async def _kmon_get(client, path: str, params: dict) -> dict:
    """GET a kmon debug surface with the client's own session (CA
    trust + credentials). 404 = the gate is off — say so instead of
    printing an empty table that looks like a healthy cluster."""
    async with client._sess().get(f"{client.base_url}{path}",
                                  params=params) as r:
        if r.status == 404:
            raise SystemExit(
                "ktl: metrics pipeline not enabled on this cluster "
                "(start with --feature-gates ClusterMetricsPipeline"
                "=true)")
        if r.status != 200:
            raise SystemExit(f"ktl: {path} answered {r.status}: "
                             f"{(await r.text())[:200]}")
        return await r.json()


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values: list, width: int = 40) -> str:
    """Text sparkline, min-max scaled over the FINITE values; NaN/inf
    (legitimate PromQL division results) render as '·' instead of
    crashing the int() conversion."""
    import math
    if not values:
        return ""
    if len(values) > width:
        # Downsample keep-last per bucket — the newest point always
        # renders (it is the one being watched).
        step = len(values) / width
        values = [values[min(len(values) - 1, int((i + 1) * step) - 1)]
                  for i in range(width)]
    finite = [v for v in values if math.isfinite(v)]
    if not finite:
        return "·" * len(values)
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in values:
        if not math.isfinite(v):
            out.append("·")
        elif span <= 0:
            out.append(_SPARK_BLOCKS[0])
        else:
            out.append(_SPARK_BLOCKS[min(
                len(_SPARK_BLOCKS) - 1,
                int((v - lo) / span * len(_SPARK_BLOCKS)))])
    return "".join(out)


def _fmt_metric_labels(labels: dict) -> str:
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())
                     if k != "__name__")
    return "{" + inner + "}" if inner else "{}"


async def cmd_query(args) -> int:
    """``ktl query <expr>`` — PromQL-lite over the kmon TSDB (instant
    by default; ``--range 5m`` evaluates a range and renders one
    sparkline per series)."""
    import json as _json
    client = make_client(args)
    try:
        params = {"query": args.expr}
        if args.range:
            import time
            from ..monitoring.promql import parse_duration
            window = parse_duration(args.range)
            now = time.time()
            params["start"] = f"{now - window:.3f}"
            params["end"] = f"{now:.3f}"
            if args.step:
                params["step"] = str(parse_duration(args.step))
        data = (await _kmon_get(client, "/debug/v1/query", params))["data"]
        if args.output == "json":
            print(_json.dumps(data, indent=2, sort_keys=True))
            return 0
        if data["resultType"] == "scalar":
            print(f"{data['result'][1]:g}")
            return 0
        if data["resultType"] == "vector":
            rows = [[_fmt_metric_labels(e["metric"]),
                     f"{e['value'][1]:g}"]
                    for e in data["result"]]
            print(printers.render_table(["SERIES", "VALUE"], rows))
            return 0
        rows = []
        for series in data["result"]:
            vals = [v for _ts, v in series["values"]]
            rows.append([
                _fmt_metric_labels(series["metric"]),
                _sparkline(vals),
                f"{min(vals):g}", f"{max(vals):g}", f"{vals[-1]:g}"])
        print(printers.render_table(
            ["SERIES", "TREND", "MIN", "MAX", "LAST"], rows))
        return 0
    finally:
        await client.close()


async def cmd_alerts(args) -> int:
    """``ktl alerts`` — active kmon alerts (pending + firing)."""
    import json as _json
    import time
    client = make_client(args)
    try:
        data = await _kmon_get(client, "/debug/v1/alerts", {})
        if args.output == "json":
            print(_json.dumps(data, indent=2, sort_keys=True))
            return 0
        now = time.time()
        rows = []
        for a in data["alerts"]:
            labels = {k: v for k, v in a["labels"].items()
                      if k not in ("job", "instance")} \
                or {k: v for k, v in a["labels"].items()}
            rows.append([
                a["name"], a["severity"], a["state"],
                printers.age_seconds(now - a["active_since"]),
                _fmt_metric_labels(labels),
                f"{a['value']:g}"])
        if not rows:
            print("No active alerts.")
            return 0
        print(printers.render_table(
            ["ALERT", "SEVERITY", "STATE", "SINCE", "LABELS", "VALUE"],
            rows))
        return 0
    finally:
        await client.close()


#: The dash panels: built-in recording rules (rules.py) + the scrape
#: health vector. (title, expr) — each renders one sparkline row per
#: series over the dash window.
_DASH_PANELS = (
    ("cluster duty %", "cluster:tpu_duty:avg"),
    ("tokens/s", "cluster:tpu_tokens:sum"),
    ("unhealthy chips", "cluster:chips_unhealthy:sum"),
    ("HBM used (GiB)", "cluster:hbm_used:sum / 1073741824"),
    ("targets up", "job:up:sum"),
    ("apiserver busy", "apiserver:loop_busy:max"),
)


async def cmd_dash(args) -> int:
    """``ktl dash`` — text dashboard over the built-in recording rules
    (the Grafana-analog single screen)."""
    import time
    from ..monitoring.promql import parse_duration
    client = make_client(args)
    try:
        window = parse_duration(args.range)
        now = time.time()
        rows = []
        for title, expr in _DASH_PANELS:
            data = (await _kmon_get(client, "/debug/v1/query", {
                "query": expr,
                "start": f"{now - window:.3f}",
                "end": f"{now:.3f}"}))["data"]
            result = data.get("result") or []
            if not result:
                rows.append([title, "", "-", "no data"])
                continue
            for series in result:
                vals = [v for _ts, v in series["values"]]
                label = _fmt_metric_labels(series["metric"])
                rows.append([
                    title if series is result[0] else "",
                    _sparkline(vals, width=32),
                    f"{vals[-1]:g}",
                    label if label != "{}" else ""])
        alerts = (await _kmon_get(client, "/debug/v1/alerts", {}))
        firing = [a for a in alerts["alerts"] if a["state"] == "firing"]
        print(f"kmon dash  window={args.range}  "
              f"firing_alerts={len(firing)}")
        print(printers.render_table(
            ["PANEL", "TREND", "LAST", "SERIES"], rows))
        for a in firing:
            print(f"  FIRING {a['name']} [{a['severity']}] "
                  f"{_fmt_metric_labels(a['labels'])} {a['summary']}")
        return 0
    finally:
        await client.close()


async def cmd_api_resources(args) -> int:
    client = make_client(args)
    try:
        # The client's own session: it carries the cluster CA trust.
        async with client._sess().get(f"{client.base_url}/apis") as r:
            data = await r.json()
        rows = [[spec["name"], spec["api_version"],
                 str(spec["namespaced"]), spec["kind"]]
                for spec in sorted(data["resources"], key=lambda d: d["name"])]
        print(printers.render_table(
            ["NAME", "APIVERSION", "NAMESPACED", "KIND"], rows))
        return 0
    finally:
        await client.close()


def _explain_type(tp):
    """Human name for a dataclass field annotation."""
    import typing
    origin = typing.get_origin(tp)
    if origin in (list, tuple):
        args = typing.get_args(tp)
        inner = _explain_type(args[0]) if args else "object"
        return f"[]{inner}"
    if origin is dict:
        args = typing.get_args(tp)
        if len(args) == 2:
            return f"map[{_explain_type(args[0])}]{_explain_type(args[1])}"
        return "map"
    if origin is typing.Union:  # Optional[X]
        inner = [a for a in typing.get_args(tp) if a is not type(None)]
        return _explain_type(inner[0]) if inner else "object"
    if isinstance(tp, str):
        return tp
    return getattr(tp, "__name__", str(tp))


def _explain_target(tp):
    """The dataclass to recurse into for a field annotation, if any."""
    import dataclasses
    import typing
    origin = typing.get_origin(tp)
    if origin in (list, tuple):
        args = typing.get_args(tp)
        return _explain_target(args[0]) if args else None
    if origin is dict:
        args = typing.get_args(tp)
        return _explain_target(args[1]) if len(args) == 2 else None
    if origin is typing.Union:
        inner = [a for a in typing.get_args(tp) if a is not type(None)]
        return _explain_target(inner[0]) if inner else None
    return tp if dataclasses.is_dataclass(tp) else None


async def cmd_explain(args) -> int:
    """Field documentation from scheme introspection (kubectl explain;
    reference drives this from OpenAPI — here the dataclasses ARE the
    schema, so the answer comes straight from the registered types,
    no server round trip)."""
    import dataclasses
    import inspect
    import typing
    from ..apiserver.registry import Registry

    path = args.resource.split(".")
    plural = resolve_plural(path[0])
    try:
        spec = Registry().spec_for(plural)
    except errors.StatusError:
        print(f"Error: unknown resource {path[0]!r} "
              f"(try: ktl api-resources)", file=sys.stderr)
        return 1
    cls = spec.cls
    walked = [plural]
    for seg in path[1:]:
        if not dataclasses.is_dataclass(cls):
            print(f"Error: {'.'.join(walked)} has no fields to descend "
                  f"into", file=sys.stderr)
            return 1
        hints = typing.get_type_hints(cls)
        fields = {f.name: f for f in dataclasses.fields(cls)}
        if seg not in fields:
            print(f"Error: field {seg!r} not found in {'.'.join(walked)} "
                  f"(fields: {', '.join(sorted(fields))})", file=sys.stderr)
            return 1
        nxt = _explain_target(hints.get(seg, fields[seg].type))
        if nxt is None:
            print(f"{'.'.join(walked + [seg])}: "
                  f"{_explain_type(hints.get(seg, fields[seg].type))} "
                  f"(scalar — nothing further to explain)")
            return 0
        cls = nxt
        walked.append(seg)

    print(f"KIND:     {spec.kind}")
    print(f"VERSION:  {spec.api_version}")
    print(f"RESOURCE: {'.'.join(walked)} <{cls.__name__}>")
    doc = inspect.getdoc(cls)
    if doc and doc.startswith(f"{cls.__name__}("):
        doc = ""  # auto-generated dataclass signature, not prose
    if doc:
        print("\nDESCRIPTION:")
        for line in doc.splitlines():
            print(f"     {line}")
    if dataclasses.is_dataclass(cls):
        hints = typing.get_type_hints(cls)
        print("\nFIELDS:")
        for f in dataclasses.fields(cls):
            tname = _explain_type(hints.get(f.name, f.type))
            print(f"   {f.name:<28} <{tname}>")
    return 0


async def cmd_version(args) -> int:
    from .. import __version__
    print(f"ktl version {__version__}")
    try:
        client = make_client(args)
    except SystemExit:
        return 0
    try:
        async with client._sess().get(f"{client.base_url}/version") as r:
            print("server:", json.dumps(await r.json()))
    except Exception:  # noqa: BLE001
        print("server: unreachable")
    finally:
        await client.close()
    return 0


async def cmd_up(args) -> int:
    """Start a single-process cluster and block until SIGINT/SIGTERM
    (the local-up-cluster.sh analog)."""
    from ..cluster.config import config_from_args
    from ..cluster.local import LocalCluster
    from ..util.features import GATES

    # All file/flag precedence lives in config_from_args — cmd_up reads
    # the merged config unconditionally.
    cfg = config_from_args(args)
    specs = cfg.nodes
    if cfg.feature_gates:
        GATES.parse(cfg.feature_gates)
    tokens = user_groups = None
    admin_token = ""
    if cfg.authorization_mode == "RBAC":
        # Bootstrap credential (reference: kubeadm's admin.conf): an
        # admin token in system:masters, used by the node agents and
        # recorded for the CLI — without it RBAC mode is a
        # chicken-and-egg brick (nobody could create the first binding).
        import secrets
        from ..api.rbac import GROUP_MASTERS
        admin_token = secrets.token_urlsafe(24)
        tokens = {admin_token: "admin"}
        user_groups = {"admin": {GROUP_MASTERS}}
    cluster = LocalCluster(
        data_dir=cfg.data_dir or None, nodes=specs,
        host=cfg.host, port=cfg.port, durable=cfg.durable,
        tokens=tokens, user_groups=user_groups,
        authorization_mode=cfg.authorization_mode,
        audit_log=cfg.audit_log, audit_policy=cfg.audit_policy,
        audit_webhook=cfg.audit_webhook,
        scheduler_policy=cfg.scheduler_policy,
        encryption_provider_config=cfg.encryption_provider_config,
        tls=not getattr(args, "insecure", False))
    base = await cluster.start()
    os.makedirs(os.path.dirname(DEFAULT_CONFIG), exist_ok=True)
    # 0600 from birth — the admin token must never be world-readable,
    # even for a moment.
    record = {"server": base, "token": admin_token}
    if cluster.tls:
        record["ca"] = cluster.ca_file
        record["client_cert"] = cluster.admin_cert.cert_path
        record["client_key"] = cluster.admin_cert.key_path
    fd = os.open(DEFAULT_CONFIG, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(record, f)
    # O_CREAT's mode only applies to NEW files; a pre-existing config
    # from an older run may be 0644 — tighten it regardless.
    os.chmod(DEFAULT_CONFIG, 0o600)
    real = [s.name for s in specs if s.real_tpu]
    stub = sum(s.tpu_chips for s in specs)
    tpu_note = (f" ({', '.join(real)} probing real TPU)" if real else
                f" ({stub} stub chips total)" if stub else "")
    print(f"cluster up at {base} — {len(specs)} node(s){tpu_note}")
    if cluster.dns is not None:
        print(f"cluster DNS at {cluster.dns.address} "
              f"(pods get KTPU_DNS_SERVER)")
    print(f"server recorded in {DEFAULT_CONFIG}; try: ktl get nodes")
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover
            pass
    await stop.wait()
    print("shutting down ...")
    await cluster.stop()
    return 0


async def cmd_rollout(args) -> int:
    """``ktl rollout status|history|undo deployment/<name>`` (reference:
    ``kubectl rollout``; undo copies the target revision's ReplicaSet
    template back into the deployment spec)."""
    from ..api import workloads as w  # noqa: F401 — kinds registered
    from ..controllers.deployment import (REVISION_ANNOTATION,
                                          TEMPLATE_HASH_LABEL,
                                          template_hash)

    client = make_client(args)
    try:
        kind, _, name = args.target.partition("/")
        if kind not in ("deployment", "deployments", "deploy") or not name:
            print("rollout supports deployment/<name>", file=sys.stderr)
            return 1
        ns = args.namespace

        async def owned_replicasets():
            rss, _ = await client.list("replicasets", ns)
            return sorted(
                (rs for rs in rss if any(
                    r.kind == "Deployment" and r.name == name and r.controller
                    for r in rs.metadata.owner_references)),
                key=lambda rs: int(rs.metadata.annotations.get(
                    REVISION_ANNOTATION, 0)))

        if args.action == "status":
            loop = asyncio.get_running_loop()
            deadline = loop.time() + args.timeout  # wall deadline, not
            while loop.time() < deadline:          # an iteration count
                dep = await client.get("deployments", ns, name)
                want = dep.spec.replicas
                st = dep.status
                # Gate on observedGeneration first (kubectl does): the
                # status is from the PREVIOUS rollout until the
                # controller has seen this generation — without the
                # gate a just-updated deployment reports instant
                # false success.
                if (st.observed_generation >= dep.metadata.generation
                        and st.updated_replicas >= want
                        and st.available_replicas >= want
                        and st.replicas == want):
                    print(f"deployment {name!r} successfully rolled out")
                    return 0
                print(f"waiting: {st.updated_replicas}/{want} updated, "
                      f"{st.available_replicas}/{want} available")
                await asyncio.sleep(0.1)
            print(f"deployment {name!r} rollout timed out", file=sys.stderr)
            return 1

        if args.action == "history":
            print(f"{'REVISION':<10}{'REPLICASET':<40}REPLICAS")
            for rs in await owned_replicasets():
                rev = rs.metadata.annotations.get(REVISION_ANNOTATION, "?")
                print(f"{rev:<10}{rs.metadata.name:<40}{rs.spec.replicas}")
            return 0

        if args.action in ("pause", "resume"):
            want = args.action == "pause"
            for attempt in range(20):
                dep = await client.get("deployments", ns, name)
                if dep.spec.paused == want:
                    print(f"deployment {name!r} already "
                          f"{'paused' if want else 'resumed'}")
                    return 0
                dep.spec.paused = want
                try:
                    await client.update(dep)
                    print(f"deployment {name!r} "
                          f"{'paused' if want else 'resumed'}")
                    return 0
                except errors.ConflictError:
                    if attempt == 19:
                        raise
                    await asyncio.sleep(0.05)
            return 1

        # undo
        rss = await owned_replicasets()
        if not rss:
            print(f"no rollout history for {name!r}", file=sys.stderr)
            return 1
        dep = await client.get("deployments", ns, name)
        if args.to_revision:
            target = next(
                (rs for rs in rss if rs.metadata.annotations.get(
                    REVISION_ANNOTATION) == str(args.to_revision)), None)
            if target is None:
                print(f"revision {args.to_revision} not found", file=sys.stderr)
                return 1
        else:
            # "Previous" = highest-revision RS that is NOT the current
            # template's RS (named <deploy>-<template hash> by the
            # controller). A rollback reuses the old RS without
            # re-numbering it, so rss[-2] would make undo-after-undo a
            # no-op; kubectl's undo/undo toggles between the last two
            # templates.
            current_rs = f"{name}-{template_hash(dep.spec.template)}"
            target = next(
                (rs for rs in reversed(rss)
                 if rs.metadata.name != current_rs), None)
            if target is None:
                print("no previous revision to roll back to", file=sys.stderr)
                return 1
        template = target.spec.template
        # Strip the controller-owned hash label before re-submitting.
        template.metadata.labels = {
            k: v for k, v in template.metadata.labels.items()
            if k != TEMPLATE_HASH_LABEL}
        # Read-modify-write retried on conflict: the deployment
        # controller updates status concurrently.
        for attempt in range(20):
            dep.spec.template = template
            try:
                await client.update(dep)
                break
            except errors.ConflictError:
                if attempt == 19:
                    raise
                await asyncio.sleep(0.05)
                dep = await client.get("deployments", ns, name)
        rev = target.metadata.annotations.get(REVISION_ANNOTATION, "?")
        print(f"deployment {name!r} rolled back to revision {rev}")
        return 0
    finally:
        await client.close()


async def cmd_create(args) -> int:
    """``ktl create configmap|secret NAME --from-literal/--from-file``
    and ``ktl create namespace NAME`` (the reference's imperative
    creators, pkg/kubectl/cmd/create_*.go)."""
    import base64 as b64
    client = make_client(args)
    try:
        data: dict = {}

        def put(key, value, source):
            if not key or "/" in key:
                print(f"Error: {source}: invalid key {key!r}",
                      file=sys.stderr)
                return False
            if key in data:
                # kubectl parity: silent last-wins would ship a
                # configmap missing data the user explicitly passed.
                print(f"Error: {source}: key {key!r} already exists",
                      file=sys.stderr)
                return False
            data[key] = value
            return True

        for lit in args.from_literal or []:
            k, eq, v = lit.partition("=")
            if not eq or not k:
                print(f"Error: --from-literal wants KEY=VALUE, got "
                      f"{lit!r}", file=sys.stderr)
                return 1
            if not put(k, v, f"--from-literal {lit!r}"):
                return 1
        for path in args.from_file or []:
            # kubectl: KEY=path, or bare path (key = basename). A bare
            # path may itself contain '=': treat it as KEY=path only
            # when the would-be key looks like a key (no separators).
            key, eq, fpath = path.partition("=")
            if not eq or not key or "/" in key or os.sep in key:
                key, fpath = os.path.basename(path), path
            try:
                with open(fpath, "rb") as f:
                    raw = f.read()
            except OSError as e:
                print(f"Error: --from-file {fpath}: {e}", file=sys.stderr)
                return 1
            if not put(key, raw, f"--from-file {path!r}"):
                return 1
        if args.kind == "namespace":
            if data:
                print("Error: namespace takes no --from-* flags",
                      file=sys.stderr)
                return 1
            await client.create(t.Namespace(
                metadata=ObjectMeta(name=args.name)))
            print(f"namespace/{args.name} created")
            return 0
        if args.kind == "configmap":
            cm_data = {}
            for k, v in data.items():
                if isinstance(v, bytes):
                    try:
                        v = v.decode()
                    except UnicodeDecodeError:
                        print(f"Error: --from-file {k!r} is not UTF-8; "
                              f"use a secret for binary data",
                              file=sys.stderr)
                        return 1
                cm_data[k] = v
            await client.create(t.ConfigMap(
                metadata=ObjectMeta(name=args.name,
                                    namespace=args.namespace),
                data=cm_data))
            print(f"configmap/{args.name} created")
            return 0
        sec_data = {
            k: b64.b64encode(v if isinstance(v, bytes)
                             else v.encode()).decode()
            for k, v in data.items()}
        await client.create(t.Secret(
            metadata=ObjectMeta(name=args.name, namespace=args.namespace),
            data=sec_data))
        print(f"secret/{args.name} created")
        return 0
    finally:
        await client.close()


async def cmd_run(args) -> int:
    """``ktl run NAME --image=IMG`` — imperative pod (default) or, with
    ``--restart=Always``, a Deployment (reference: kubectl run's
    generator selection in pkg/kubectl/run.go)."""
    from ..api import workloads as w
    from ..api.selectors import LabelSelector
    client = make_client(args)
    try:
        labels = {"run": args.name}
        for e in args.env or []:
            if "=" not in e:
                print(f"Error: --env wants KEY=VALUE, got {e!r}",
                      file=sys.stderr)
                return 1
        container = t.Container(
            name=args.name, image=args.image,
            command=list(args.cmd or []),
            env=[t.EnvVar(name=k, value=v) for k, v in
                 (e.split("=", 1) for e in args.env or [])])
        if args.port:
            container.ports = [t.ContainerPort(container_port=args.port)]
        if args.restart == "Always":
            dep = w.Deployment(
                metadata=ObjectMeta(name=args.name, namespace=args.namespace,
                                    labels=dict(labels)),
                spec=w.DeploymentSpec(
                    replicas=args.replicas,
                    selector=LabelSelector(match_labels=dict(labels)),
                    template=t.PodTemplateSpec(
                        metadata=ObjectMeta(labels=dict(labels)),
                        spec=t.PodSpec(containers=[container]))))
            await client.create(dep)
            print(f"deployment/{args.name} created")
        else:
            pod = t.Pod(
                metadata=ObjectMeta(name=args.name, namespace=args.namespace,
                                    labels=dict(labels)),
                spec=t.PodSpec(containers=[container],
                               restart_policy=args.restart))
            await client.create(pod)
            print(f"pod/{args.name} created")
        return 0
    finally:
        await client.close()


async def cmd_expose(args) -> int:
    """``ktl expose deployment NAME --port=P`` — Service from a
    workload's selector (reference: kubectl expose / service
    generators)."""
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        obj = await client.get(plural, args.namespace, args.name)
        if plural == "pods":
            selector = dict(obj.metadata.labels)
        else:
            raw_sel = getattr(obj.spec, "selector", None)
            if isinstance(raw_sel, dict):  # Service-style plain map
                selector = dict(raw_sel)
            elif raw_sel is not None and hasattr(raw_sel, "match_labels"):
                selector = dict(raw_sel.match_labels)
                if not selector and getattr(raw_sel, "match_expressions",
                                            None):
                    print(f"Error: {plural}/{args.name} selects only by "
                          f"expressions; a Service needs equality labels",
                          file=sys.stderr)
                    return 1
            else:
                selector = {}
        if not selector:
            print(f"Error: {plural}/{args.name} has no selector/labels "
                  f"to expose", file=sys.stderr)
            return 1
        svc = t.Service(
            metadata=ObjectMeta(name=args.service_name or args.name,
                                namespace=args.namespace,
                                labels=dict(obj.metadata.labels)),
            spec=t.ServiceSpec(
                selector=selector,
                type=args.type,
                ports=[t.ServicePort(
                    port=args.port,
                    target_port=args.target_port or args.port)]))
        await client.create(svc)
        print(f"service/{svc.metadata.name} exposed")
        return 0
    finally:
        await client.close()


async def cmd_autoscale(args) -> int:
    """``ktl autoscale deployment NAME --min --max [--cpu-percent]`` —
    creates an HPA targeting the workload (reference: kubectl
    autoscale)."""
    from ..api import workloads as w
    client = make_client(args)
    try:
        plural = resolve_plural(args.resource)
        obj = await client.get(plural, args.namespace, args.name)
        if args.max < max(args.min, 1):
            print("Error: --max must be >= --min and >= 1",
                  file=sys.stderr)
            return 1
        hpa = w.HorizontalPodAutoscaler(
            metadata=ObjectMeta(name=args.name, namespace=args.namespace),
            spec=w.HorizontalPodAutoscalerSpec(
                scale_target_ref=w.CrossVersionObjectReference(
                    kind=obj.kind or "Deployment", name=args.name),
                min_replicas=args.min, max_replicas=args.max,
                target_cpu_utilization_percentage=args.cpu_percent))
        await client.create(hpa)
        print(f"horizontalpodautoscaler/{args.name} autoscaled")
        return 0
    finally:
        await client.close()


# -- kubeadm analog: token management + join -------------------------------

async def cmd_token(args) -> int:
    """``ktl token create|list|delete`` (kubeadm token analog; the
    secrets live in kube-system as bootstrap.kubernetes.io/token)."""
    from ..apiserver.bootstrap import (NODES_NAMESPACE,
                                       SECRET_TYPE_BOOTSTRAP,
                                       generate_token,
                                       make_bootstrap_secret)
    client = make_client(args)
    try:
        if args.action == "create":
            token = generate_token()
            await client.create(make_bootstrap_secret(
                token, ttl_seconds=args.ttl * 3600,
                description=args.description))
            print(token)
            return 0
        if args.action == "list":
            secrets, _ = await client.list("secrets", NODES_NAMESPACE)
            import base64 as b64
            rows = [("TOKEN-ID", "EXPIRES", "DESCRIPTION")]
            for s in secrets:
                if s.type != SECRET_TYPE_BOOTSTRAP:
                    continue

                def dec(k, s=s):
                    # Malformed fields render as <invalid>, same
                    # fail-soft stance as the server-side _field().
                    try:
                        return b64.b64decode(
                            s.data.get(k, ""), validate=True).decode()
                    except Exception:  # noqa: BLE001
                        return "<invalid>"
                rows.append((dec("token-id"), dec("expiration"),
                             dec("description") if "description" in s.data
                             else ""))
            for row in rows:
                print(f"{row[0]:<10} {row[1]:<34} {row[2]}")
            return 0
        # delete
        await client.delete("secrets", NODES_NAMESPACE,
                            f"bootstrap-token-{args.token_id}")
        print(f"bootstrap token {args.token_id!r} deleted")
        return 0
    finally:
        await client.close()


async def cmd_join(args) -> int:
    """``ktl join --server URL --token id.secret`` — exchange the
    bootstrap token for a node credential and run a node agent against
    the remote apiserver (kubeadm join analog; multi-host path)."""
    import socket as socketlib

    import aiohttp

    from ..node.agent import NodeAgent
    from ..node.devicemanager import DeviceManager
    from ..node.eviction import EvictionManager
    from ..node.runtime import ProcessRuntime

    server = load_server(args)
    node_name = args.name or socketlib.gethostname().lower()
    # Private by default: pod volumes (decoded Secrets) land here —
    # never a predictable world-readable /tmp path.
    node_dir = args.data_dir or os.path.join(
        os.path.expanduser("~/.ktl"), "nodes", node_name)
    os.makedirs(node_dir, mode=0o700, exist_ok=True)
    os.chmod(node_dir, 0o700)  # pre-existing dirs tightened too

    # 0. TLS discovery (kubeadm discovery-token flow): fetch the
    # cluster CA over an unverified-yet-encrypted channel, check it
    # against the --ca-hash pin, THEN trust it for everything after.
    ca_file = client_cert = client_key = ""
    if server.startswith("https://"):
        from ..apiserver.certs import (client_ssl_context, fingerprint_pem,
                                       make_csr_pem)
        if not args.ca_hash and not args.insecure_skip_ca_verification:
            # kubeadm refuses unpinned discovery without an explicit
            # opt-in; silent trust-on-first-use would hand the
            # bootstrap token to any MITM on the join path.
            print("ktl join over https needs --ca-hash sha256:<hex> "
                  "(printed by `ktl up`), or the explicit "
                  "--insecure-skip-ca-verification opt-in",
                  file=sys.stderr)
            return 1
        async with aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(ssl=False)) as sess:
            resp = await sess.get(f"{server}/bootstrap/v1/ca")
            if resp.status != 200:
                print(f"CA fetch failed ({resp.status})", file=sys.stderr)
                return 1
            info = await resp.json()
        # Hash what we RECEIVED — a server-asserted fingerprint would
        # let a MITM echo the real cluster's pin for its own CA.
        fp = fingerprint_pem(info["ca_pem"].encode())
        if args.ca_hash and args.ca_hash != fp:
            print(f"CA fingerprint mismatch: received={fp} "
                  f"pin={args.ca_hash} — refusing to join",
                  file=sys.stderr)
            return 1
        if not args.ca_hash:
            print(f"WARNING: trusting cluster CA without verification "
                  f"(--insecure-skip-ca-verification): {fp}")
        ca_file = os.path.join(node_dir, "ca.crt")
        with open(ca_file, "w") as f:
            f.write(info["ca_pem"])
        # TLS bootstrap: key stays local, only the CSR travels.
        client_key = os.path.join(node_dir, "node.key")
        csr = make_csr_pem(client_key, f"system:node:{node_name}")
        # CA-fingerprint-pinned (checked above) — hostname verification
        # stays off: the user-supplied --server address is routinely a
        # routable IP absent from the apiserver cert's SANs, and the
        # pin already binds the peer to the cluster CA.
        join_ctx = client_ssl_context(ca_file, check_hostname=False)
        async with aiohttp.ClientSession() as sess:
            resp = await sess.post(
                f"{server}/bootstrap/v1/sign-csr",
                json={"node_name": node_name, "csr_pem": csr.decode()},
                headers={"Authorization": f"Bearer {args.token}"},
                ssl=join_ctx)
            if resp.status != 200:
                print(f"CSR signing failed ({resp.status}): "
                      f"{(await resp.text())[:200]}", file=sys.stderr)
                return 1
            signed = await resp.json()
        client_cert = os.path.join(node_dir, "node.crt")
        with open(client_cert, "w") as f:
            f.write(signed["cert_pem"])
        print(f"node certificate minted for {signed['user']}")
        # Node SERVING cert (kubelet serving-cert CSR flow): the node
        # server refuses plain HTTP under cluster TLS — exec on this
        # host must not be open to anyone who can reach the port.
        serving_key = os.path.join(node_dir, "node-serving.key")
        serving_csr = make_csr_pem(serving_key, f"system:node:{node_name}")
        from ..apiserver.certs import local_host_sans
        claimed = local_host_sans([node_name])
        async with aiohttp.ClientSession() as sess:
            resp = await sess.post(
                f"{server}/bootstrap/v1/sign-csr",
                json={"node_name": node_name,
                      "csr_pem": serving_csr.decode(),
                      "usage": "serving", "sans": claimed},
                headers={"Authorization": f"Bearer {args.token}"},
                ssl=join_ctx)
            if resp.status != 200:
                print(f"serving-cert signing failed ({resp.status}): "
                      f"{(await resp.text())[:200]}", file=sys.stderr)
                return 1
            serving_signed = await resp.json()
        serving_cert = os.path.join(node_dir, "node-serving.crt")
        with open(serving_cert, "w") as f:
            f.write(serving_signed["cert_pem"])

    # 1. Bootstrap-token -> durable node credential (token beside the
    # cert: agents authenticate with either; the response also carries
    # the cluster DNS address).
    ssl_arg = {}
    if ca_file:
        ssl_arg["ssl"] = join_ctx
    async with aiohttp.ClientSession() as sess:
        resp = await sess.post(
            f"{server}/bootstrap/v1/node-credentials",
            json={"node_name": node_name},
            headers={"Authorization": f"Bearer {args.token}"}, **ssl_arg)
        if resp.status != 200:
            # Body may be anything (older server's 404 page, proxy
            # error) — never crash on it.
            try:
                body = await resp.json()
                detail = body.get("message", body)
            except Exception:  # noqa: BLE001
                detail = (await resp.text())[:200]
            print(f"join rejected ({resp.status}): {detail}", file=sys.stderr)
            return 1
        body = await resp.json()
    cred = body["token"]
    print(f"joined as {body['user']}")

    # 2. Run the node agent with the minted identity (cert-first).
    # Same trust model as the join itself: CA-fingerprint-pinned, so
    # hostname verification stays off for the user-supplied --server.
    client = RESTClient(server, token=cred, ca_file=ca_file,
                        client_cert=client_cert, client_key=client_key,
                        check_hostname=False)
    runtime = ProcessRuntime(node_dir)
    dm = None
    if args.real_tpu or args.tpu_chips:
        plugin_dir = os.path.join(node_dir, "device-plugins")
        if args.real_tpu:
            from ..deviceplugin.tpu_plugin import TpuDevicePlugin
            plugin = TpuDevicePlugin(slice_id=f"slice-{node_name}")
        else:
            from ..deviceplugin.stub import StubTpuPlugin, make_topology
            plugin = StubTpuPlugin(make_topology(
                mesh_shape=(args.tpu_chips, 1, 1), slice_id=node_name))
        plugin.serve(os.path.join(plugin_dir, "tpu.sock"))
        dm = DeviceManager(plugin_dir)
    agent = NodeAgent(client, node_name, runtime, device_manager=dm,
                      eviction=EvictionManager(), server_port=0)
    rotator = None
    if ca_file:
        from ..apiserver.certs import CertPair, server_ssl_context
        agent.server_tls = server_ssl_context(
            CertPair(serving_cert, serving_key), ca_file)

        # Certificate rotation (kubelet pkg/kubelet/certificate): the
        # agent renews its own client + serving certs through the CSR
        # endpoint before they expire; live contexts reload in place.
        from ..node.certrotation import CertRotator

        def reload_tls():
            client.rebuild_ssl(ca_file, client_cert, client_key,
                               check_hostname=False)
            # Server context: reload the pair in place — new
            # handshakes pick it up, existing connections finish.
            agent.server_tls.load_cert_chain(serving_cert, serving_key)

        rotator = CertRotator(server, node_name, ca_file,
                              client_cert, client_key,
                              serving_cert=serving_cert,
                              serving_key=serving_key,
                              on_rotated=reload_tls)
    # Cluster DNS rides the credential response (see _node_credentials)
    # so pods here resolve rank hostnames exactly like local-node pods.
    agent.dns_server = body.get("dns_server", "")
    await agent.start()
    if rotator is not None:
        rotator.start()
    print(f"node agent {node_name!r} running against {server} "
          "(SIGINT to leave)")
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # same guard as cmd_up
            signal.signal(sig, lambda *_: stop.set())
    await stop.wait()
    if rotator is not None:
        await rotator.stop()
    await agent.stop()
    await client.close()
    return 0


# -- argument parsing ------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ktl",
                                description="TPU-cluster CLI (kubectl analog)")
    p.add_argument("--server", default="", help="apiserver URL")
    p.add_argument("--as", dest="as_user", default="",
                   help="impersonate this user (RBAC 'impersonate' verb)")
    p.add_argument("--as-group", dest="as_group", action="append",
                   default=[], help="impersonate this group (repeatable)")
    sub = p.add_subparsers(dest="command", required=True)

    def add(name, fn, **kw):
        sp = sub.add_parser(name, **kw)
        sp.set_defaults(fn=fn)
        # default=SUPPRESS so a subcommand-level flag absence does not
        # clobber the top-level --server value already parsed.
        sp.add_argument("--server", default=argparse.SUPPRESS,
                        help=argparse.SUPPRESS)
        sp.add_argument("--as", dest="as_user", default=argparse.SUPPRESS,
                        help=argparse.SUPPRESS)
        # Separate dest: subparsers OVERWRITE parent namespace values,
        # so appending to as_group here would silently drop top-level
        # --as-group entries; make_client merges both dests.
        sp.add_argument("--as-group", dest="as_group_sub", action="append",
                        default=argparse.SUPPRESS, help=argparse.SUPPRESS)
        return sp

    sp = add("get", cmd_get, help="list or get resources")
    sp.add_argument("resource")
    sp.add_argument("name", nargs="?", default="")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("-l", "--selector", default="")
    sp.add_argument("-o", "--output", default="",
                    help="''|wide|json|yaml|jsonpath=TEMPLATE|"
                         "custom-columns=H:expr,...")
    sp.add_argument("--sort-by", default="",
                    help="jsonpath expression to sort the list by, "
                         "e.g. {.metadata.name}")
    sp.add_argument("-w", "--watch", action="store_true", default=False,
                    help="stream changes after the initial list")

    sp = add("explain", cmd_explain,
             help="field documentation for a resource, e.g. "
                  "'ktl explain pods.spec.containers'")
    sp.add_argument("resource",
                    help="resource or dotted field path "
                         "(pods | pods.spec.tolerations)")

    sp = add("describe", cmd_describe, help="show one object in detail")
    sp.add_argument("resource")
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("apply", cmd_apply, help="create-or-update from manifest")
    sp.add_argument("-f", "--filename", required=True,
                    help="YAML/JSON file ('-' = stdin)")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("-l", "--selector", default="",
                    help="label selector bounding --prune")
    sp.add_argument("--prune", action="store_true", default=False,
                    help="delete selector-matching ktl-applied objects "
                         "absent from this file set")

    sp = add("edit", cmd_edit,
             help="edit a live object in $EDITOR (KTL_EDITOR wins)")
    sp.add_argument("resource")
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("delete", cmd_delete, help="delete resources")
    sp.add_argument("resource", nargs="?", default="")
    sp.add_argument("name", nargs="?", default="")
    sp.add_argument("-f", "--filename", default="")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--cascade", default="background",
                    choices=sorted(_CASCADE),
                    help="dependent handling: background (GC cascades "
                         "after), foreground (dependents first), orphan "
                         "(dependents survive)")

    sp = add("logs", cmd_logs, help="pod container logs")
    sp.add_argument("pod")
    sp.add_argument("-c", "--container", default="")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--tail", type=int, default=0)
    sp.add_argument("-p", "--previous", action="store_true",
                    default=False,
                    help="logs of the previous container instance")
    sp.add_argument("-f", "--follow", action="store_true", default=False,
                    help="stream new output until the container exits")

    sp = add("scale", cmd_scale, help="set replicas")
    sp.add_argument("resource")
    sp.add_argument("name")
    sp.add_argument("--replicas", type=int, required=True)
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("patch", cmd_patch, help="patch an object in place")
    sp.add_argument("resource")
    sp.add_argument("name")
    sp.add_argument("-p", "--patch", required=True,
                    help="patch body as JSON")
    sp.add_argument("--type", default="strategic",
                    choices=["strategic", "merge", "json"],
                    help="strategic merge (default), RFC 7386 merge, "
                         "or RFC 6902 json ops")
    sp.add_argument("-n", "--namespace", default="default")

    for vname, vfn in (("label", cmd_label), ("annotate", cmd_annotate)):
        sp = add(vname, vfn,
                 help=f"{vname} objects (key=value sets, key- removes)")
        sp.add_argument("resource")
        sp.add_argument("name")
        sp.add_argument("pairs", nargs="+",
                        help="key=value to set, key- to remove")
        sp.add_argument("--overwrite", action="store_true", default=False,
                        help="allow replacing existing values")
        sp.add_argument("-n", "--namespace", default="default")

    sp = add("auth", cmd_auth_can_i,
             help="check API access (auth can-i VERB RESOURCE [NAME])")
    sp.add_argument("subverb", choices=["can-i"],
                    help="only can-i is supported")
    sp.add_argument("verb")
    sp.add_argument("resource")
    sp.add_argument("name", nargs="?", default="")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("-q", "--quiet", action="store_true", default=False,
                    help="suppress the denial reason on stderr")

    sp = add("wait", cmd_wait,
             help="block until a condition holds or an object is gone")
    sp.add_argument("resource")
    sp.add_argument("name")
    sp.add_argument("--for", dest="wait_for", required=True,
                    help="condition=Type[=Status] or delete")
    sp.add_argument("--timeout", type=float, default=60.0)
    sp.add_argument("-n", "--namespace", default="default")

    for name, fn in (("cordon", cmd_cordon), ("uncordon", cmd_uncordon)):
        sp = add(name, fn, help=f"{name} a node")
        sp.add_argument("node")

    sp = add("taint", cmd_taint, help="add/remove node taints")
    sp.add_argument("resource", choices=["nodes", "node", "no"],
                    help="only nodes are taintable")
    sp.add_argument("node")
    sp.add_argument("taint",
                    help="key=value:Effect to add, key:Effect- or "
                         "key- to remove")
    sp.add_argument("--overwrite", action="store_true", default=False)

    sp = add("set", cmd_set_image, help="set image on a workload")
    sp.add_argument("subcommand", choices=["image"])
    sp.add_argument("target", help="deployment/NAME (or sts/ds/rs/pod)")
    sp.add_argument("images", nargs="+", help="container=image ...")
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("drain", cmd_drain, help="cordon + evict all pods")
    sp.add_argument("node")
    sp.add_argument("--grace-period", type=int, default=5)
    sp.add_argument("--ignore-daemonsets", action="store_true",
                    help="skip DaemonSet-managed pods instead of aborting")
    sp.add_argument("--force", action="store_true",
                    help="evict pods that no controller would recreate")
    sp.add_argument("--timeout", type=float, default=60.0,
                    help="seconds to keep retrying PDB-blocked evictions")
    sp.add_argument("--disable-eviction", action="store_true",
                    help="raw-delete instead of the PDB-gated Eviction API")

    sp = add("top", cmd_top, help="node/pod/chip stats "
                                  "('nodes'/'pods' = TPU telemetry views)")
    sp.add_argument("node", nargs="?", default="")

    sp = add("migrations", cmd_migrations,
             help="live gang-migration rounds and recent outcomes")
    sp.add_argument("-n", "--namespace", default="",
                    help="namespace ('' = all namespaces)")

    sp = add("trace", cmd_trace,
             help="render a pod's (or gang's) ktrace lifecycle timeline")
    sp.add_argument("kind", choices=["pod", "gang"])
    sp.add_argument("name")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("-o", "--output", default="", help="''|json")

    sp = add("query", cmd_query,
             help="PromQL-lite query over the kmon metrics TSDB")
    sp.add_argument("expr", help="e.g. 'up == 0', "
                                 "'rate(tpu_ici_tx_bytes[30s])'")
    sp.add_argument("--range", default="",
                    help="evaluate over a trailing window (e.g. 5m) "
                         "instead of one instant")
    sp.add_argument("--step", default="",
                    help="range resolution (default: scrape interval)")
    sp.add_argument("-o", "--output", default="", help="''|json")

    sp = add("alerts", cmd_alerts,
             help="active kmon alerts (pending + firing)")
    sp.add_argument("-o", "--output", default="", help="''|json")

    sp = add("dash", cmd_dash,
             help="text sparkline dashboard over the kmon recording "
                  "rules")
    sp.add_argument("--range", default="5m",
                    help="dash window (default 5m)")

    add("api-resources", cmd_api_resources, help="list server resources")
    add("version", cmd_version, help="client+server version")

    sp = add("attach", cmd_attach,
             help="stream a running container's output (Ctrl-C detaches)")
    sp.add_argument("pod")
    sp.add_argument("-c", "--container", default="")
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("cp", cmd_cp,
             help="copy files to/from a container (pod:path <-> local)")
    sp.add_argument("src", help="pod:path or local path")
    sp.add_argument("dst", help="local path or pod:path")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("-c", "--container", default="")

    sp = add("exec", cmd_exec, help="run a command in a container")
    sp.add_argument("pod")
    sp.add_argument("cmd", nargs="+", help="command (prefix with -- )")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("-c", "--container", default="")
    sp.add_argument("-i", "--stdin", action="store_true", default=False,
                    help="interactive: stream local stdin to the "
                         "command over a WebSocket (use with -t/-it)")
    sp.add_argument("-t", "--tty", action="store_true", default=False,
                    help="accepted for kubectl parity (streams are "
                         "pipe-based; no pty allocation)")
    sp.add_argument("--timeout", type=float, default=None,
                    help="kill the command after this many seconds "
                         "(default 30, or 3600 with -i)")

    sp = add("port-forward", cmd_port_forward,
             help="tunnel a local port to a pod port")
    sp.add_argument("pod")
    sp.add_argument("ports", help="LOCAL[:REMOTE] (0 = pick a free port)")
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("rollout", cmd_rollout, help="status/history/undo a rollout")
    sp.add_argument("action", choices=["status", "history", "undo",
                                       "pause", "resume"])
    sp.add_argument("target", help="deployment/<name>")
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--to-revision", type=int, default=0)
    sp.add_argument("--timeout", type=float, default=60.0,
                    help="status wait bound (seconds)")

    sp = add("create", cmd_create,
             help="imperative create: configmap|secret|namespace")
    sp.add_argument("kind", choices=["configmap", "secret", "namespace"])
    sp.add_argument("name")
    sp.add_argument("--from-literal", action="append", default=[],
                    help="KEY=VALUE (repeatable)")
    sp.add_argument("--from-file", action="append", default=[],
                    help="[KEY=]path (repeatable; key defaults to "
                         "the basename)")
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("run", cmd_run, help="run an image as a pod (or deployment)")
    sp.add_argument("name")
    sp.add_argument("--image", required=True)
    sp.add_argument("-n", "--namespace", default="default")
    sp.add_argument("--restart", default="Never",
                    choices=["Never", "OnFailure", "Always"],
                    help="Always creates a Deployment")
    sp.add_argument("--replicas", type=int, default=1,
                    help="replicas for --restart=Always")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--env", action="append", default=[],
                    help="KEY=VALUE (repeatable)")
    sp.add_argument("cmd", nargs="*", default=[],
                    help="command to run (after --)")

    sp = add("expose", cmd_expose,
             help="create a Service for a workload's selector")
    sp.add_argument("resource", help="deployment|replicaset|pod|...")
    sp.add_argument("name")
    sp.add_argument("--port", type=int, required=True)
    sp.add_argument("--target-port", type=int, default=0)
    sp.add_argument("--type", default="ClusterIP",
                    choices=["ClusterIP", "NodePort"])
    sp.add_argument("--name", dest="service_name", default="",
                    help="service name (defaults to the workload's)")
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("autoscale", cmd_autoscale,
             help="create an HPA for a workload")
    sp.add_argument("resource")
    sp.add_argument("name")
    sp.add_argument("--min", type=int, default=1)
    sp.add_argument("--max", type=int, required=True)
    sp.add_argument("--cpu-percent", type=int, default=80)
    sp.add_argument("-n", "--namespace", default="default")

    sp = add("token", cmd_token, help="manage bootstrap tokens (kubeadm analog)")
    sp.add_argument("action", choices=["create", "list", "delete"])
    sp.add_argument("token_id", nargs="?", default="",
                    help="token id (delete)")
    sp.add_argument("--ttl", type=float, default=24.0,
                    help="token lifetime in hours (create)")
    sp.add_argument("--description", default="")

    sp = add("join", cmd_join, help="join this host as a node (kubeadm join)")
    sp.add_argument("--token", required=True, help="bootstrap token id.secret")
    sp.add_argument("--name", default="", help="node name (default: hostname)")
    sp.add_argument("--tpu-chips", type=int, default=0,
                    help="serve a stub plugin with N chips")
    sp.add_argument("--real-tpu", action="store_true", default=False,
                    help="probe real TPU hardware")
    sp.add_argument("--data-dir", default="")
    sp.add_argument("--ca-hash", default="",
                    help="sha256:<hex> pin for the cluster CA "
                         "(kubeadm discovery-token-ca-cert-hash)")
    sp.add_argument("--insecure-skip-ca-verification", action="store_true",
                    default=False,
                    help="join without a CA pin (MITM-exposed; the "
                         "kubeadm unsafe-skip flag analog)")

    sp = add("up", cmd_up, help="run a single-process cluster")
    # SUPPRESS defaults: flag PRESENCE marks it explicitly passed, so
    # config_from_args can layer flags over --config file values
    # without default-value sentinels (real defaults live in
    # cluster/config.py ClusterConfig).
    S = argparse.SUPPRESS
    sp.add_argument("--config", default="",
                    help="ClusterConfig YAML (componentconfig analog); "
                         "explicit flags override file values")
    sp.add_argument("--insecure", action="store_true", default=False,
                    help="serve plaintext HTTP (default: TLS-only from "
                         "a cluster CA under <data-dir>/pki)")
    sp.add_argument("--nodes", type=int, default=S)
    sp.add_argument("--tpu-chips", type=int, default=S,
                    help="stub chips per node")
    sp.add_argument("--real-tpu", action="store_true", default=S,
                    help="probe real hardware on node-0")
    sp.add_argument("--host", default=S)
    sp.add_argument("--port", type=int, default=S)
    sp.add_argument("--data-dir", default=S)
    sp.add_argument("--durable", action="store_true", default=S,
                    help="persist state (WAL+snapshot) under --data-dir")
    sp.add_argument("--feature-gates", default=S,
                    help="comma-separated Gate=true|false overrides")
    sp.add_argument("--authorization-mode", default=S,
                    choices=["AlwaysAllow", "RBAC"])
    sp.add_argument("--audit-log", default=S,
                    help="write request audit JSONL to this path")
    sp.add_argument("--audit-policy", default=S,
                    help="per-rule audit policy file (YAML/JSON: "
                         "default_level + rules of level/users/verbs/"
                         "resources/namespaces)")
    sp.add_argument("--audit-webhook", default=S,
                    help="POST batched audit events to this URL")
    sp.add_argument("--scheduler-policy", default=S,
                    help="scheduler Policy file (YAML/JSON) selecting "
                         "predicates, priority weights, and extenders")
    sp.add_argument("--encryption-provider-config", default=S,
                    help="EncryptionConfig file: encrypt listed resources "
                         "(e.g. secrets) at rest in the WAL/snapshot; "
                         "first provider writes, all providers read")

    return p


def main(argv: Optional[list[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    args = None
    if "--" in argv:
        # argparse cannot fill a trailing nargs="*" positional once
        # options sit between it and the subcommand (bpo-13922):
        # ``run NAME --image IMG -- CMD...`` dies with "unrecognized
        # arguments". Trial-parse the head and hand the tail to verbs
        # that take a command; anything else falls through to the
        # plain parse (exec's contiguous ``NAME -- CMD`` form already
        # works there).
        import contextlib
        import io
        i = argv.index("--")
        head, tail = argv[:i], argv[i + 1:]
        try:
            with contextlib.redirect_stderr(io.StringIO()):
                cand = build_parser().parse_args(head)
        except SystemExit:
            cand = None
        if cand is not None and hasattr(cand, "cmd"):
            cand.cmd = list(cand.cmd or []) + tail
            args = cand
    if args is None:
        args = build_parser().parse_args(argv)
    try:
        return asyncio.run(args.fn(args))
    except errors.StatusError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 130
    except Exception as e:  # noqa: BLE001 — bad jsonpath/promql input
        # must print cleanly; every other exception stays a loud
        # traceback
        from ..monitoring.promql import PromQLError
        from .jsonpath import JsonPathError
        if isinstance(e, (JsonPathError, PromQLError)):
            print(f"Error: {e}", file=sys.stderr)
            return 1
        raise


if __name__ == "__main__":
    sys.exit(main())
