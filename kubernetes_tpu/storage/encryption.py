"""Encryption at rest for stored API objects (secrets by default).

Reference: ``staging/src/k8s.io/apiserver/pkg/storage/value/`` — value
transformers (identity, aescbc, aesgcm, secretbox) selected per
resource by ``--experimental-encryption-provider-config``, an
``EncryptionConfig`` document where the FIRST provider encrypts new
writes and every listed provider can decrypt (key rotation = prepend a
new key, restart, rewrite objects, drop the old key).

TPU-native placement differs deliberately: the reference transforms at
the etcd-client boundary because etcd is a separate process reachable
over a network; this framework's MVCC store is embedded, so "at rest"
means the WAL and snapshot on disk. Values are enveloped at the
persistence boundary (``mvcc.py _append_event / snapshot / _load``)
and the in-memory store stays plaintext — get/list/watch never pay a
decrypt, and a stolen disk yields ciphertext only.

Envelope (JSON-friendly, self-describing)::

    {"__enc__": {"p": "aesgcm", "kid": "key1", "n": "<b64>", "d": "<b64>"}}

Plaintext values read back unchanged (migration: enabling encryption
on an existing data dir re-encrypts each object as it is next
written; calling ``MVCCStore.snapshot()`` does it eagerly — the
snapshot writer passes every stored value through the transformer).

Config file (reference EncryptionConfig shape)::

    kind: EncryptionConfig
    resources:
      - resources: [secrets]
        providers:
          - aesgcm:
              keys:
                - name: key1
                  secret: <base64 16/24/32-byte key>
          - identity: {}
"""
from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass, field

ENVELOPE_FIELD = "__enc__"


class DecryptError(Exception):
    """Ciphertext present but no configured provider/key can open it —
    surfaced loudly at load: silently dropping objects would look like
    data loss, and passing ciphertext through would corrupt decoders."""


@dataclass
class _Key:
    name: str
    secret: bytes


class _AesProvider:
    """Shared key handling: AES key-size validation, kid-addressed key
    map, first key writes."""

    name = "aes"

    def __init__(self, keys: list[_Key]):
        if not keys:
            raise ValueError(f"{self.name}: at least one key required")
        for k in keys:
            if len(k.secret) not in (16, 24, 32):
                raise ValueError(
                    f"{self.name} key {k.name!r}: secret must be "
                    f"16/24/32 bytes, got {len(k.secret)}")
        self._keys = {k.name: k.secret for k in keys}
        self._write_key = keys[0]


class AesGcmProvider(_AesProvider):
    """AEAD (the provider to prefer). 12-byte random nonce per write;
    the envelope's ``kid`` selects the decrypt key directly — no
    trial decryption."""

    name = "aesgcm"

    def encrypt(self, plaintext: bytes) -> dict:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        nonce = os.urandom(12)
        ct = AESGCM(self._write_key.secret).encrypt(nonce, plaintext, None)
        return {"p": self.name, "kid": self._write_key.name,
                "n": base64.b64encode(nonce).decode(),
                "d": base64.b64encode(ct).decode()}

    def decrypt(self, env: dict) -> bytes | None:
        if env.get("p") != self.name:
            return None
        secret = self._keys.get(env.get("kid", ""))
        if secret is None:
            return None
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        return AESGCM(secret).decrypt(
            base64.b64decode(env["n"]), base64.b64decode(env["d"]), None)


class AesCbcProvider(_AesProvider):
    """CBC with PKCS7 (reference parity; aesgcm is the better choice —
    CBC has no integrity tag, kept for config compatibility)."""

    name = "aescbc"

    def encrypt(self, plaintext: bytes) -> dict:
        from cryptography.hazmat.primitives import padding
        from cryptography.hazmat.primitives.ciphers import (
            Cipher, algorithms, modes)
        iv = os.urandom(16)
        padder = padding.PKCS7(128).padder()
        padded = padder.update(plaintext) + padder.finalize()
        enc = Cipher(algorithms.AES(self._write_key.secret),
                     modes.CBC(iv)).encryptor()
        ct = enc.update(padded) + enc.finalize()
        return {"p": self.name, "kid": self._write_key.name,
                "n": base64.b64encode(iv).decode(),
                "d": base64.b64encode(ct).decode()}

    def decrypt(self, env: dict) -> bytes | None:
        if env.get("p") != self.name:
            return None
        secret = self._keys.get(env.get("kid", ""))
        if secret is None:
            return None
        from cryptography.hazmat.primitives import padding
        from cryptography.hazmat.primitives.ciphers import (
            Cipher, algorithms, modes)
        dec = Cipher(algorithms.AES(secret),
                     modes.CBC(base64.b64decode(env["n"]))).decryptor()
        padded = dec.update(base64.b64decode(env["d"])) + dec.finalize()
        unpadder = padding.PKCS7(128).unpadder()
        return unpadder.update(padded) + unpadder.finalize()


class IdentityProvider:
    """Plaintext passthrough. As the FIRST provider it disables
    encryption for new writes while later providers still decrypt old
    data (the reference's decrypt-only migration posture)."""

    name = "identity"

    def __init__(self, _keys=None):
        pass

    def encrypt(self, plaintext: bytes) -> dict | None:
        return None  # caller stores plaintext

    def decrypt(self, env: dict) -> bytes | None:
        return None  # envelopes are never identity's


_PROVIDERS = {p.name: p for p in (AesGcmProvider, AesCbcProvider,
                                  IdentityProvider)}


@dataclass
class Transformer:
    """Provider chain for one resource set: first provider writes,
    every provider gets a shot at reads."""

    providers: list = field(default_factory=list)

    def for_write(self, value: dict) -> dict:
        if not self.providers:
            return value
        first = self.providers[0]
        if isinstance(first, IdentityProvider):
            # identity first = encryption off: skip the per-write
            # serialization entirely, don't pay json.dumps only for
            # encrypt() to answer None (hot-path-cost finding).
            return value
        # Reached only with a real (non-identity) provider first:
        # encryption on means serialize-then-encrypt IS the write.
        env = first.encrypt(
            json.dumps(value, separators=(",", ":")).encode())  # tpuvet: ignore[hot-path-cost]
        if env is None:
            return value
        return {ENVELOPE_FIELD: env}

    def for_read(self, value: dict) -> dict:
        env = value.get(ENVELOPE_FIELD) if isinstance(value, dict) else None
        if env is None:
            return value  # plaintext (pre-encryption data, or identity)
        for p in self.providers:
            try:
                pt = p.decrypt(env)
            except Exception as e:  # noqa: BLE001 — InvalidTag, padding
                # Corrupt ciphertext or a key whose secret changed under
                # its kid: surface WITH context, not a raw crypto trace.
                raise DecryptError(
                    f"provider={env.get('p')!r} kid={env.get('kid')!r}: "
                    f"ciphertext failed to decrypt ({type(e).__name__}: "
                    f"{e}) — corrupted record, or the key's secret "
                    f"changed while keeping its name?") from e
            if pt is not None:
                try:
                    return json.loads(pt)
                except ValueError as e:
                    raise DecryptError(
                        f"provider={env.get('p')!r} kid={env.get('kid')!r}:"
                        f" decrypted bytes are not JSON ({e}) — wrong key "
                        f"under the right name?") from e
        raise DecryptError(
            f"no configured provider/key decrypts envelope "
            f"(provider={env.get('p')!r} kid={env.get('kid')!r}) — "
            f"was a rotation key dropped before rewriting old objects?")


def load_encryption_config(path: str) -> dict[str, Transformer]:
    """Parse an EncryptionConfig file into {key-prefix: Transformer}
    consumable by ``MVCCStore(transformers=...)``. Resource names are
    plurals; the registry stores under ``/registry/<plural>/``."""
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        raw = json.loads(text)
    else:
        import yaml
        raw = yaml.safe_load(text) or {}
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: document must be a mapping")
    if raw.get("kind", "EncryptionConfig") != "EncryptionConfig":
        raise ValueError(f"{path}: kind must be EncryptionConfig")
    out: dict[str, Transformer] = {}
    for i, entry in enumerate(raw.get("resources") or []):
        plurals = entry.get("resources") or []
        if not plurals:
            raise ValueError(f"{path}: resources[{i}]: empty resource list")
        providers = []
        for j, pconf in enumerate(entry.get("providers") or []):
            if not isinstance(pconf, dict) or len(pconf) != 1:
                raise ValueError(
                    f"{path}: resources[{i}].providers[{j}]: each entry "
                    f"is one provider mapping, e.g. 'aesgcm: {{keys: ...}}'")
            (pname, pbody), = pconf.items()
            cls = _PROVIDERS.get(pname)
            if cls is None:
                raise ValueError(
                    f"{path}: resources[{i}].providers[{j}]: unknown "
                    f"provider {pname!r} (known: {sorted(_PROVIDERS)})")
            keys = [
                _Key(name=k.get("name", ""),
                     secret=base64.b64decode(k.get("secret", "")))
                for k in (pbody or {}).get("keys") or []]
            for k in keys:
                if not k.name:
                    raise ValueError(
                        f"{path}: resources[{i}].providers[{j}]: every "
                        f"key needs a name (it becomes the envelope kid)")
            providers.append(cls(keys))
        if not providers:
            raise ValueError(f"{path}: resources[{i}]: no providers")
        tf = Transformer(providers)
        for plural in plurals:
            # First matching entry wins (reference transformer-chain
            # semantics): a plural repeated in a later stanza does not
            # silently change which providers write it.
            out.setdefault(f"/registry/{plural}/", tf)
    return out
