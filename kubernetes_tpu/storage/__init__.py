from .mvcc import MVCCStore, StoredObject, Watch, WatchEvent  # noqa: F401
