"""Embedded MVCC store with etcd3 semantics.

The reference stores all cluster state in etcd3 through
``staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go`` (``:152
Create``, ``:263 GuaranteedUpdate``) and fans watches out from
``etcd3/watcher.go:99``. There is no etcd binary in this environment, so
this module IS the storage layer: an in-process MVCC keyspace with the
same contract the apiserver depends on —

- a single monotonically-increasing **revision** stamped on every write;
- **create** fails if the key is live; **update/delete** take an
  expected mod-revision and fail with Conflict when stale (the
  optimistic-concurrency primitive under GuaranteedUpdate);
- **list** returns a consistent snapshot + the revision it was read at;
- **watch(prefix, from_rev)** replays history from ``from_rev``
  (exclusive) then streams live events, in revision order, with no gap
  between replay and live — the property informers rely on;
- **compaction** discards history and turns stale watches into
  GoneError (410), forcing a relist, exactly like etcd.

Durability: optional write-ahead log + snapshot. WAL records are
CRC32-framed JSON lines (``<crc32hex> <json>``); recovery replays the
longest valid prefix and TRUNCATES a torn/corrupt tail so later appends
never land mid-garbage (etcd's WAL does the same cut). ``fsync=`` picks
the durability/latency trade: ``"none"`` (flush per record, no fsync —
components are crash-only and resync from watch), ``"batch"``
(group-commit: an append fsyncs when ``fsync_batch`` records or
``fsync_interval`` seconds have accumulated since the last sync,
amortizing the cost the way etcd batches raft entries — the bound is
enforced on the append path, so an idle tail stays unsynced until the
next write or a quiesce point: ``close``/``snapshot``/``fsync_now``),
or ``"always"``. The WAL append path is also the
``wal`` chaos injection site (chaos/core.py): an injected torn/flipped/
lost record simulates a crash mid-write — the store captures the
durable-consistent state in ``pre_crash_state``, refuses further
writes, and recovery must reproduce that state exactly.

Concurrency: mutations take a process-wide lock (writes are tiny dict
ops); watch delivery crosses into asyncio via ``call_soon_threadsafe``
so the store can be driven from worker threads while informers live on
the event loop.
"""
from __future__ import annotations

import asyncio
import bisect
import json
import os
import threading as _threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from ..analysis import interleave, invariants, loopsan
from ..api import errors
from ..chaos import core as chaos
from ..metrics.registry import Counter
from ..util.lockdep import make_lock

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"
ERROR = "ERROR"
#: WAL/replication record kind for one committed transaction: N
#: sub-records under ONE CRC frame / ONE log entry. Never a watch
#: event type — events inside a batch keep their per-op kinds.
BATCH = "BATCH"

MVCC_TXN_COMMITS = Counter(
    "mvcc_txn_commits_total",
    "multi-op transactions committed (one WAL record / one watch "
    "round each)")
MVCC_TXN_OPS = Counter(
    "mvcc_txn_ops_total",
    "individual writes committed through multi-op transactions")


class TxnError(Exception):
    """One op of a :meth:`MVCCStore.txn` failed validation; NOTHING was
    committed. ``index`` is the offending op's position, ``error`` the
    per-op StatusError — callers split-commit around it."""

    def __init__(self, index: int, error: Exception):
        super().__init__(f"txn op {index}: {error}")
        self.index = index
        self.error = error


@dataclass
class WatchEvent:
    type: str = ADDED
    key: str = ""
    value: Optional[dict] = None
    #: Value before this event (for DELETED consumers needing the corpse).
    prev_value: Optional[dict] = None
    revision: int = 0


@dataclass
class StoredObject:
    key: str = ""
    value: dict = field(default_factory=dict)
    mod_revision: int = 0
    create_revision: int = 0


class Watch:
    """One watcher: a bounded queue bridged onto an asyncio loop.

    ``cancel()`` is idempotent; after cancel the stream ends with None.

    Backpressure (reference: the apiserver watch cache terminates
    watchers that cannot keep up rather than buffering unboundedly —
    the client relists and re-watches): when more than ``queue_limit``
    events are in flight, the watch is closed with ``overflowed`` set.
    """

    #: Sized to ride out a reference-scale create burst (30k pods
    #: arriving faster than a watcher drains while the shared core is
    #: busy): entries are references into the store log, so buffering
    #: is cheap, while an overflow costs the consumer a full relist —
    #: 30k typed decodes — and at density scale relist thrash.
    DEFAULT_QUEUE_LIMIT = 65536

    def __init__(self, store: "MVCCStore", prefix: str,
                 loop: asyncio.AbstractEventLoop,
                 queue_limit: int = DEFAULT_QUEUE_LIMIT,
                 start_revision: int = 0):
        self._store = store
        self.prefix = prefix
        #: Events at or below this revision are never delivered. On a
        #: single store live events always outrun it; on a REPLICATION
        #: FOLLOWER a watcher may resume from a revision the follower
        #: has not applied yet — the lagging entries arrive as "live"
        #: events and must not be re-delivered to a client that already
        #: saw them through the leader it listed against.
        self.start_revision = start_revision
        self._loop = loop
        self._queue: asyncio.Queue[Optional[WatchEvent]] = asyncio.Queue()
        self._cancelled = False
        self._queue_limit = queue_limit
        self._pending = 0
        self._pending_lock = make_lock("mvcc.WatchStream.pending")
        #: Set once the end-of-stream sentinel has been consumed; lets
        #: callers distinguish 'stream ended' from 'idle timeout'.
        self.closed = False
        #: True when the stream was closed because the consumer was too
        #: slow (the client must relist).
        self.overflowed = False
        #: Observability flag: the store compacted PAST this watch's
        #: start revision while it was attached. The stream itself is
        #: unaffected (replay already happened under the lock; queued
        #: events are references that survive the history trim) — only
        #: a RECONNECT from that old revision would now 410.
        self.compacted = False
        #: ``(index name, value)`` when subscribed through a dispatch
        #: index (see ``MVCCStore.register_watch_index``); None = plain
        #: prefix-scan delivery.
        self.index: Optional[tuple[str, str]] = None

    def _post(self, item: Optional[WatchEvent]) -> None:
        """Enqueue onto the consumer loop from wherever we are.
        ``call_soon_threadsafe`` writes to the loop's wake-up pipe per
        call — a real socket send per event PER WATCHER, which loopsan
        measured as the top cost inside the ``mvcc.write`` seam on the
        inline (non-durable) write path, where the writer already IS
        the consumer loop and the wake-up buys nothing. Same-loop
        callers take plain ``call_soon`` (identical FIFO ordering);
        worker threads keep the threadsafe wake-up."""
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop:
            self._loop.call_soon(self._queue.put_nowait, item)
        else:
            self._loop.call_soon_threadsafe(self._queue.put_nowait, item)

    def _deliver(self, ev: Optional[WatchEvent]) -> None:
        # Called with store lock held, possibly from a foreign thread.
        if ev is not None and ev.revision <= self.start_revision:
            return  # the client already observed this revision
        if ev is not None:
            c = chaos.CONTROLLER
            if c is not None and not self.overflowed:
                fault = c.decide(chaos.SITE_WATCH_STORE)
                if fault is not None and fault.kind == "overflow":
                    # Forced overflow: same path as a genuinely slow
                    # consumer — stream terminates, client must relist.
                    self.overflowed = True
                    self._post(None)
                    self._store._remove_watch(self)
                    return
            with self._pending_lock:
                self._pending += 1
                if self._pending > self._queue_limit:
                    if not self.overflowed:
                        self.overflowed = True
                        # Terminate instead of buffering forever; the
                        # end-of-stream sentinel jumps the queue.
                        self._post(None)
                        self._store._remove_watch(self)
                    return
        self._post(ev)

    def _deliver_batch(self, evs: list[WatchEvent]) -> None:
        """Deliver one txn's events to this watcher in ONE round: one
        pending-count bump, one loop wake (``call_soon`` writes the
        wake-up pipe once per call — per-event delivery paid that
        syscall N times per watcher per batch). Called with the store
        lock held, possibly from a foreign thread; ordering vs
        :meth:`_deliver` is preserved because both go through the same
        loop's FIFO ``call_soon`` queue."""
        items: list[WatchEvent] = []
        for ev in evs:
            if ev.revision <= self.start_revision:
                continue
            c = chaos.CONTROLLER
            if c is not None and not self.overflowed:
                fault = c.decide(chaos.SITE_WATCH_STORE)
                if fault is not None and fault.kind == "overflow":
                    self.overflowed = True
                    self._post(None)
                    self._store._remove_watch(self)
                    return
            items.append(ev)
        if not items:
            return
        with self._pending_lock:
            self._pending += len(items)
            if self._pending > self._queue_limit:
                if not self.overflowed:
                    self.overflowed = True
                    self._post(None)
                    self._store._remove_watch(self)
                return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop:
            self._loop.call_soon(self._enqueue_batch, items)
        else:
            self._loop.call_soon_threadsafe(self._enqueue_batch, items)

    def _enqueue_batch(self, items: list[WatchEvent]) -> None:
        for it in items:
            self._queue.put_nowait(it)

    def _consumed(self) -> None:
        with self._pending_lock:
            self._pending -= 1

    def cancel(self) -> None:
        if not self._cancelled:
            self._cancelled = True
            self._store._remove_watch(self)
            self._post(None)

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self._queue.get()
        if ev is None:
            raise StopAsyncIteration
        self._consumed()
        return ev

    async def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        """None on timeout; None with ``self.closed`` set on stream end."""
        if self.closed:
            return None
        try:
            # Fast path: an already-queued event needs no wait_for —
            # at fan-out scale the per-event timer + task churn of
            # wait_for was measurable event-loop time.
            ev = self._queue.get_nowait()
        except asyncio.QueueEmpty:
            if timeout is None:
                ev = await self._queue.get()
            else:
                try:
                    ev = await asyncio.wait_for(self._queue.get(), timeout)
                except asyncio.TimeoutError:
                    return None
        if ev is None:
            self.closed = True
        else:
            self._consumed()
        return ev

    def next_nowait(self) -> Optional[WatchEvent]:
        """An already-delivered event, or None when the queue is empty
        (or the stream just ended — ``self.closed`` distinguishes).
        The watch fan-out's drain primitive: after one awaited event,
        the server batches every event already in flight into a single
        socket write instead of one syscall per event per watcher."""
        if self.closed:
            return None
        try:
            ev = self._queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if ev is None:
            self.closed = True
            return None
        self._consumed()
        return ev


class _PrefixIndexedMap(dict):
    """dict[str, StoredObject] with a secondary index bucketing keys by
    their first two path segments (``/registry/<plural>/``), so prefix
    lists cost O(bucket) instead of O(total keys). At reference density
    (30k pods + their events + nodes) the full-keyspace startswith scan
    was the apiserver's single hottest path — every LIST and every
    quota-admission check paid it."""

    def __init__(self):
        super().__init__()
        self.buckets: dict[str, dict] = {}

    @staticmethod
    def bucket_key(key: str):
        """'/registry/pods/default/x' -> '/registry/pods/'; None when
        the key has fewer than two '/'-terminated segments."""
        i = key.find("/", 1)
        if i == -1:
            return None
        j = key.find("/", i + 1)
        if j == -1:
            return None
        return key[: j + 1]

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        bk = self.bucket_key(key)
        if bk is not None:
            self.buckets.setdefault(bk, {})[key] = value

    def __delitem__(self, key):
        super().__delitem__(key)
        bk = self.bucket_key(key)
        if bk is not None:
            bucket = self.buckets.get(bk)
            if bucket is not None:
                bucket.pop(key, None)
                if not bucket:
                    del self.buckets[bk]

    def pop(self, key, *default):
        try:
            value = self[key]
        except KeyError:
            if default:
                return default[0]
            raise
        self.__delitem__(key)
        return value

    def prefix_items(self, prefix: str):
        """(key, value) pairs under ``prefix`` — bucket-indexed when the
        prefix reaches into a single bucket, full scan otherwise."""
        bk = self.bucket_key(prefix)
        if bk is not None and prefix.startswith(bk):
            bucket = self.buckets.get(bk, {})
            if prefix == bk:
                return list(bucket.items())
            return [(k, v) for k, v in bucket.items() if k.startswith(prefix)]
        return [(k, v) for k, v in self.items() if k.startswith(prefix)]

    def prefix_count(self, prefix: str) -> int:
        """O(1) for whole-bucket prefixes (the quota-admission path)."""
        bk = self.bucket_key(prefix)
        if bk is not None and prefix.startswith(bk):
            bucket = self.buckets.get(bk, {})
            if prefix == bk:
                return len(bucket)
            return sum(1 for k in bucket if k.startswith(prefix))
        return sum(1 for k in self if k.startswith(prefix))

    # The bucket index is maintained only through __setitem__/
    # __delitem__/pop — the mutators MVCCStore uses. The rest would
    # silently desync it; fail loudly instead.
    def _unsupported(self, *a, **kw):
        raise NotImplementedError(
            "mutator bypasses the prefix index; use item assignment/del/pop")

    update = setdefault = clear = popitem = _unsupported
    __ior__ = _unsupported


class MVCCStore:
    def __init__(self, data_dir: Optional[str] = None, history_limit: int = 100_000,
                 transformers: Optional[dict] = None, fsync: str = "none",
                 fsync_batch: int = 64, fsync_interval: float = 0.05,
                 wal_max_bytes: int = 0, wal_max_records: int = 0):
        """``transformers``: key-prefix -> encryption.Transformer,
        applied at the persistence boundary only (WAL append, snapshot
        write, load) — the in-memory store, watch history, and every
        read path stay plaintext. See storage/encryption.py for why
        "at rest" means the disk here, not the client-server hop the
        reference transforms at. Calling :meth:`snapshot` after
        enabling encryption eagerly rewrites all existing plaintext.

        ``fsync``: WAL sync policy — "none" | "batch" | "always" (see
        module docstring); "batch" group-commits: an APPEND fsyncs
        once ``fsync_batch`` records or ``fsync_interval`` seconds
        accumulated since the last sync (idle tails sync at
        close/snapshot/fsync_now, not on a timer).

        ``wal_max_bytes`` / ``wal_max_records``: WAL rotation
        thresholds (0 = disabled). When the log crosses either limit
        the store auto-:meth:`snapshot`\\ s inline on the append path,
        folding the log into snapshot.json and truncating it — disk
        footprint and recovery time stay flat under sustained churn
        instead of growing with total write count (the etcd
        snap-count discipline)."""
        if fsync not in ("none", "batch", "always"):
            raise ValueError(f"fsync must be none|batch|always, got {fsync!r}")
        self._lock = make_lock("mvcc.Store", rlock=True)
        self._fsync = fsync
        self._fsync_batch = fsync_batch
        self._fsync_interval = fsync_interval
        self._wal_unsynced = 0
        self._wal_last_sync = time.monotonic()
        self._wal_max_bytes = wal_max_bytes
        self._wal_max_records = wal_max_records
        #: Current WAL footprint (bytes / record count since the last
        #: truncation) — the auto-snapshot trigger and the numbers the
        #: /debug/v1/storage endpoint and endurance gate read.
        self._wal_bytes = 0
        self._wal_records = 0
        #: Lifetime counters (NOT reset by rotation): WAL records ever
        #: appended vs logical write ops they carried — the
        #: ``wal_records_per_create`` ratio /debug/v1/storage serves
        #: and the endurance gate asserts drops >=8x under batching.
        self._wal_records_total = 0
        self._wal_ops_total = 0
        self._snapshots = 0
        self._compactions = 0
        #: chaos ``wal:compact-crash``: when armed, the NEXT snapshot
        #: dies after installing snapshot.json but before truncating
        #: the WAL (see :meth:`snapshot`).
        self._compact_crash_armed = False
        #: True once a WAL fault (chaos) crashed the backend: every
        #: further mutation raises until the store is rebuilt from disk.
        self._wal_failed = False
        #: Replication follower guard: when set (to a human-readable
        #: reason), every direct mutation raises ServiceUnavailable —
        #: a follower's state may only advance through
        #: :meth:`apply_replicated`, or it diverges from the leader.
        self.writes_blocked: Optional[str] = None
        #: Raft term stamped into WAL records (and the snapshot) while
        #: a replication layer drives this store — the log-entry term
        #: raft's election restriction and consistency checks need to
        #: SURVIVE A RESTART. 0 (unreplicated) keeps the record format
        #: byte-identical to the pre-replication WAL.
        self.wal_term = 0
        #: Term of the last APPLIED record (never the stamping term —
        #: a snapshot must claim exactly what its log holds, or a
        #: restarted node would out-vote genuinely longer logs).
        self.last_entry_term = 0
        #: Term of the last record recovered from disk (snapshot term,
        #: advanced by each replayed WAL record) — what a restarted
        #: ReplicaNode resumes its (last_term, last_rev) coordinate
        #: from. Without this, a rebooted replica would claim term 0
        #: for its whole log and grant votes to candidates with older,
        #: shorter logs — losing quorum-committed writes.
        self.recovered_term = 0
        #: Per-thread capture of the last revision a mutation wrote
        #: (see :meth:`last_write_in`).
        self._write_tls = _threading.local()
        #: True while :meth:`apply_replicated` is inside _append_event;
        #: lets a replication event hook tell a LOCAL write (to ship to
        #: followers) from a replicated apply (already shipped). Valid
        #: only under the store lock, which is where hooks run.
        self.applying_replicated = False
        #: True while :meth:`_append_batch` runs a txn's per-event
        #: hooks: an event hook that captures writes one-by-one (the
        #: replication leader seam) must skip them — the whole batch
        #: arrives once through the txn hooks instead.
        self.in_txn = False
        #: Canonical state captured the instant a WAL crash fault fired
        #: — what recovery from disk must reproduce, byte for byte.
        self.pre_crash_state: Optional[dict] = None
        self._transformers = dict(transformers or {})
        #: key -> StoredObject (live keys only).
        self._data: _PrefixIndexedMap = _PrefixIndexedMap()
        self._rev = 0
        self._compact_rev = 0
        #: Event history for watch replay, ascending by revision.
        self._log: list[WatchEvent] = []
        self._log_revs: list[int] = []
        self._history_limit = history_limit
        self._watches: list[Watch] = []
        #: Watch dispatch index (see :meth:`register_watch_index`):
        #: name -> (prefix, extractor(raw value dict) -> str | None).
        self._watch_indexes: dict[str, tuple[str, Callable]] = {}
        #: (index name, extracted value) -> watches subscribed to that
        #: bucket. Indexed watches live here INSTEAD of the plain scan
        #: list; ``self._watches`` stays the authoritative union for
        #: bookkeeping (close/compact/count).
        self._watch_buckets: dict[tuple[str, str], list[Watch]] = {}
        #: Watches delivered by the O(watchers) prefix scan (everything
        #: without an index hint).
        self._plain_watches: list[Watch] = []
        #: Key-level write listeners (see :meth:`add_write_hook`).
        self._write_hooks: list[Callable[[str], None]] = []
        #: Full-event listeners (see :meth:`add_event_hook`).
        self._event_hooks: list[Callable[[WatchEvent], None]] = []
        #: Whole-txn listeners (see :meth:`add_txn_hook`).
        self._txn_hooks: list[Callable[[list], None]] = []
        self._data_dir = data_dir
        self._wal = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load()
            wal_path = os.path.join(data_dir, "wal.jsonl")
            self._wal = open(wal_path, "a", buffering=1)
            # Footprint resumes from the recovered (post-truncation)
            # log, so rotation thresholds survive a restart.
            self._wal_bytes = os.path.getsize(wal_path)
        if invariants.SANITIZER is not None:
            # tpusan: every store built while the sanitizer is armed is
            # checked on every write (chaos harness restarts included).
            invariants.SANITIZER.attach_store(self)

    @property
    def durable(self) -> bool:
        """True when writes append to a WAL (may block on disk)."""
        return self._wal is not None

    # -- persistence ------------------------------------------------------

    def _disk(self, key: str, value):
        """Value as persisted: enveloped when a transformer claims the
        key's prefix, unchanged otherwise (and for delete tombstones)."""
        if value is None or not self._transformers:
            return value
        for prefix, tf in self._transformers.items():
            if key.startswith(prefix):
                return tf.for_write(value)
        return value

    def _from_disk(self, key: str, value):
        if value is None:
            return value
        for prefix, tf in self._transformers.items():
            if key.startswith(prefix):
                return tf.for_read(value)
        if isinstance(value, dict) and "__enc__" in value:
            # Enveloped on disk but no transformer claims the key: the
            # operator restarted without --encryption-provider-config
            # (or dropped this resource from it). Serving the envelope
            # as the object would be silent corruption — fail the load.
            from .encryption import DecryptError
            raise DecryptError(
                f"{key}: encrypted at rest but no encryption provider "
                f"is configured for it — restart with the same "
                f"--encryption-provider-config used to write it")
        return value

    def _load(self) -> None:
        snap = os.path.join(self._data_dir, "snapshot.json")
        if os.path.exists(snap):
            with open(snap) as f:
                state = json.load(f)
            self._rev = state["rev"]
            self._compact_rev = state.get("compact_rev", 0)
            self.recovered_term = state.get("term", 0)
            for k, v in state["data"].items():
                self._data[k] = StoredObject(
                    key=k, value=self._from_disk(k, v["value"]),
                    mod_revision=v["mod_revision"],
                    create_revision=v["create_revision"],
                )
        wal = os.path.join(self._data_dir, "wal.jsonl")
        if os.path.exists(wal):
            good_end = self._replay_wal(wal)
            if good_end < os.path.getsize(wal):
                # Torn/corrupt tail: truncate to the last good record
                # so future appends extend a clean log instead of
                # continuing a half-written line (which would poison
                # every record after it on the NEXT replay).
                with open(wal, "rb+") as f:
                    f.truncate(good_end)
        # Event history does not survive restart: everything up to the
        # recovered revision is effectively compacted, so watches resuming
        # from a pre-restart revision get GoneError (410) and relist —
        # the same contract etcd gives after compaction.
        self._compact_rev = max(self._compact_rev, self._rev)
        self.last_entry_term = self.recovered_term

    def _replay_wal(self, wal: str) -> int:
        """Apply the WAL's longest valid record prefix; returns the
        byte offset just past the last good record. A record is good
        when it is a complete line, its CRC (when framed) matches, and
        it parses — anything else is the crash cut: that record and
        everything after it never happened."""
        with open(wal, "rb") as f:
            raw = f.read()
        good_end = 0
        while good_end < len(raw):
            nl = raw.find(b"\n", good_end)
            if nl == -1:
                break  # torn tail: no newline ever made it to disk
            line = raw[good_end:nl].strip()
            if line:
                rec = self._parse_wal_line(line)
                if rec is None:
                    break  # bad CRC / truncated JSON — corrupt cutoff
                self._apply_wal_record(rec)
                self._wal_records += 1
            good_end = nl + 1
        return good_end

    @staticmethod
    def _parse_wal_line(line: bytes) -> Optional[dict]:
        """One WAL line -> record dict, or None when corrupt. Framed
        form is ``<crc32hex> <json>``; bare-JSON lines (pre-CRC WALs)
        still load, checked only by the parse."""
        payload = line
        if not line.startswith(b"{"):
            crc_hex, _, payload = line.partition(b" ")
            try:
                want = int(crc_hex, 16)
            except ValueError:
                return None
            if zlib.crc32(payload) != want:
                return None
        try:
            rec = json.loads(payload)
        except json.JSONDecodeError:
            return None
        return rec if isinstance(rec, dict) and "rev" in rec else None

    def _apply_wal_record(self, rec: dict) -> None:
        if rec["rev"] <= self._rev:
            return
        if rec.get("op") == BATCH:
            # One framed line, N sub-records: replay each in commit
            # order. The whole line shares one CRC so a batch is
            # all-or-nothing on disk; per-sub idempotence still guards
            # a replay over a store that already holds a prefix (the
            # compact-crash stale-log path).
            term = rec.get("term", 0)
            for sub in rec["ops"]:
                if sub["rev"] <= self._rev:
                    continue
                self._apply_wal_record(
                    {**sub, "term": term} if term else sub)
            return
        self._rev = rec["rev"]
        self.recovered_term = rec.get("term", self.recovered_term)
        key = rec["key"]
        if rec["op"] == DELETED:
            self._data.pop(key, None)
        else:
            prev = self._data.get(key)
            self._data[key] = StoredObject(
                key=key, value=self._from_disk(key, rec["value"]),
                mod_revision=rec["rev"],
                create_revision=prev.create_revision if prev else rec["rev"],
            )

    def snapshot(self) -> None:
        """Write a full snapshot and truncate the WAL."""
        if not self._data_dir:
            return
        with self._lock:
            state = {
                "rev": self._rev,
                "compact_rev": self._compact_rev,
                "term": self.last_entry_term,
                "data": {
                    k: {"value": self._disk(k, o.value),
                        "mod_revision": o.mod_revision,
                        "create_revision": o.create_revision}
                    for k, o in self._data.items()
                },
            }
            tmp = os.path.join(self._data_dir, "snapshot.json.tmp")
            # Amortized: snapshot() runs once per snapshot_every
            # writes, and durable stores run writes off-loop
            # (registry.run -> to_thread).
            with open(tmp, "w") as f:  # tpuvet: ignore[hot-path-cost]
                json.dump(state, f)
                f.flush()
                os.fsync(f.fileno())  # tpuvet: ignore[hot-path-cost] (amortized snapshot)
            os.replace(tmp, os.path.join(self._data_dir, "snapshot.json"))
            if self._compact_crash_armed:
                # chaos ``wal:compact-crash``: die in the window where
                # the new snapshot is durable but the old WAL has not
                # been truncated. Recovery loads the snapshot AND
                # replays the whole stale log; replay idempotence
                # (``rec["rev"] <= self._rev`` skipped) must make that
                # byte-identical to the pre-crash state.
                self._compact_crash_armed = False
                self.pre_crash_state = self.state()
                if self._wal:
                    self._wal.close()
                self._wal_failed = True
                raise errors.ServiceUnavailableError(
                    "chaos: crashed between snapshot install and WAL "
                    "truncation (compact-crash)")
            if self._wal:
                self._wal.close()
            wal_path = os.path.join(self._data_dir, "wal.jsonl")
            open(wal_path, "w").close()  # tpuvet: ignore[hot-path-cost] (amortized snapshot)
            self._wal = open(wal_path, "a", buffering=1)  # tpuvet: ignore[hot-path-cost] (amortized snapshot)
            self._wal_bytes = 0
            self._wal_records = 0
            self._wal_unsynced = 0
            self._snapshots += 1

    def close(self) -> None:
        with self._lock:
            for wch in list(self._watches):
                wch.cancel()
            if self._wal:
                if self._fsync != "none" and not self._wal.closed:
                    # Quiesce point: a clean shutdown must not leave a
                    # mid-batch tail in the page cache only.
                    self.fsync_now()
                self._wal.close()
                self._wal = None

    # -- core mutations ---------------------------------------------------

    def add_write_hook(self, fn: Callable[[str], None]) -> None:
        """Register ``fn(key)`` to run on every write (create/update/
        delete), under the store lock, before watch delivery. Hooks must
        be cheap, non-blocking leaf operations (the registry's encode
        cache uses this for invalidate-on-write); they must never call
        back into the store."""
        self._write_hooks.append(fn)

    def add_event_hook(self, fn: Callable[[WatchEvent], None]) -> None:
        """Like :meth:`add_write_hook` but with the full event (type,
        key, value, prev_value, revision) — the tpusan invariant seam.
        Same contract: cheap, non-raising, no store re-entry."""
        self._event_hooks.append(fn)

    def add_txn_hook(self, fn: Callable[[list], None]) -> None:
        """Register ``fn(events)`` to run once per committed :meth:`txn`
        with the whole batch's events, under the store lock, after the
        WAL append and before watch delivery — the replication leader's
        one-log-entry-per-chunk capture seam. Single-op writes never
        call it. Same contract as the other hooks: cheap, non-raising,
        no store re-entry."""
        self._txn_hooks.append(fn)

    def _append_event(self, ev: WatchEvent) -> None:
        interleave.touch(ev.key)
        if self.wal_term:
            self.last_entry_term = self.wal_term
        self._write_tls.last_rev = ev.revision
        for hook in self._write_hooks:
            hook(ev.key)
        for hook in self._event_hooks:
            hook(ev)
        self._log.append(ev)
        self._log_revs.append(ev.revision)
        if len(self._log) > self._history_limit:
            cut = len(self._log) - self._history_limit
            self._compact_rev = self._log_revs[cut - 1]
            del self._log[:cut]
            del self._log_revs[:cut]
        if self._wal and not self._wal_failed:
            line = self._wal_line(ev.revision, ev.type, ev.key, ev.value)
            self._wal.write(line)
            self._wal_bytes += len(line)
            self._wal_records += 1
            self._wal_records_total += 1
            self._wal_ops_total += 1
            self._wal_sync()
            self._maybe_rotate_wal()
        # Snapshot: an overflowing watcher removes itself from _watches
        # during _deliver; mutating the live list mid-iteration would
        # silently skip the next watcher's delivery of this event.
        for wch in list(self._plain_watches):
            if ev.key.startswith(wch.prefix):
                wch._deliver(ev)
        if self._watch_buckets:
            self._dispatch_indexed(ev)

    def _dispatch_indexed(self, ev: WatchEvent) -> None:
        """Deliver one event to the watch buckets it belongs to.

        Cost is O(indexes + matching watchers), NOT O(all watchers):
        at hollow-fleet width (5k per-node pod watchers) the plain
        prefix scan above would evaluate every watcher for every pod
        event — the extractor runs once per registered index instead,
        and only the bucket whose value matches gets a delivery. Both
        the current and previous value's buckets are notified so
        selector transitions (a bind moving a pod INTO a node's
        selected set, a reschedule moving it out) surface exactly like
        the unindexed path — the ObjectWatch filter on top keeps the
        transition semantics."""
        for name, (prefix, extract) in self._watch_indexes.items():
            if not ev.key.startswith(prefix):
                continue
            cur = extract(ev.value) if ev.value is not None else None
            prev = extract(ev.prev_value) if ev.prev_value is not None else None
            for val in ((cur,) if cur == prev or prev is None
                        else (cur, prev) if cur is not None else (prev,)):
                if not val:
                    continue
                bucket = self._watch_buckets.get((name, val))
                if bucket:
                    for wch in list(bucket):
                        if ev.key.startswith(wch.prefix):
                            wch._deliver(ev)

    def _wal_line(self, rev: int, op: str, key: str,
                  value: Optional[dict]) -> str:
        rec = {"rev": rev, "op": op, "key": key,
               "value": self._disk(key, value)}
        if self.wal_term:
            # Only replicated stores stamp terms — an unreplicated WAL
            # stays byte-identical to the pre-replication format.
            rec["term"] = self.wal_term
        # Durable arm only: the WAL record serialization IS the
        # write, and durable stores run it off-loop (to_thread).
        payload = json.dumps(rec, separators=(",", ":"))  # tpuvet: ignore[hot-path-cost]
        return f"{zlib.crc32(payload.encode()):08x} {payload}\n"

    def _wal_batch_line(self, events: list[WatchEvent]) -> str:
        """One framed WAL line for a whole committed txn. The outer
        record's ``rev`` is the batch's FINAL revision (so the replay
        idempotence check — ``rec["rev"] <= self._rev`` skip — covers
        the batch as one unit) and ``op`` is the :data:`BATCH` kind;
        ``ops`` carries the sub-records in commit order, each in the
        legacy single-record shape. One CRC covers the whole line: a
        torn or flipped batch frame drops the whole chunk on replay —
        a batch record is atomic on disk by construction."""
        subs = [{"rev": ev.revision, "op": ev.type, "key": ev.key,
                 "value": self._disk(ev.key, ev.value)} for ev in events]
        rec = {"rev": subs[-1]["rev"], "op": BATCH, "ops": subs}
        if self.wal_term:
            rec["term"] = self.wal_term
        # Durable arm only, off-loop (see _wal_line).
        payload = json.dumps(rec, separators=(",", ":"))  # tpuvet: ignore[hot-path-cost]
        return f"{zlib.crc32(payload.encode()):08x} {payload}\n"

    def _append_batch(self, events: list[WatchEvent]) -> None:
        """Commit tail for one txn: per-event hooks and history in
        commit order, then ONE WAL record, ONE group-commit sync, ONE
        whole-batch replication hook, and ONE watch-delivery round per
        watcher (all matching events enqueued before the single loop
        wake). The single-write path (:meth:`_append_event`) pays each
        of those per op."""
        if self.wal_term:
            self.last_entry_term = self.wal_term
        self._write_tls.last_rev = events[-1].revision
        self.in_txn = True
        try:
            for ev in events:
                interleave.touch(ev.key)
                for hook in self._write_hooks:
                    hook(ev.key)
                for hook in self._event_hooks:
                    hook(ev)
                self._log.append(ev)
                self._log_revs.append(ev.revision)
        finally:
            self.in_txn = False
        if len(self._log) > self._history_limit:
            cut = len(self._log) - self._history_limit
            self._compact_rev = self._log_revs[cut - 1]
            del self._log[:cut]
            del self._log_revs[:cut]
        if self._wal and not self._wal_failed:
            line = self._wal_batch_line(events)
            self._wal.write(line)
            self._wal_bytes += len(line)
            self._wal_records += 1
            self._wal_records_total += 1
            self._wal_ops_total += len(events)
            self._wal_sync()
        for hook in self._txn_hooks:
            hook(events)
        # One delivery round per watcher (see _append_event for the
        # list() snapshot rationale).
        for wch in list(self._plain_watches):
            evs = [ev for ev in events if ev.key.startswith(wch.prefix)]
            if evs:
                wch._deliver_batch(evs)
        if self._watch_buckets:
            # Group the batch by bucket in ONE pass over the events,
            # then one delivery round per touched bucket — same
            # O(indexes) per-event cost as _dispatch_indexed, same
            # single loop wake per watcher as the plain path.
            grouped: dict[tuple[str, str], list[WatchEvent]] = {}
            for ev in events:
                for name, (prefix, extract) in self._watch_indexes.items():
                    if not ev.key.startswith(prefix):
                        continue
                    cur = (extract(ev.value)
                           if ev.value is not None else None)
                    prev = (extract(ev.prev_value)
                            if ev.prev_value is not None else None)
                    for val in ((cur,) if cur == prev or prev is None
                                else (cur, prev) if cur is not None
                                else (prev,)):
                        if val and (name, val) in self._watch_buckets:
                            grouped.setdefault((name, val), []).append(ev)
            for bkey, evs in grouped.items():
                for wch in list(self._watch_buckets.get(bkey, ())):
                    mine = [ev for ev in evs
                            if ev.key.startswith(wch.prefix)]
                    if mine:
                        wch._deliver_batch(mine)
        MVCC_TXN_COMMITS.inc()
        MVCC_TXN_OPS.inc(float(len(events)))
        self._maybe_rotate_wal()

    def _wal_sync(self) -> None:
        """Group-commit: fsync per policy, decided at APPEND time.
        Under "batch", one fsync covers up to ``fsync_batch`` records /
        ``fsync_interval`` seconds of appends — the etcd raft-entry
        batching analog. No timer: an idle tail waits for the next
        append or a quiesce point (close/snapshot/fsync_now)."""
        if self._fsync == "none":
            return
        self._wal_unsynced += 1
        if self._fsync == "batch" \
                and self._wal_unsynced < self._fsync_batch \
                and time.monotonic() - self._wal_last_sync < self._fsync_interval:
            return
        self.fsync_now()

    def _maybe_rotate_wal(self) -> None:
        """Threshold-driven WAL rotation, checked after every append
        (under the store RLock — :meth:`snapshot` re-enters safely).
        With both thresholds 0 the WAL grows until a manual snapshot,
        byte-identical to the pre-rotation store."""
        if self._wal is None or self._wal_failed:
            return
        if (self._wal_max_bytes and self._wal_bytes >= self._wal_max_bytes) \
                or (self._wal_max_records
                    and self._wal_records >= self._wal_max_records):
            self.snapshot()

    def fsync_now(self) -> None:
        """Flush + fsync the WAL now (quiesce points: snapshot, close,
        harness barriers)."""
        with self._lock:
            if self._wal is None or self._wal.closed:
                return
            self._wal.flush()
            # Durable arm only, off-loop via registry.run/to_thread;
            # group-commit policy already amortizes the fsync.
            os.fsync(self._wal.fileno())  # tpuvet: ignore[hot-path-cost]
            self._wal_unsynced = 0
            self._wal_last_sync = time.monotonic()

    @property
    def wal_failed(self) -> bool:
        """True once a (chaos-injected) WAL crash stopped the backend;
        only rebuilding the store from ``data_dir`` recovers."""
        return self._wal_failed

    def state(self) -> dict:
        """Canonical, deep-copied snapshot of revision + live keys —
        the recovery-equality artifact (``json.dumps(..., sort_keys=
        True)`` of two stores' state() compares byte-identical)."""
        with self._lock:
            return {
                "rev": self._rev,
                "data": {k: {"value": self._freeze(o.value),
                             "mod_revision": o.mod_revision,
                             "create_revision": o.create_revision}
                         for k, o in sorted(self._data.items())},
            }

    def _wal_chaos_precheck(self, op: str, key: str,
                            value: Optional[dict]) -> None:
        """The ``wal`` chaos site, consulted BEFORE a mutation touches
        memory. An injected fault is a crash mid-append: the record
        never applies, the on-disk tail is damaged per the fault kind,
        and the store refuses every later write (an etcd that lost its
        disk) until rebuilt from ``data_dir`` — at which point recovery
        must reproduce :attr:`pre_crash_state` exactly."""
        fault = self._wal_fault_or_none()
        if fault is None:
            return
        self._wal_crash(fault, self._wal_line(self._rev + 1, op, key, value))

    def _wal_chaos_precheck_batch(
            self, entries: list[tuple[str, str, Optional[dict]]]) -> None:
        """Batch-txn twin of :meth:`_wal_chaos_precheck`: the injected
        crash damages the ONE framed batch record the txn would have
        written (``entries`` = the txn's (op, key, value) triples with
        hypothetical contiguous revisions), so recovery must drop the
        whole chunk — a batch record is atomic on disk."""
        fault = self._wal_fault_or_none()
        if fault is None:
            return
        evs = [WatchEvent(op, key, value, None, self._rev + 1 + j)
               for j, (op, key, value) in enumerate(entries)]
        self._wal_crash(fault, self._wal_batch_line(evs))

    def _wal_fault_or_none(self):
        """Shared decide step for the single/batch WAL chaos prechecks:
        raises if the WAL already crashed, arms compact-crash, returns
        the fault to inject (or None when nothing fires)."""
        if self._wal is None:
            return None
        if self._wal_failed:
            raise errors.ServiceUnavailableError(
                "storage backend unavailable (WAL crashed; rebuild the "
                "store from its data dir to recover)")
        c = chaos.CONTROLLER
        if c is None:
            return None
        fault = c.decide(chaos.SITE_WAL)
        if fault is None:
            return None
        if fault.kind == "compact-crash":
            # Armed, not fired: THIS write proceeds normally; the next
            # snapshot (manual or threshold-triggered) crashes between
            # installing snapshot.json and truncating the WAL — the
            # compaction analog of a torn tail (see :meth:`snapshot`).
            self._compact_crash_armed = True
            return None
        return fault

    def _wal_crash(self, fault, line: str) -> None:
        self.pre_crash_state = self.state()
        if fault.kind == "torn":
            # Crash mid-write: a record prefix, no newline.
            self._wal.write(line[: max(1, len(line) // 2)])
        elif fault.kind == "flip":
            # Full record on disk, one byte corrupted in flight — the
            # CRC frame catches it on replay.
            mid = len(line) // 2
            self._wal.write(line[:mid]
                            + chr((ord(line[mid]) + 1) % 128 or 1)
                            + line[mid + 1:])
        # "crash": the record never reached the disk buffer at all.
        try:
            self._wal.flush()
            # Chaos-armed only (TPU_CHAOS wal faults): never on in
            # a production or perf arm.
            os.fsync(self._wal.fileno())  # tpuvet: ignore[hot-path-cost]
        except OSError:
            pass  # the "disk" is dying by definition here
        self._wal.close()
        self._wal_failed = True
        raise errors.ServiceUnavailableError(
            f"chaos: WAL crashed mid-append ({fault.kind})")

    @staticmethod
    def _freeze(value):
        """Deep-copy on write so the store/WAL/watch-history never alias a
        dict the caller may mutate later. Hand-rolled structural copy:
        values are JSON-plain (``to_dict()`` output), and the recursive
        copy is ~2.5x cheaper per pod than a ``json.dumps``/``loads``
        round trip — this runs once per MVCC write AND once per copied
        ``get`` at density scale (loopsan's top ``mvcc.write`` cost).
        Tuples normalize to lists like the old JSON round trip did;
        scalars are immutable and pass through by reference."""
        if type(value) is dict:
            return {k: MVCCStore._freeze(v) for k, v in value.items()}
        if type(value) is list or type(value) is tuple:
            return [MVCCStore._freeze(v) for v in value]
        return value

    def _check_write_guard(self) -> None:
        if self.writes_blocked:
            raise errors.ServiceUnavailableError(self.writes_blocked)

    def create(self, key: str, value: dict) -> int:
        with loopsan.seam("mvcc.write"):
            return self._create(key, value)

    def _create(self, key: str, value: dict) -> int:
        value = self._freeze(value)
        with self._lock:
            self._check_write_guard()
            if key in self._data:
                raise errors.AlreadyExistsError(f"key {key!r} already exists")
            self._wal_chaos_precheck(ADDED, key, value)
            self._rev += 1
            self._data[key] = StoredObject(
                key=key, value=value, mod_revision=self._rev, create_revision=self._rev
            )
            self._append_event(WatchEvent(ADDED, key, value, None, self._rev))
            return self._rev

    def exists(self, key: str) -> bool:
        with self._lock:
            return key in self._data

    def get(self, key: str, copy: bool = True) -> StoredObject:
        """Read one key. ``copy=True`` (default) deep-copies the value so
        callers can't corrupt store state; readers that immediately decode
        through the scheme (which copies structurally) may pass False."""
        with self._lock:
            obj = self._data.get(key)
            if obj is None:
                raise errors.NotFoundError(f"key {key!r} not found")
            if copy:
                return StoredObject(obj.key, self._freeze(obj.value),
                                    obj.mod_revision, obj.create_revision)
            return obj

    def update(self, key: str, value: dict, expected_revision: Optional[int] = None) -> int:
        with loopsan.seam("mvcc.write"):
            return self._update(key, value, expected_revision)

    def _update(self, key: str, value: dict, expected_revision: Optional[int] = None) -> int:
        value = self._freeze(value)
        with self._lock:
            self._check_write_guard()
            obj = self._data.get(key)
            if obj is None:
                raise errors.NotFoundError(f"key {key!r} not found")
            if expected_revision is not None and obj.mod_revision != expected_revision:
                raise errors.ConflictError(
                    f"key {key!r}: revision mismatch (have {obj.mod_revision}, "
                    f"caller expected {expected_revision})"
                )
            self._wal_chaos_precheck(MODIFIED, key, value)
            self._rev += 1
            prev = obj.value
            self._data[key] = StoredObject(
                key=key, value=value, mod_revision=self._rev,
                create_revision=obj.create_revision,
            )
            self._append_event(WatchEvent(MODIFIED, key, value, prev, self._rev))
            return self._rev

    def delete(self, key: str, expected_revision: Optional[int] = None) -> int:
        with loopsan.seam("mvcc.write"):
            return self._delete(key, expected_revision)

    def _delete(self, key: str, expected_revision: Optional[int] = None) -> int:
        with self._lock:
            self._check_write_guard()
            obj = self._data.get(key)
            if obj is None:
                raise errors.NotFoundError(f"key {key!r} not found")
            if expected_revision is not None and obj.mod_revision != expected_revision:
                raise errors.ConflictError(
                    f"key {key!r}: revision mismatch (have {obj.mod_revision}, "
                    f"caller expected {expected_revision})"
                )
            self._wal_chaos_precheck(DELETED, key, obj.value)
            self._rev += 1
            del self._data[key]
            self._append_event(WatchEvent(DELETED, key, obj.value, obj.value, self._rev))
            return self._rev

    def txn(self, ops: list[tuple]) -> list[int]:
        """Commit N writes as ONE transaction: one lock acquisition,
        one contiguous revision range, one framed WAL record (see
        :meth:`_wal_batch_line`), one group-commit sync, one watch
        round. ``ops`` is ``[(op, key, value, expected_revision)]``
        with ``op`` in {ADDED, MODIFIED, DELETED}; ``value`` is the
        new object (ADDED/MODIFIED) and ignored for DELETED;
        ``expected_revision`` is the usual CAS guard (None skips it).
        All-or-nothing: any per-op validation failure raises
        :class:`TxnError` naming the offending index and NOTHING
        commits — callers split-commit around the bad item. Returns
        the committed revisions in op order."""
        with loopsan.seam("mvcc.txn"):
            return self._txn(ops)

    def _txn(self, ops: list[tuple]) -> list[int]:
        if not ops:
            return []
        with self._lock:
            self._check_write_guard()
            # Pass 1 — validate every op against an overlay of the ops
            # before it WITHOUT touching store state: TxnError must
            # leave no trace.
            staged: dict[str, dict] = {}
            frozen: list[Optional[dict]] = []
            wal_vals: list[Optional[dict]] = []
            for i, (op, key, value, expected) in enumerate(ops):
                try:
                    st = staged.get(key)
                    if st is not None:
                        alive = st["op"] != DELETED
                        cur_val = st["value"]
                        cur_rev = None  # mid-txn revs aren't assigned yet
                    else:
                        obj = self._data.get(key)
                        alive = obj is not None
                        cur_val = obj.value if obj is not None else None
                        cur_rev = obj.mod_revision if obj is not None else None
                    if op == ADDED:
                        if alive:
                            raise errors.AlreadyExistsError(
                                f"key {key!r} already exists")
                        fv = self._freeze(value)
                        frozen.append(fv)
                        wal_vals.append(fv)
                    else:
                        if not alive:
                            raise errors.NotFoundError(
                                f"key {key!r} not found")
                        if expected is not None:
                            if cur_rev is None or cur_rev != expected:
                                raise errors.ConflictError(
                                    f"key {key!r}: revision mismatch "
                                    f"(have {cur_rev}, caller expected "
                                    f"{expected})")
                        if op == MODIFIED:
                            fv = self._freeze(value)
                            frozen.append(fv)
                            wal_vals.append(fv)
                        else:
                            frozen.append(None)
                            wal_vals.append(cur_val)  # the corpse
                    staged[key] = {"op": op, "value": frozen[-1]}
                except errors.StatusError as e:
                    raise TxnError(i, e) from e
            self._wal_chaos_precheck_batch(
                [(op, key, wal_vals[j])
                 for j, (op, key, _v, _e) in enumerate(ops)])
            # Pass 2 — apply sequentially under the contiguous range.
            base = self._rev
            events: list[WatchEvent] = []
            for j, (op, key, _value, _expected) in enumerate(ops):
                rev = base + 1 + j
                prev_obj = self._data.get(key)
                if op == DELETED:
                    corpse = prev_obj.value
                    del self._data[key]
                    ev = WatchEvent(DELETED, key, corpse, corpse, rev)
                elif op == ADDED:
                    fv = frozen[j]
                    self._data[key] = StoredObject(
                        key=key, value=fv, mod_revision=rev,
                        create_revision=rev)
                    ev = WatchEvent(ADDED, key, fv, None, rev)
                else:
                    fv = frozen[j]
                    self._data[key] = StoredObject(
                        key=key, value=fv, mod_revision=rev,
                        create_revision=prev_obj.create_revision)
                    ev = WatchEvent(MODIFIED, key, fv, prev_obj.value, rev)
                events.append(ev)
            self._rev = base + len(ops)
            self._append_batch(events)
            return [e.revision for e in events]

    def last_write_in(self, fn, *args) -> tuple:
        """Run ``fn(*args)`` and return ``(result, rev)`` where ``rev``
        is the highest revision the call itself wrote (0 if it wrote
        nothing). Capture is per-thread — concurrent requests in other
        worker threads (or interleaved on the loop between THIS sync
        call's boundaries) cannot leak their revisions into it — so the
        replicated ack gate waits on exactly the write it acked, never
        on a neighbor's in-flight mutation."""
        self._write_tls.last_rev = 0
        out = fn(*args)
        return out, self._write_tls.last_rev

    # -- replication apply path -------------------------------------------

    def apply_replicated(self, op: str, key: str, value: Optional[dict],
                         rev: int, term: int = 0) -> bool:
        """Apply one replicated log entry with its LEADER-ASSIGNED
        revision — the follower half of storage/replication.py. Bypasses
        the follower write guard and all CAS checks (the leader already
        arbitrated them), but takes the same path through the WAL, the
        write/event hooks, and watch delivery, so a follower is fully
        durable and fully watchable. Idempotent: a resent entry at or
        below the current revision is a no-op (returns False).
        ``term`` is the entry's raft term, stamped into the WAL record
        so the log coordinate survives a restart.

        A :data:`BATCH` entry (op == BATCH, ``value["ops"]`` = the
        txn's sub-records, ``rev`` = the final revision) applies the
        whole chunk under one lock hold / one WAL record / one watch
        round, exactly like the leader's :meth:`txn` commit."""
        if op == BATCH:
            return self._apply_replicated_batch(value["ops"], rev, term)
        with self._lock:
            if rev <= self._rev:
                return False
            if rev != self._rev + 1:
                raise ValueError(
                    f"replicated entry rev {rev} leaves a gap after local "
                    f"rev {self._rev}; replication must apply contiguously")
            if term:
                self.wal_term = term
            self._wal_chaos_precheck(op, key, value)
            self._rev = rev
            prev_obj = self._data.get(key)
            if op == DELETED:
                if prev_obj is not None:
                    del self._data[key]
                corpse = prev_obj.value if prev_obj is not None else value
                ev = WatchEvent(DELETED, key, corpse, corpse, rev)
            else:
                value = self._freeze(value)
                self._data[key] = StoredObject(
                    key=key, value=value, mod_revision=rev,
                    create_revision=(prev_obj.create_revision
                                     if prev_obj is not None else rev))
                ev = WatchEvent(
                    op, key, value,
                    prev_obj.value if prev_obj is not None else None, rev)
            self.applying_replicated = True
            try:
                self._append_event(ev)
            finally:
                self.applying_replicated = False
            return True

    def _apply_replicated_batch(self, subs: list[dict], rev: int,
                                term: int = 0) -> bool:
        """Follower-side :meth:`txn` commit. Idempotent per sub-record:
        a resend overlapping already-applied revisions re-applies only
        the unseen suffix (still contiguous with the local head)."""
        with self._lock:
            if rev <= self._rev:
                return False
            pending = [s for s in subs if s["rev"] > self._rev]
            if not pending or pending[0]["rev"] != self._rev + 1:
                head = pending[0]["rev"] if pending else rev
                raise ValueError(
                    f"replicated batch head rev {head} leaves a gap "
                    f"after local rev {self._rev}; replication must "
                    f"apply contiguously")
            if term:
                self.wal_term = term
            self._wal_chaos_precheck_batch(
                [(s["op"], s["key"], s["value"]) for s in pending])
            events: list[WatchEvent] = []
            for s in pending:
                key = s["key"]
                prev_obj = self._data.get(key)
                if s["op"] == DELETED:
                    if prev_obj is not None:
                        del self._data[key]
                    corpse = (prev_obj.value if prev_obj is not None
                              else s["value"])
                    ev = WatchEvent(DELETED, key, corpse, corpse, s["rev"])
                else:
                    fv = self._freeze(s["value"])
                    self._data[key] = StoredObject(
                        key=key, value=fv, mod_revision=s["rev"],
                        create_revision=(prev_obj.create_revision
                                         if prev_obj is not None
                                         else s["rev"]))
                    ev = WatchEvent(
                        s["op"], key, fv,
                        prev_obj.value if prev_obj is not None else None,
                        s["rev"])
                events.append(ev)
            self._rev = pending[-1]["rev"]
            self.applying_replicated = True
            try:
                self._append_batch(events)
            finally:
                self.applying_replicated = False
            return True

    def reset_from_state(self, state: dict, term: int = 0) -> None:
        """Snapshot install: replace the ENTIRE store contents with a
        leader's canonical :meth:`state` snapshot (a diverged or
        far-behind replica catching up). Every live watch is cancelled
        — clients relist, exactly like post-compaction — and on a
        durable store the snapshot is persisted and the WAL truncated,
        so recovery replays the installed state, not the divergent
        pre-install log. ``term``: the raft term of the snapshot's last
        entry, persisted with it so a post-install restart resumes the
        true log coordinate."""
        with self._lock:
            if term:
                self.wal_term = term
                self.last_entry_term = term
            for wch in list(self._watches):
                wch.cancel()
            old_keys = set(self._data)
            self._data = _PrefixIndexedMap()
            for k, v in state["data"].items():
                self._data[k] = StoredObject(
                    key=k, value=self._freeze(v["value"]),
                    mod_revision=v["mod_revision"],
                    create_revision=v["create_revision"])
            self._rev = state["rev"]
            # History before the install never happened here: resuming
            # watchers must relist (GoneError), like after compaction.
            self._compact_rev = self._rev
            self._log.clear()
            self._log_revs.clear()
            for key in old_keys | set(self._data):
                for hook in self._write_hooks:
                    hook(key)
            if self._data_dir:
                self.snapshot()
        invariants.note_store_reset(self)

    def guaranteed_update(
        self, key: str, fn: Callable[[Optional[dict]], Optional[dict]],
        create_if_missing: bool = False, max_retries: int = 100,
    ) -> tuple[dict, int]:
        """Retry-on-conflict read-modify-write (etcd3 ``GuaranteedUpdate``,
        ``store.go:263``). ``fn`` gets the current value (None if absent when
        ``create_if_missing``) and returns the new value, or None to abort."""
        for _ in range(max_retries):
            try:
                cur = self.get(key, copy=False)
                base, rev = cur.value, cur.mod_revision
            except errors.NotFoundError:
                if not create_if_missing:
                    raise
                base, rev = None, None
            new = fn(json.loads(json.dumps(base)) if base is not None else None)
            if new is None:
                return base, rev or 0
            try:
                if rev is None:
                    return new, self.create(key, new)
                return new, self.update(key, new, expected_revision=rev)
            except (errors.ConflictError, errors.AlreadyExistsError):
                continue
        raise errors.ConflictError(f"guaranteed_update on {key!r}: too much contention")

    # -- reads ------------------------------------------------------------

    def list(self, prefix: str, copy: bool = True) -> tuple[list[StoredObject], int]:
        with self._lock:
            items = [o for _k, o in self._data.prefix_items(prefix)]
            items.sort(key=lambda o: o.key)
            if copy:
                items = [StoredObject(o.key, self._freeze(o.value),
                                      o.mod_revision, o.create_revision)
                         for o in items]
            return items, self._rev

    def count(self, prefix: str) -> int:
        with self._lock:
            return self._data.prefix_count(prefix)

    @property
    def revision(self) -> int:
        with self._lock:
            return self._rev

    # -- watch ------------------------------------------------------------

    def register_watch_index(self, name: str, prefix: str,
                             extractor: Callable[[dict], Optional[str]]) -> None:
        """Declare a watch dispatch index: ``extractor(raw value dict)``
        returns the index value for any key under ``prefix`` (None/""
        = unindexed object). Watches opened with ``index=(name, value)``
        are delivered ONLY events whose current or previous value
        extracts to ``value`` — O(1) bucket dispatch instead of the
        O(watchers) prefix scan. The registry registers
        ``pods.spec.node_name`` so hollow-fleet width (one per-node
        field-selector watcher per node) costs one dict lookup per pod
        event, not 5k prefix checks + 5k typed decodes. Extractors run
        under the store lock on the write path: they must be cheap,
        non-raising dict lookups. Idempotent re-registration with the
        same prefix is allowed (LocalCluster restarts)."""
        with self._lock:
            old = self._watch_indexes.get(name)
            if old is not None and old[0] != prefix:
                raise ValueError(
                    f"watch index {name!r} already registered for "
                    f"prefix {old[0]!r}")
            self._watch_indexes[name] = (prefix, extractor)

    def watch(self, prefix: str, start_revision: int = 0,
              loop: Optional[asyncio.AbstractEventLoop] = None,
              index: Optional[tuple[str, str]] = None) -> Watch:
        """Stream events for keys under ``prefix`` with revision >
        ``start_revision``. Raises GoneError if that history was compacted
        (client must relist). ``start_revision=0`` means 'live only from
        now' (callers normally pass the revision a LIST returned).

        ``index=(name, value)`` subscribes via a registered dispatch
        index (see :meth:`register_watch_index`): the watch receives
        only events whose extracted value matches — a strict superset
        of what a ``field=value`` selector on that attribute matches,
        so selector filtering above stays correct and cheap.

        Must either be called on a running event loop or be given the
        ``loop`` events should be delivered to (worker threads pass the
        loop explicitly)."""
        if loop is None:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                raise RuntimeError(
                    "MVCCStore.watch() called with no running event loop; "
                    "pass loop= explicitly when watching from a worker thread"
                ) from None
        with self._lock:
            if index is not None and index[0] not in self._watch_indexes:
                raise ValueError(f"unknown watch index {index[0]!r}")
            if start_revision and start_revision < self._compact_rev:
                raise errors.GoneError(
                    f"revision {start_revision} compacted (compact_rev={self._compact_rev})"
                )
            wch = Watch(self, prefix, loop, start_revision=start_revision)
            wch.index = index
            if start_revision:
                # Replay filters by prefix only — the index applies to
                # live dispatch; a few extra replayed events are
                # dropped by the selector filter above.
                idx = bisect.bisect_right(self._log_revs, start_revision)
                for ev in self._log[idx:]:
                    if ev.key.startswith(prefix):
                        wch._deliver(ev)
            if not wch.overflowed:  # replay itself may have overflowed
                self._watches.append(wch)
                if index is not None:
                    self._watch_buckets.setdefault(index, []).append(wch)
                else:
                    self._plain_watches.append(wch)
            return wch

    def _remove_watch(self, wch: Watch) -> None:
        with self._lock:
            try:
                self._watches.remove(wch)
            except ValueError:
                pass
            index = getattr(wch, "index", None)
            if index is not None:
                bucket = self._watch_buckets.get(index)
                if bucket is not None:
                    try:
                        bucket.remove(wch)
                    except ValueError:
                        pass
                    if not bucket:
                        del self._watch_buckets[index]
            else:
                try:
                    self._plain_watches.remove(wch)
                except ValueError:
                    pass

    def compact(self, revision: int) -> int:
        """Online revision compaction (etcd ``Compact``): discard event
        history at or below ``revision`` and advance the compacted
        floor. Live state is untouched — ``state()``, reads, and WAL
        replay are byte-identical across a compaction; only how far
        back a NEW watch may resume changes (a ``start_revision`` below
        the floor gets GoneError/410 and the client relists).

        Already-attached watches need no cancellation: watch replay is
        serialized with compaction under the store lock, so any history
        a live watch was owed has been delivered before the trim, and
        its queued events are references unaffected by it. They are
        only FLAGGED (:attr:`Watch.compacted`) — the signal that a
        reconnect from their start revision would now 410.

        ``revision`` is clamped to the current revision; at or below
        the existing floor is a no-op. Returns the new floor.
        Replicated stores must only be compacted at or below the quorum
        commit revision (the registry compactor enforces this) so
        committed-never-lost is untouched."""
        with self._lock:
            revision = min(revision, self._rev)
            if revision <= self._compact_rev:
                return self._compact_rev
            idx = bisect.bisect_right(self._log_revs, revision)
            self._compact_rev = revision
            del self._log[:idx]
            del self._log_revs[:idx]
            self._compactions += 1
            for wch in self._watches:
                if wch.start_revision and wch.start_revision < revision:
                    wch.compacted = True
            return self._compact_rev

    # -- endurance observability ------------------------------------------
    # The numbers /debug/v1/storage serves and the endurance gate reads.

    @property
    def compact_rev(self) -> int:
        """Compacted floor: watches may not resume at or below this."""
        with self._lock:
            return self._compact_rev

    @property
    def wal_bytes(self) -> int:
        """WAL bytes since the last truncation (0 when not durable)."""
        with self._lock:
            return self._wal_bytes

    @property
    def wal_records(self) -> int:
        """WAL records since the last truncation (0 when not durable)."""
        with self._lock:
            return self._wal_records

    @property
    def wal_records_total(self) -> int:
        """Lifetime WAL records appended (survives rotation; 0 when
        not durable). With :attr:`wal_ops_total` this is the
        ``wal_records_per_create`` ratio the endurance gate asserts
        drops under batching."""
        with self._lock:
            return self._wal_records_total

    @property
    def wal_ops_total(self) -> int:
        """Lifetime logical write ops carried by those records."""
        with self._lock:
            return self._wal_ops_total

    @property
    def history_len(self) -> int:
        """Watch-replay event history currently retained in memory."""
        with self._lock:
            return len(self._log)

    @property
    def watcher_count(self) -> int:
        with self._lock:
            return len(self._watches)

    @property
    def indexed_watcher_count(self) -> int:
        """Watches riding a dispatch index bucket (fleet width minus
        the handful of informer/controller prefix scans)."""
        with self._lock:
            return sum(len(b) for b in self._watch_buckets.values())

    @property
    def compactions(self) -> int:
        """Explicit :meth:`compact` calls that advanced the floor."""
        with self._lock:
            return self._compactions

    @property
    def snapshots(self) -> int:
        """Snapshot+truncate cycles completed since this store opened
        (manual and threshold-triggered alike)."""
        with self._lock:
            return self._snapshots
