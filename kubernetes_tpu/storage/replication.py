"""Raft-lite quorum replication for the control plane.

Reference lineage: etcd's raft (``go.etcd.io/etcd/raft``) carrying the
apiserver's storage, compressed to the three mechanisms the cluster
actually needs and layered over the existing CRC-framed MVCC WAL
(storage/mvcc.py):

- **single-leader election** with durable term/vote records
  (``<data_dir>/raft.json``) and the standard log-completeness
  restriction: a vote is granted only to a candidate whose
  ``(last_term, last_rev)`` is at least the voter's — so an elected
  leader always holds every committed entry;
- **append-entries replication**: every local write on the leader's
  store is captured at the MVCC event seam and shipped, in revision
  order, to followers, which apply it through
  :meth:`~.mvcc.MVCCStore.apply_replicated` — into their own store,
  their own WAL, and their own watchers (followers are fully durable
  and fully watchable);
- **commit at quorum**: a write is acknowledged to the client
  (:meth:`ReplicaNode.wait_commit`, awaited by ``Registry.run``) only
  once a majority of replicas hold it. A leader that loses quorum fails
  the ack with 503 — the write may or may not survive, exactly etcd's
  "leader changed" answer, and clients retry (create → AlreadyExists
  on the survivor is the recovery signal).

Divergence recovery is deliberately blunt: a follower whose log cannot
be verified as a prefix of the leader's (a rejoining crashed ex-leader
with applied-but-uncommitted entries, or a laggard that outran the
bounded entry buffer) gets a full **snapshot install**
(:meth:`~.mvcc.MVCCStore.reset_from_state`) instead of per-entry
truncation — state transfer is cheap at this scale and cannot be
subtly wrong.

Determinism: election timeouts are drawn from a per-node rng stream
seeded ``f"{seed}:{node_id}"`` — the same contract the chaos layer
gives its sites — so which replica campaigns first is a pure function
of the seed, not of wall-clock noise, and TPU_SAN schedule exploration
replays elections. The in-process transport is the ``repl`` chaos site
(kinds: ``drop``, ``delay``, ``partition``).

Single-process path: a cluster composed WITHOUT a ReplicaNode touches
none of this — no hook, no guard, no wait — and stays byte-identical
to the unreplicated control plane. A ``replicas=1`` ReplicaSet elects
itself at the first timeout and commits every write immediately
(quorum of one).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import time
from dataclasses import dataclass
from typing import Optional

from ..analysis import interleave, invariants
from ..api import errors
from ..chaos import core as chaos
from ..metrics.registry import Counter, Gauge
from ..util.lockdep import make_lock
from ..util.tasks import spawn
from .mvcc import BATCH, MVCCStore, WatchEvent

log = logging.getLogger("replication")

FOLLOWER = "Follower"
CANDIDATE = "Candidate"
LEADER = "Leader"

REPL_ELECTIONS = Counter(
    "replication_elections_total",
    "Leader elections by node and outcome (won/lost/stepped_down)",
    labels=("node", "outcome"))

REPL_MESSAGES = Counter(
    "replication_messages_total",
    "Replication RPCs sent, by message type and result",
    labels=("type", "result"))

REPL_COMMIT_REV = Gauge(
    "replication_commit_revision",
    "Highest quorum-committed store revision, per node",
    labels=("node",))

REPL_TERM = Gauge(
    "replication_term",
    "Current raft term, per node",
    labels=("node",))

REPL_SNAPSHOT_INSTALLS = Counter(
    "replication_snapshot_installs_total",
    "Full state transfers to diverged/lagging followers",
    labels=("node",))

#: The follower write-guard reason (also the 503 detail clients see if
#: a write slips past the apiserver's redirect).
NOT_LEADER = ("not the replication leader; writes must go through the "
              "leader (follow the 307 Location hint)")


class ReplError(Exception):
    """Transport-level replication failure (drop/partition/peer dead).
    Handled like a lost packet: the next round retries."""


@dataclass(frozen=True)
class LogEntry:
    """One replicated write: the WAL record plus the term it was
    appended under (the conflict-detection coordinate)."""
    term: int
    rev: int
    op: str
    key: str
    value: Optional[dict]

    def to_wire(self) -> dict:
        return {"term": self.term, "rev": self.rev, "op": self.op,
                "key": self.key, "value": self.value}

    @staticmethod
    def from_wire(d: dict) -> "LogEntry":
        return LogEntry(d["term"], d["rev"], d["op"], d["key"], d["value"])


class LocalTransport:
    """In-process replica-to-replica RPC fabric — every control-plane
    composition in this repo runs its replicas on one event loop (the
    chaos/tpusan harness shape), so the transport is direct coroutine
    dispatch with the ``repl`` chaos site in front of every send.

    Fault kinds: ``drop`` (this message is lost), ``delay`` (param
    seconds of added latency), ``partition`` (the DESTINATION node is
    unreachable — both directions — for param seconds). Harnesses may
    also partition explicitly via :meth:`partition`.
    """

    def __init__(self):
        self._nodes: dict[str, "ReplicaNode"] = {}
        #: node_id -> monotonic deadline while partitioned.
        self._partitioned: dict[str, float] = {}

    def register(self, node: "ReplicaNode") -> None:
        self._nodes[node.node_id] = node

    def unregister(self, node_id: str) -> None:
        self._nodes.pop(node_id, None)

    def peer_ids(self, exclude: str) -> list[str]:
        return sorted(n for n in self._nodes if n != exclude)

    def node(self, node_id: str) -> Optional["ReplicaNode"]:
        return self._nodes.get(node_id)

    def advertise_url(self, node_id: str) -> str:
        node = self._nodes.get(node_id)
        return node.advertise_url if node is not None else ""

    def partition(self, node_id: str, seconds: float) -> None:
        """Cut ``node_id`` off from every peer for ``seconds``."""
        self._partitioned[node_id] = time.monotonic() + seconds

    def _is_partitioned(self, node_id: str, now: float) -> bool:
        until = self._partitioned.get(node_id)
        if until is None:
            return False
        if now >= until:
            del self._partitioned[node_id]
            return False
        return True

    async def call(self, src: str, dst: str, msg: dict) -> dict:
        mtype = msg.get("type", "?")
        c = chaos.CONTROLLER
        if c is not None:
            fault = c.decide(chaos.SITE_REPL)
            if fault is not None:
                if fault.kind == "drop":
                    REPL_MESSAGES.inc(type=mtype, result="dropped")
                    raise ReplError(f"chaos: {src}->{dst} {mtype} dropped")
                if fault.kind == "delay":
                    await asyncio.sleep(fault.param or 0.02)
                elif fault.kind == "partition":
                    self.partition(dst, fault.param or 0.5)
        now = time.monotonic()
        node = self._nodes.get(dst)
        if node is None or node.crashed \
                or self._is_partitioned(src, now) \
                or self._is_partitioned(dst, now):
            REPL_MESSAGES.inc(type=mtype, result="unreachable")
            raise ReplError(f"{src}->{dst} {mtype}: peer unreachable")
        REPL_MESSAGES.inc(type=mtype, result="ok")
        return await node.handle(src, msg)


class ReplicaNode:
    """One replica: an MVCC store plus its raft-lite persona.

    Lifecycle: :meth:`start` registers with the transport, arms the
    store's follower write guard, and runs the main loop (election
    ticker as follower, heartbeat/append rounds as leader).
    :meth:`stop` steps down cleanly; :meth:`crash` is the abrupt kill
    the failover scenarios use — tasks die mid-flight, the store is
    abandoned as-is, peers find out by timeout.
    """

    #: In-memory entry buffer for follower catch-up; a follower whose
    #: next needed entry fell out of the buffer gets a snapshot.
    MAX_BUFFER = 4096

    def __init__(self, node_id: str, store: MVCCStore,
                 transport: LocalTransport, *, seed: int = 0,
                 heartbeat_interval: float = 0.03,
                 election_timeout: float = 0.15,
                 commit_timeout: float = 5.0,
                 advertise_url: str = "", group: str = "control-plane"):
        self.node_id = node_id
        self.store = store
        self.transport = transport
        self.group = group
        self.advertise_url = advertise_url
        self.heartbeat_interval = heartbeat_interval
        self.election_timeout = election_timeout
        self.commit_timeout = commit_timeout
        #: Seeded per-node stream: the election-timeout sequence (and so
        #: the campaign order across replicas) replays by seed.
        self._rng = random.Random(f"{seed}:{node_id}")
        self.state = FOLLOWER
        self.term = 0
        self.voted_for = ""
        self.leader_id: Optional[str] = None
        self.crashed = False
        self.commit_rev = store.revision
        #: Monotonic stamp of the last append/snapshot observed from a
        #: live leader — the follower-read staleness clock: a follower
        #: that heard from its leader within the client's bound serves
        #: the read; one that has not (partition, election) answers
        #: 503 + X-Ktpu-Stale so the client falls back to the leader.
        self.last_leader_contact: Optional[float] = None
        #: Last log coordinate. A fresh store boots the common term-0
        #: base; a RECOVERED store resumes the term its last durable
        #: record was written under (persisted in every WAL record and
        #: the snapshot) — without it, a rebooted replica would claim
        #: term 0 for its whole log and vote for candidates with
        #: older, shorter logs, un-electing its own committed entries.
        self.last_rev = store.revision
        self.last_term = store.last_entry_term
        self._base_rev = store.revision
        self._base_term = store.last_entry_term
        self._entries: dict[int, LogEntry] = {}
        self._buf_lock = make_lock(f"replication.{node_id}.buffer")
        self._next_rev: dict[str, int] = {}
        self._match_rev: dict[str, int] = {}
        self._commit_waiters: list[tuple[int, asyncio.Future]] = []
        self._kick = asyncio.Event()
        self._hb_seen = asyncio.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._tasks: set = set()
        self._main_task: Optional[asyncio.Task] = None
        self._load_term_state()
        store.writes_blocked = NOT_LEADER
        store.add_event_hook(self._on_store_event)
        store.add_txn_hook(self._on_store_txn)
        invariants.register_replica_store(self.group, self.node_id, store)

    # -- durable term/vote ------------------------------------------------

    def _raft_path(self) -> Optional[str]:
        d = self.store._data_dir
        return os.path.join(d, "raft.json") if d else None

    def _load_term_state(self) -> None:
        path = self._raft_path()
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                st = json.load(f)
            self.term = int(st.get("term", 0))
            self.voted_for = st.get("voted_for", "")
        except (OSError, ValueError, json.JSONDecodeError) as e:
            # A torn raft.json is a fresh follower, not a crash loop:
            # the worst case is voting twice in an old term, which the
            # vote-counting quorum still tolerates for a kill-restart.
            log.warning("%s: unreadable raft state %s: %s — starting at "
                        "term 0", self.node_id, path, e)

    def _persist_term_state(self) -> None:
        path = self._raft_path()
        if not path:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _set_term(self, term: int, voted_for: str) -> None:
        self.term = term
        self.voted_for = voted_for
        REPL_TERM.set(float(term), node=self.node_id)
        self._persist_term_state()

    # -- lifecycle --------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER and not self.crashed

    def leader_hint(self) -> str:
        """The current leader's advertised client URL, or ""."""
        if self.leader_id is None:
            return ""
        return self.transport.advertise_url(self.leader_id)

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self.transport.register(self)
        self._main_task = spawn(self._main(),
                                name=f"replica-{self.node_id}",
                                store=self._tasks)

    async def stop(self) -> None:
        """Clean shutdown: step down so peers elect without waiting out
        the election timeout... they still must time out (no explicit
        abdication message — crash-only, like the rest of the repo)."""
        self.crashed = True
        self._step_down(self.term, leader=None)
        if self._main_task is not None:
            self._main_task.cancel()
            try:
                await self._main_task
            except asyncio.CancelledError:
                pass
        self.transport.unregister(self.node_id)

    def crash(self) -> None:
        """Abrupt kill: the process is gone mid-flight. The store is
        abandoned exactly as-is (whatever reached ITS wal is what a
        restart would recover); peers notice only by missed
        heartbeats."""
        self.crashed = True
        # The frozen store may hold a divergent uncommitted tail (the
        # minority-holder case a rejoin snapshots away) — tell the
        # sanitizer to exclude it from the committed-never-lost sweep
        # until a rebuild re-registers the node.
        invariants.note_replica_down(self.group, self.node_id)
        for t in list(self._tasks):
            t.cancel()
        self._fail_waiters("replica crashed before the write committed")

    # -- local write capture (leader side) --------------------------------

    def _on_store_event(self, ev: WatchEvent) -> None:
        # Runs under the store lock, possibly from a worker thread
        # (Registry.run dispatches durable-store mutations to_thread).
        if self.store.applying_replicated:
            return  # a replicated apply, not a local write
        if self.store.in_txn:
            # A txn's per-event hooks: the whole chunk arrives once
            # through _on_store_txn as ONE log entry — capturing the
            # sub-writes here too would double-ship them.
            return
        # The entry's term is what the WAL record was STAMPED with
        # (store.wal_term, read under the same store lock) — not
        # self.term, which a concurrent step-down on the event loop may
        # already have advanced past the term this write really ran
        # under; a mismatch would let a divergent uncommitted tail pass
        # the overlap term check and survive.
        entry = LogEntry(self.store.wal_term, ev.revision, ev.type, ev.key,
                         ev.value)
        with self._buf_lock:
            self._entries[ev.revision] = entry
            self.last_rev = ev.revision
            self.last_term = entry.term
            self._trim_buffer()
        if self._loop is not None and not self.crashed:
            try:
                self._loop.call_soon_threadsafe(self._kick.set)
            except RuntimeError:
                pass  # loop already closed: shutdown race, nothing to ship

    def _on_store_txn(self, events: list[WatchEvent]) -> None:
        # One committed MVCCStore.txn -> ONE log entry carrying all N
        # sub-writes (mirroring the one WAL record on disk). Same
        # threading contract as _on_store_event. Every covered revision
        # maps to the SAME entry object so _term_at and the catch-up
        # scan resolve mid-batch revisions; the wire builder dedupes by
        # identity.
        if self.store.applying_replicated:
            return
        subs = [{"rev": ev.revision, "op": ev.type, "key": ev.key,
                 "value": ev.value} for ev in events]
        entry = LogEntry(self.store.wal_term, events[-1].revision, BATCH,
                         "", {"ops": subs})
        with self._buf_lock:
            for ev in events:
                self._entries[ev.revision] = entry
            self.last_rev = entry.rev
            self.last_term = entry.term
            self._trim_buffer()
        if self._loop is not None and not self.crashed:
            try:
                self._loop.call_soon_threadsafe(self._kick.set)
            except RuntimeError:
                pass  # loop already closed: shutdown race, nothing to ship

    def _trim_buffer(self) -> None:
        # Only committed entries may be dropped — an uncommitted entry
        # still needs shipping; a follower that needs a dropped one
        # gets a snapshot instead.
        while len(self._entries) > self.MAX_BUFFER:
            oldest = min(self._entries)
            if oldest > self.commit_rev:
                break
            del self._entries[oldest]

    def _term_at(self, rev: int) -> Optional[int]:
        e = self._entries.get(rev)
        if e is not None:
            return e.term
        if rev == self._base_rev:
            return self._base_term
        if rev < self._base_rev and self._base_term == 0:
            return 0
        return None

    # -- main loop --------------------------------------------------------

    def next_election_timeout(self) -> float:
        """Seeded jitter in [T, 2T): the sequence — and therefore which
        replica campaigns first — replays by (seed, node_id)."""
        return self.election_timeout * (1.0 + self._rng.random())

    async def _main(self) -> None:
        while not self.crashed:
            interleave.touch(f"repl:{self.node_id}")
            if self.state == LEADER:
                await self._lead_round()
                try:
                    await asyncio.wait_for(self._kick.wait(),
                                           self.heartbeat_interval)
                except asyncio.TimeoutError:
                    pass
                self._kick.clear()
            else:
                try:
                    await asyncio.wait_for(self._hb_seen.wait(),
                                           self.next_election_timeout())
                    self._hb_seen.clear()
                except asyncio.TimeoutError:
                    await self._campaign()

    # -- election ---------------------------------------------------------

    async def _campaign(self) -> None:
        self._set_term(self.term + 1, voted_for=self.node_id)
        self.state = CANDIDATE
        self.leader_id = None
        term = self.term
        peers = self.transport.peer_ids(exclude=self.node_id)
        log.info("%s: campaigning in term %d (%d peers)",
                 self.node_id, term, len(peers))
        with self._buf_lock:
            last_rev, last_term = self.last_rev, self.last_term
        msg = {"type": "vote", "term": term, "candidate": self.node_id,
               "last_rev": last_rev, "last_term": last_term}

        async def ask(peer: str):
            try:
                return await asyncio.wait_for(
                    self.transport.call(self.node_id, peer, msg),
                    self.election_timeout)
            except (ReplError, asyncio.TimeoutError) as e:
                log.debug("%s: vote request to %s failed: %s",
                          self.node_id, peer, e)
                return None

        results = await asyncio.gather(*(ask(p) for p in peers))
        if self.crashed or self.term != term or self.state != CANDIDATE:
            return  # a heartbeat or higher term arrived mid-campaign
        votes = 1  # self
        for r in results:
            if r is None:
                continue
            if r.get("term", 0) > self.term:
                self._step_down(r["term"])
                return
            if r.get("granted") and r.get("term") == term:
                votes += 1
        if 2 * votes > len(peers) + 1:
            self._become_leader()
        else:
            REPL_ELECTIONS.inc(node=self.node_id, outcome="lost")
            self.state = FOLLOWER  # retry after the next seeded timeout

    def _become_leader(self) -> None:
        self.state = LEADER
        self.leader_id = self.node_id
        REPL_ELECTIONS.inc(node=self.node_id, outcome="won")
        invariants.note_leader(self.group, self.node_id, self.term)
        with self._buf_lock:
            nxt = self.last_rev + 1
        self._next_rev = {p: nxt
                          for p in self.transport.peer_ids(self.node_id)}
        self._match_rev = {p: 0
                           for p in self.transport.peer_ids(self.node_id)}
        # Open the store for local writes, stamped with our term so
        # the log coordinate is durable; the apiserver stops
        # redirecting the instant the guard flips.
        self.store.wal_term = self.term
        self.store.writes_blocked = None
        log.info("%s: leader for term %d at rev %d",
                 self.node_id, self.term, self.last_rev)
        self._kick.set()
        # A lone replica (or a full-quorum singleton round) commits on
        # its own vote; with peers the first append round advances it.
        self._advance_commit()

    def _step_down(self, term: int, leader: Optional[str] = None) -> None:
        if term > self.term:
            self._set_term(term, voted_for="")
        was = self.state
        self.state = FOLLOWER
        self.leader_id = leader
        self.store.writes_blocked = NOT_LEADER
        if was == LEADER:
            REPL_ELECTIONS.inc(node=self.node_id, outcome="stepped_down")
            log.warning("%s: stepped down in term %d", self.node_id, term)
            self._fail_waiters(
                "leadership lost before the write reached quorum; the "
                "write may or may not survive — retry against the new "
                "leader")

    # -- leader replication rounds ----------------------------------------

    async def _lead_round(self) -> None:
        peers = self.transport.peer_ids(exclude=self.node_id)
        if peers:
            await asyncio.gather(*(self._append_to(p) for p in peers))
        if self.state == LEADER:
            self._advance_commit()

    async def _append_to(self, peer: str) -> None:
        try:
            await self._append_to_inner(peer)
        except (ReplError, asyncio.TimeoutError) as e:
            log.debug("%s: append to %s failed: %s", self.node_id, peer, e)

    async def _append_to_inner(self, peer: str) -> None:
        with self._buf_lock:
            last_rev = self.last_rev
            nxt = self._next_rev.get(peer, last_rev + 1)
            missing = [r for r in range(nxt, last_rev + 1)
                       if r not in self._entries]
            entries: list[dict] = []
            if not missing:
                # A batch entry maps every covered revision to one
                # object — ship it once (identity dedupe), not once
                # per revision.
                prev_e = None
                for r in range(nxt, last_rev + 1):
                    e = self._entries[r]
                    if e is prev_e:
                        continue
                    entries.append(e.to_wire())
                    prev_e = e
        if missing and nxt <= last_rev:
            await self._install_snapshot(peer)
            return
        prev_rev = nxt - 1
        prev_term = self._term_at(prev_rev)
        if prev_term is None:
            await self._install_snapshot(peer)
            return
        msg = {"type": "append", "term": self.term, "leader": self.node_id,
               "prev_rev": prev_rev, "prev_term": prev_term,
               "entries": entries, "commit_rev": self.commit_rev}
        resp = await asyncio.wait_for(
            self.transport.call(self.node_id, peer, msg),
            self.election_timeout)
        if self.state != LEADER:
            return
        if resp.get("term", 0) > self.term:
            self._step_down(resp["term"])
            return
        if resp.get("ok"):
            # len(entries) undercounts when a batch entry covers
            # several revisions — the follower acked through last_rev
            # (the snapshot we captured the wire list under).
            shipped_to = last_rev if entries else prev_rev
            self._match_rev[peer] = max(self._match_rev.get(peer, 0),
                                        shipped_to)
            self._next_rev[peer] = self._match_rev[peer] + 1
            return
        if resp.get("conflict"):
            await self._install_snapshot(peer)
            return
        follower_last = resp.get("last_rev", 0)
        if follower_last < prev_rev:
            # Follower is behind the probe point: back up — but only if
            # its log tail verifiably matches ours there.
            t = self._term_at(follower_last)
            if t is None or t != resp.get("last_term", 0):
                await self._install_snapshot(peer)
            else:
                self._next_rev[peer] = follower_last + 1
        else:
            # ok=False with a log at/ahead of the probe: unverifiable.
            await self._install_snapshot(peer)

    async def _install_snapshot(self, peer: str) -> None:
        with self._buf_lock:
            last_rev, last_term = self.last_rev, self.last_term
        msg = {"type": "snapshot", "term": self.term,
               "leader": self.node_id, "state": self.store.state(),
               "last_term": last_term, "commit_rev": self.commit_rev}
        REPL_SNAPSHOT_INSTALLS.inc(node=peer)
        resp = await asyncio.wait_for(
            self.transport.call(self.node_id, peer, msg),
            max(1.0, self.election_timeout))
        if self.state != LEADER:
            return
        if resp.get("term", 0) > self.term:
            self._step_down(resp["term"])
            return
        if resp.get("ok"):
            self._match_rev[peer] = resp.get("last_rev", last_rev)
            self._next_rev[peer] = self._match_rev[peer] + 1

    def _advance_commit(self) -> None:
        if self.state != LEADER:
            return
        # Quorum over the REGISTERED membership, not just peers that
        # have acked something: a freshly joined replica widens the
        # cluster the instant it registers (its match defaults to 0),
        # so the majority can never be computed over a stale, smaller
        # cluster.
        peers = self.transport.peer_ids(exclude=self.node_id)
        with self._buf_lock:
            revs = sorted([self.last_rev]
                          + [self._match_rev.get(p, 0) for p in peers],
                          reverse=True)
        candidate = revs[len(revs) // 2]
        if candidate <= self.commit_rev:
            return
        # Raft's commit restriction: only a CURRENT-term entry advances
        # the commit index directly (older entries ride along). The
        # shared term-0 boot base is committed by construction.
        t = self._term_at(candidate)
        if candidate > self._base_rev and t != self.term:
            return
        self._set_commit(candidate)

    def _set_commit(self, rev: int) -> None:
        prev = self.commit_rev
        self.commit_rev = rev
        REPL_COMMIT_REV.set(float(rev), node=self.node_id)
        if invariants.SANITIZER is not None:
            for r in range(prev + 1, rev + 1):
                e = self._entries.get(r)
                if e is None:
                    continue
                if e.op == BATCH:
                    sub = next((s for s in e.value["ops"]
                                if s["rev"] == r), None)
                    if sub is not None:
                        invariants.note_commit(
                            self.group, sub["rev"], sub["op"],
                            sub["key"], sub["value"])
                else:
                    invariants.note_commit(self.group, e.rev, e.op, e.key,
                                           e.value)
        if self._commit_waiters:
            still = []
            for want, fut in self._commit_waiters:
                if want <= rev:
                    if not fut.done():
                        fut.set_result(None)
                else:
                    still.append((want, fut))
            self._commit_waiters = still

    def _fail_waiters(self, reason: str) -> None:
        waiters, self._commit_waiters = self._commit_waiters, []
        for _want, fut in waiters:
            if not fut.done():
                fut.set_exception(errors.ServiceUnavailableError(reason))

    async def wait_commit(self, rev: int) -> None:
        """Block until revision ``rev`` is quorum-committed — the ack
        gate ``Registry.run`` awaits before a write returns to its
        client. Raises ServiceUnavailable when leadership is lost or
        quorum stays unreachable past ``commit_timeout``: the write's
        fate is then genuinely unknown and the client must resolve it
        by reading (or by the AlreadyExists of its retry)."""
        if rev <= self.commit_rev:
            return
        if not self.is_leader:
            raise errors.ServiceUnavailableError(NOT_LEADER)
        fut = asyncio.get_running_loop().create_future()
        self._commit_waiters.append((rev, fut))
        self._kick.set()
        try:
            await asyncio.wait_for(fut, self.commit_timeout)
        except asyncio.TimeoutError:
            self._commit_waiters = [(w, f) for w, f in self._commit_waiters
                                    if f is not fut]
            raise errors.ServiceUnavailableError(
                f"write at revision {rev} did not reach quorum within "
                f"{self.commit_timeout}s") from None

    # -- follower handlers ------------------------------------------------

    async def handle(self, src: str, msg: dict) -> dict:
        interleave.touch(f"repl:{self.node_id}")
        mtype = msg.get("type")
        if mtype == "append":
            return self._handle_append(msg)
        if mtype == "vote":
            return self._handle_vote(msg)
        if mtype == "snapshot":
            return self._handle_snapshot(msg)
        raise ReplError(f"unknown replication message type {mtype!r}")

    def _observe_leader(self, msg: dict) -> None:
        if msg["term"] > self.term or self.state != FOLLOWER:
            self._step_down(msg["term"], leader=msg["leader"])
        self.leader_id = msg["leader"]
        self.last_leader_contact = time.monotonic()
        self._hb_seen.set()

    def read_staleness(self) -> float:
        """Seconds since this replica last heard from a live leader —
        the bounded-staleness answer for follower reads. 0 on the
        leader itself; +inf before any leader contact (elections, a
        just-booted replica): reads with ANY finite bound then fall
        back to the leader."""
        if self.is_leader:
            return 0.0
        if self.last_leader_contact is None:
            return float("inf")
        return max(0.0, time.monotonic() - self.last_leader_contact)

    def _handle_append(self, msg: dict) -> dict:
        if msg["term"] < self.term:
            return {"term": self.term, "ok": False, "stale": True}
        self._observe_leader(msg)
        with self._buf_lock:
            last_rev, last_term = self.last_rev, self.last_term
        if msg["prev_rev"] > last_rev:
            return {"term": self.term, "ok": False,
                    "last_rev": last_rev, "last_term": last_term}
        t = self._term_at(msg["prev_rev"])
        if t is None or t != msg["prev_term"]:
            return {"term": self.term, "ok": False, "conflict": True,
                    "last_rev": last_rev}
        for wire in msg["entries"]:
            e = LogEntry.from_wire(wire)
            if e.rev <= last_rev:
                # Overlap: already have it — but a TERM mismatch there
                # means our tail diverged (we were the minority holder
                # of an uncommitted entry) and must be rebuilt.
                mine = self._term_at(e.rev)
                if mine is not None and mine != e.term:
                    return {"term": self.term, "ok": False,
                            "conflict": True, "last_rev": last_rev}
                continue
            try:
                self.store.apply_replicated(e.op, e.key, e.value, e.rev,
                                            term=e.term)
            except errors.StatusError as e2:
                # This replica's own WAL died (chaos): it is crash-only
                # from here — stop participating, peers re-replicate.
                log.error("%s: apply of rev %d failed (%s); replica is "
                          "down until rebuilt", self.node_id, e.rev, e2)
                self.crash()
                raise ReplError(f"{self.node_id}: apply failed") from e2
            covered = ([s["rev"] for s in e.value["ops"]]
                       if e.op == BATCH else [e.rev])
            with self._buf_lock:
                for r in covered:
                    self._entries[r] = e
                self.last_rev, self.last_term = e.rev, e.term
                self._trim_buffer()
            last_rev = e.rev
        commit = min(msg.get("commit_rev", 0), last_rev)
        if commit > self.commit_rev:
            self._set_commit(commit)
        return {"term": self.term, "ok": True, "last_rev": last_rev}

    def _handle_vote(self, msg: dict) -> dict:
        if msg["term"] < self.term:
            return {"term": self.term, "granted": False}
        if msg["term"] > self.term:
            self._step_down(msg["term"])
        with self._buf_lock:
            mine = (self.last_term, self.last_rev)
        up_to_date = (msg["last_term"], msg["last_rev"]) >= mine
        if up_to_date and self.voted_for in ("", msg["candidate"]):
            self._set_term(self.term, voted_for=msg["candidate"])
            # Granting a vote defers our own campaign a full timeout —
            # without this, simultaneous timeouts livelock elections.
            self._hb_seen.set()
            return {"term": self.term, "granted": True}
        return {"term": self.term, "granted": False}

    def _handle_snapshot(self, msg: dict) -> dict:
        if msg["term"] < self.term:
            return {"term": self.term, "ok": False, "stale": True}
        self._observe_leader(msg)
        state = msg["state"]
        self.store.reset_from_state(state, term=msg["last_term"])
        with self._buf_lock:
            self._entries.clear()
            self.last_rev = state["rev"]
            self.last_term = msg["last_term"]
            self._base_rev = state["rev"]
            self._base_term = msg["last_term"]
        commit = min(msg.get("commit_rev", state["rev"]), state["rev"])
        if commit > self.commit_rev:
            self.commit_rev = commit
            REPL_COMMIT_REV.set(float(commit), node=self.node_id)
        log.info("%s: installed snapshot at rev %d (term %d)",
                 self.node_id, state["rev"], msg["term"])
        return {"term": self.term, "ok": True, "last_rev": self.last_rev}

    # -- introspection ----------------------------------------------------

    def status(self) -> dict:
        """The /ha/v1/status payload (and the failover harness's
        time-to-new-leader probe)."""
        return {"node": self.node_id, "state": self.state,
                "term": self.term, "leader": self.leader_id or "",
                "leader_url": self.leader_hint(),
                "commit_rev": self.commit_rev, "last_rev": self.last_rev,
                "crashed": self.crashed}


async def wait_for_leader(nodes: list, timeout: float = 5.0) -> ReplicaNode:
    """Poll until exactly one live node leads; returns it."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        leaders = [n for n in nodes if n.is_leader]
        if leaders:
            return leaders[0]
        if loop.time() > deadline:
            raise TimeoutError(
                f"no leader elected within {timeout}s: "
                f"{[n.status() for n in nodes]}")
        await asyncio.sleep(0.01)


async def wait_converged(nodes: list, timeout: float = 5.0) -> int:
    """Wait until every live node's store reached the leader's
    revision; returns that revision. Call with writes quiesced."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        live = [n for n in nodes if not n.crashed]
        target = max(n.store.revision for n in live)
        if all(n.store.revision >= target for n in live):
            return target
        if loop.time() > deadline:
            raise TimeoutError(
                f"replicas did not converge to rev {target} within "
                f"{timeout}s: {[n.status() for n in nodes]}")
        await asyncio.sleep(0.01)
