// Sanitizer driver for the native sub-mesh allocator (submesh.cpp).
//
// The KUBE_RACE analog for the repo's C++ (reference:
// hack/make-rules/test.sh:107 runs the Go suite under the race
// detector; Python has no TSAN, but the native fast path does).
// hack/race.sh compiles this file together with submesh.cpp under
// -fsanitize=thread and -fsanitize=address,undefined and runs it:
//
// - Phase 1 (TSAN): the production contract is many scheduler worker
//   calls against a shared read-only free-mask snapshot; N threads
//   hammer tpu_find_box concurrently on one mask. Any shared mutable
//   state inside the allocator is a bug TSAN flags.
// - Phase 2 (ASAN/UBSAN): randomized mesh/shape sweeps checking the
//   returned box is in bounds and actually free — out-of-bounds reads
//   or UB in the index arithmetic surface here.
//
// Exit 0 = clean. Any sanitizer report aborts with nonzero.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" int tpu_find_box(const uint8_t* free_mask, const int32_t* mesh_in,
                            const int32_t* shape_in, int32_t torus,
                            int32_t* out);

namespace {

// Deterministic LCG — sanitizer runs must reproduce.
uint32_t lcg(uint32_t& s) { return s = s * 1664525u + 1013904223u; }

void fill_mask(std::vector<uint8_t>& mask, uint32_t seed, int percent_free) {
  uint32_t s = seed;
  for (auto& m : mask) m = (lcg(s) % 100u) < static_cast<uint32_t>(percent_free);
}

int check_box(const std::vector<uint8_t>& mask, const int32_t mesh[3],
              const int32_t out[6]) {
  // out = {x, y, z, sx, sy, sz}; every covered chip must be free and
  // in bounds (modulo torus wrap which find_box may use).
  for (int dx = 0; dx < out[3]; ++dx)
    for (int dy = 0; dy < out[4]; ++dy)
      for (int dz = 0; dz < out[5]; ++dz) {
        int x = (out[0] + dx) % mesh[0];
        int y = (out[1] + dy) % mesh[1];
        int z = (out[2] + dz) % mesh[2];
        size_t idx = (static_cast<size_t>(x) * mesh[1] + y) * mesh[2] + z;
        if (idx >= mask.size() || !mask[idx]) return 0;
      }
  return 1;
}

}  // namespace

int main() {
  // Phase 1: concurrent readers over one shared mask (TSAN target).
  {
    const int32_t mesh[3] = {8, 8, 4};
    std::vector<uint8_t> mask(8 * 8 * 4);
    fill_mask(mask, 42, 70);
    std::vector<std::thread> threads;
    for (int t = 0; t < 8; ++t) {
      threads.emplace_back([&mask, &mesh, t] {
        const int32_t shapes[4][3] = {{2, 2, 1}, {4, 2, 2}, {1, 1, 4}, {8, 8, 4}};
        for (int i = 0; i < 200; ++i) {
          int32_t out[6];
          const int32_t* shape = shapes[(t + i) % 4];
          int rc = tpu_find_box(mask.data(), mesh, shape, i % 2, out);
          if (rc == 1 && !check_box(mask, mesh, out)) {
            std::fprintf(stderr, "thread %d: invalid box\n", t);
            std::exit(2);
          }
        }
      });
    }
    for (auto& th : threads) th.join();
  }

  // Phase 2: randomized single-thread sweep (ASAN/UBSAN target).
  {
    uint32_t s = 7;
    for (int iter = 0; iter < 500; ++iter) {
      int32_t mesh[3] = {static_cast<int32_t>(1 + lcg(s) % 8),
                         static_cast<int32_t>(1 + lcg(s) % 8),
                         static_cast<int32_t>(1 + lcg(s) % 4)};
      std::vector<uint8_t> mask(static_cast<size_t>(mesh[0]) * mesh[1] * mesh[2]);
      fill_mask(mask, lcg(s), static_cast<int>(lcg(s) % 101));
      int32_t shape[3] = {static_cast<int32_t>(1 + lcg(s) % 9),
                          static_cast<int32_t>(1 + lcg(s) % 9),
                          static_cast<int32_t>(1 + lcg(s) % 5)};
      int32_t out[6];
      int rc = tpu_find_box(mask.data(), mesh, shape, lcg(s) % 2, out);
      if (rc == 1 && !check_box(mask, mesh, out)) {
        std::fprintf(stderr, "iter %d: invalid box\n", iter);
        return 2;
      }
    }
  }
  std::puts("submesh sanitizer driver: OK");
  return 0;
}
