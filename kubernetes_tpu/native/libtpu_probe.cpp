// libtpu_probe — native TPU enumeration via the PJRT C API.
//
// The gonvml analog for TPUs (reference: vendor/github.com/mindprince/
// gonvml/bindings.go:19-30 dlopen()s libnvidia-ml.so and binds a
// handful of query functions behind function pointers so the kubelet
// never links the driver).  Here the driver-equivalent is libtpu.so,
// whose stable C surface is the PJRT C API: we dlopen it, resolve
// GetPjrtApi, create a client, and enumerate chips with mesh
// coordinates + HBM stats.
//
// Unlike NVML, libtpu is the *compute* runtime: creating a PJRT client
// takes ownership of the host's chips.  So this is a short-lived probe
// binary (crash-isolated from the node agent / device plugin, which
// exec it and parse one JSON line from stdout), not a resident daemon.
// The JSON contract matches the plugin's Python jax probe
// (deviceplugin/tpu_plugin.py _PROBE_SRC) so either can serve.
//
// Build: g++ -O2 -std=c++17 -I<dir containing xla/pjrt/c/pjrt_c_api.h>
//        libtpu_probe.cpp -ldl -o _libtpu_probe
// Run:   _libtpu_probe [path/to/libtpu.so]

#include <dlfcn.h>
#include <unistd.h>

#include <csignal>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

// JSON string escaping for the few vendor strings we emit.
std::string jesc(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 2);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

// Print {"tpu": false, ...} and exit 0: "no TPU" is a answer, not a
// failure — the caller treats a non-zero exit / garbage stdout as a
// crashed probe instead.
[[noreturn]] void no_tpu(const std::string& why) {
  std::printf("{\"tpu\": false, \"error\": \"%s\", \"source\": \"libtpu_probe\"}\n",
              jesc(why).c_str());
  std::exit(0);
}

std::string error_message(const PJRT_Api* api, PJRT_Error* err) {
  PJRT_Error_Message_Args margs;
  std::memset(&margs, 0, sizeof margs);
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.error = err;
  api->PJRT_Error_Message(&margs);
  std::string msg(margs.message, margs.message_size);
  PJRT_Error_Destroy_Args dargs;
  std::memset(&dargs, 0, sizeof dargs);
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.error = err;
  api->PJRT_Error_Destroy(&dargs);
  return msg;
}

#define CHECK_PJRT(api, call)                          \
  do {                                                 \
    PJRT_Error* _err = (call);                         \
    if (_err) no_tpu(error_message((api), _err));      \
  } while (0)

// An older same-major plugin's PJRT_Api struct may end before a member
// this (newer) header declares — dereferencing past api->struct_size
// would read garbage function pointers.  Guard every member that
// postdates the API's earliest revisions (pjrt_c_api.h:104 prescribes
// exactly this struct_size discipline).
#define API_HAS(api, field) \
  ((api)->struct_size > offsetof(PJRT_Api, field) && (api)->field != nullptr)

// The probe's whole contract is "always terminates with a JSON
// verdict", but PJRT_Client_Create inside libtpu can block forever on
// a host with no reachable TPU (or with the chips/lockfile held by
// another process) — observed wedging the caller for its full
// subprocess timeout. A SIGALRM watchdog turns that hang into the
// answer it actually is: tpu:false. Async-signal-safe by construction
// (write + _exit only); nothing is buffered on stdout until the final
// verdict, so the direct write cannot interleave with stdio output.
extern "C" void watchdog_fire(int) {
  static const char msg[] =
      "{\"tpu\": false, \"error\": \"watchdog: PJRT initialization did not "
      "terminate\", \"source\": \"libtpu_probe\"}\n";
  ssize_t n = write(STDOUT_FILENO, msg, sizeof msg - 1);
  (void)n;
  _exit(0);
}

}  // namespace

int main(int argc, char** argv) {
  // Watchdog before any PJRT call (see watchdog_fire above).
  // TPU_PROBE_TIMEOUT_S overrides; default leaves real-hardware init
  // (~10-20s cold) comfortable room.
  unsigned watchdog_s = 30;
  if (const char* w = std::getenv("TPU_PROBE_TIMEOUT_S")) {
    long v = std::strtol(w, nullptr, 10);
    if (v > 0) watchdog_s = static_cast<unsigned>(v);
  }
  std::signal(SIGALRM, watchdog_fire);
  alarm(watchdog_s);

  // Candidate library paths: an explicit argv[1] is authoritative (no
  // soname fallback — a caller that named a path wants THAT library,
  // and a surprise fallback would seize the host's chips); otherwise
  // $TPU_LIBRARY_PATH, then the soname the dynamic loader knows.
  std::vector<std::string> candidates;
  if (argc > 1) {
    candidates.push_back(argv[1]);
  } else {
    if (const char* p = std::getenv("TPU_LIBRARY_PATH")) candidates.push_back(p);
    candidates.push_back("libtpu.so");
  }

  void* handle = nullptr;
  std::string dlerr;
  for (const auto& c : candidates) {
    if (c.empty()) continue;  // dlopen("") resolves to the main program
    handle = dlopen(c.c_str(), RTLD_NOW | RTLD_LOCAL);
    if (handle) break;
    const char* e = dlerror();
    if (e) dlerr = e;
  }
  if (!handle) no_tpu("dlopen libtpu.so failed: " + dlerr);

  using GetPjrtApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetPjrtApiFn>(dlsym(handle, "GetPjrtApi"));
  if (!get_api) no_tpu("GetPjrtApi symbol missing (not a PJRT plugin)");
  const PJRT_Api* api = get_api();
  if (!api) no_tpu("GetPjrtApi returned null");
  if (api->pjrt_api_version.major_version != PJRT_API_MAJOR) {
    no_tpu("PJRT ABI major mismatch: plugin " +
           std::to_string(api->pjrt_api_version.major_version) +
           " vs header " + std::to_string(PJRT_API_MAJOR));
  }

  if (API_HAS(api, PJRT_Plugin_Initialize)) {
    PJRT_Plugin_Initialize_Args init;
    std::memset(&init, 0, sizeof init);
    init.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    CHECK_PJRT(api, api->PJRT_Plugin_Initialize(&init));
  }

  // Takes ownership of the chips for the probe's lifetime — the reason
  // this runs as a short-lived subprocess (see file docstring).
  PJRT_Client_Create_Args cc;
  std::memset(&cc, 0, sizeof cc);
  cc.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  CHECK_PJRT(api, api->PJRT_Client_Create(&cc));
  PJRT_Client* client = cc.client;
  // Client creation is the hang-prone call; past it, enumeration is
  // quick queries. Cancel the watchdog so a slow-but-successful probe
  // (real hardware, ~20s init) can't have its buffered true verdict
  // discarded by a late alarm firing mid-enumeration or during the
  // (potentially slow) client destroy below.
  alarm(0);

  PJRT_Client_PlatformName_Args pn;
  std::memset(&pn, 0, sizeof pn);
  pn.struct_size = PJRT_Client_PlatformName_Args_STRUCT_SIZE;
  pn.client = client;
  CHECK_PJRT(api, api->PJRT_Client_PlatformName(&pn));
  std::string platform(pn.platform_name, pn.platform_name_size);

  PJRT_Client_Devices_Args dv;
  std::memset(&dv, 0, sizeof dv);
  dv.struct_size = PJRT_Client_Devices_Args_STRUCT_SIZE;
  dv.client = client;
  CHECK_PJRT(api, api->PJRT_Client_Devices(&dv));

  std::string devices_json;
  int process_index = 0;
  for (size_t i = 0; i < dv.num_devices; ++i) {
    PJRT_Device* dev = dv.devices[i];

    PJRT_Device_IsAddressable_Args ia;
    std::memset(&ia, 0, sizeof ia);
    ia.struct_size = PJRT_Device_IsAddressable_Args_STRUCT_SIZE;
    ia.device = dev;
    CHECK_PJRT(api, api->PJRT_Device_IsAddressable(&ia));
    if (!ia.is_addressable) continue;  // local_devices() semantics

    PJRT_Device_GetDescription_Args gd;
    std::memset(&gd, 0, sizeof gd);
    gd.struct_size = PJRT_Device_GetDescription_Args_STRUCT_SIZE;
    gd.device = dev;
    CHECK_PJRT(api, api->PJRT_Device_GetDescription(&gd));
    PJRT_DeviceDescription* desc = gd.device_description;

    PJRT_DeviceDescription_Id_Args id;
    std::memset(&id, 0, sizeof id);
    id.struct_size = PJRT_DeviceDescription_Id_Args_STRUCT_SIZE;
    id.device_description = desc;
    CHECK_PJRT(api, api->PJRT_DeviceDescription_Id(&id));

    PJRT_DeviceDescription_ProcessIndex_Args pi;
    std::memset(&pi, 0, sizeof pi);
    pi.struct_size = PJRT_DeviceDescription_ProcessIndex_Args_STRUCT_SIZE;
    pi.device_description = desc;
    CHECK_PJRT(api, api->PJRT_DeviceDescription_ProcessIndex(&pi));
    process_index = pi.process_index;

    PJRT_DeviceDescription_Kind_Args kd;
    std::memset(&kd, 0, sizeof kd);
    kd.struct_size = PJRT_DeviceDescription_Kind_Args_STRUCT_SIZE;
    kd.device_description = desc;
    CHECK_PJRT(api, api->PJRT_DeviceDescription_Kind(&kd));
    std::string kind(kd.device_kind, kd.device_kind_size);

    // TPU PJRT publishes mesh position as the "coords" Int64List
    // attribute (what jax Device.coords reads); core_on_chip is a
    // scalar attribute on multi-core-per-chip generations.
    std::vector<int64_t> coords;
    int64_t core_on_chip = 0;
    PJRT_DeviceDescription_Attributes_Args at;
    std::memset(&at, 0, sizeof at);
    at.struct_size = PJRT_DeviceDescription_Attributes_Args_STRUCT_SIZE;
    at.device_description = desc;
    CHECK_PJRT(api, api->PJRT_DeviceDescription_Attributes(&at));
    for (size_t a = 0; a < at.num_attributes; ++a) {
      const PJRT_NamedValue& nv = at.attributes[a];
      std::string name(nv.name, nv.name_size);
      if (name == "coords" && nv.type == PJRT_NamedValue_kInt64List) {
        coords.assign(nv.int64_array_value,
                      nv.int64_array_value + nv.value_size);
      } else if (name == "core_on_chip" && nv.type == PJRT_NamedValue_kInt64) {
        core_on_chip = nv.int64_value;
      }
    }
    if (coords.empty()) coords = {id.id, 0, 0};

    std::string mem_json;
    if (API_HAS(api, PJRT_Device_MemoryStats)) {
      PJRT_Device_MemoryStats_Args ms;
      std::memset(&ms, 0, sizeof ms);
      ms.struct_size = PJRT_Device_MemoryStats_Args_STRUCT_SIZE;
      ms.device = dev;
      PJRT_Error* merr = api->PJRT_Device_MemoryStats(&ms);
      if (merr) {
        error_message(api, merr);  // UNIMPLEMENTED on some backends; drop
      } else if (ms.bytes_limit_is_set) {
        mem_json = ", \"memory\": {\"hbm_used_bytes\": " +
                   std::to_string(ms.bytes_in_use) +
                   ", \"hbm_total_bytes\": " + std::to_string(ms.bytes_limit) +
                   "}";
      }
    }

    std::string coords_json;
    for (size_t c = 0; c < coords.size(); ++c) {
      if (c) coords_json += ", ";
      coords_json += std::to_string(coords[c]);
    }
    if (!devices_json.empty()) devices_json += ", ";
    devices_json += "{\"index\": " + std::to_string(id.id) +
                    ", \"kind\": \"" + jesc(kind) +
                    "\", \"coords\": [" + coords_json +
                    "], \"core_on_chip\": " + std::to_string(core_on_chip) +
                    mem_json + "}";
  }

  bool is_tpu = platform.find("tpu") != std::string::npos || platform.find("axon") != std::string::npos;
  std::printf(
      "{\"tpu\": %s, \"backend\": \"%s\", \"process_index\": %d, "
      "\"pjrt_api\": \"%d.%d\", \"source\": \"libtpu_probe\", "
      "\"devices\": [%s]}\n",
      (is_tpu && !devices_json.empty()) ? "true" : "false",
      jesc(platform).c_str(), process_index,
      api->pjrt_api_version.major_version,
      api->pjrt_api_version.minor_version, devices_json.c_str());

  PJRT_Client_Destroy_Args cd;
  std::memset(&cd, 0, sizeof cd);
  cd.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
  cd.client = client;
  PJRT_Error* derr = api->PJRT_Client_Destroy(&cd);
  if (derr) error_message(api, derr);
  return 0;
}
