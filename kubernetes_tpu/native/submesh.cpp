// Contiguous sub-mesh box search — native fast path.
//
// Same contract as kubernetes_tpu/scheduler/submesh.py:find_box (which
// is the reference implementation): given a free/occupied mask over a
// 3D (torus) chip mesh and a requested box shape, return the best free
// axis-aligned box over all axis permutations of the shape, scored by
// corner packing (fewest free neighbors outside the box).
//
// Design: a summed-area table over the mesh tiled 2x along each torus
// axis makes every "is this (possibly wrapped) box fully free?" test
// and every face-slab score O(1), so a full scan of all origins for
// one permutation is O(mesh volume). At 8k chips x 6 permutations this
// is well under a millisecond — the scale the Python reference scan
// (O(volume) per origin) cannot reach. The scheduler calls this per
// pod placement, so it is a hot path at density scale.
//
// Replaces the role of the reference's flat extended-resource counter
// (plugin/pkg/scheduler/core; no geometry there) with TPU ICI-aware
// placement. Exposed via ctypes — no pybind11 in this environment.

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

struct Prefix {
  // P has dims (tx+1, ty+1, tz+1); P[i][j][k] = sum of tiled mask over
  // [0,i) x [0,j) x [0,k).
  std::vector<int32_t> p;
  int ny1, nz1;

  inline int32_t at(int i, int j, int k) const {
    return p[(static_cast<int64_t>(i) * ny1 + j) * nz1 + k];
  }

  // Sum over [l0,h0) x [l1,h1) x [l2,h2) of the tiled mask.
  inline int32_t rect(int l0, int h0, int l1, int h1, int l2, int h2) const {
    return at(h0, h1, h2) - at(l0, h1, h2) - at(h0, l1, h2) - at(h0, h1, l2) +
           at(l0, l1, h2) + at(l0, h1, l2) + at(h0, l1, l2) - at(l0, l1, l2);
  }
};

void build_prefix(const uint8_t* mask, const int32_t m[3], bool torus,
                  int t[3], Prefix& pre) {
  for (int a = 0; a < 3; ++a) t[a] = torus ? 2 * m[a] : m[a];
  pre.ny1 = t[1] + 1;
  pre.nz1 = t[2] + 1;
  pre.p.assign(static_cast<size_t>(t[0] + 1) * pre.ny1 * pre.nz1, 0);
  for (int x = 0; x < t[0]; ++x)
    for (int y = 0; y < t[1]; ++y) {
      const uint8_t* row = mask + (static_cast<int64_t>(x % m[0]) * m[1] +
                                   (y % m[1])) * m[2];
      int32_t acc = 0;
      for (int z = 0; z < t[2]; ++z) {
        acc += row[z % m[2]];
        // P[x+1][y+1][z+1] = row acc + P[x][y+1][z+1] + P[x+1][y][z+1]
        //                    - P[x][y][z+1]
        pre.p[(static_cast<int64_t>(x + 1) * pre.ny1 + y + 1) * pre.nz1 + z + 1] =
            acc + pre.at(x, y + 1, z + 1) + pre.at(x + 1, y, z + 1) -
            pre.at(x, y, z + 1);
      }
    }
}

}  // namespace

extern "C" {

// free_mask: row-major uint8 over mesh dims, 1 = free chip.
// mesh, shape: 3 ints (pad with 1s for lower-rank meshes).
// On success returns 1 and fills out[0..2] = origin, out[3..5] = the
// winning permutation of shape. Returns 0 when no free box exists.
int tpu_find_box(const uint8_t* free_mask, const int32_t* mesh_in,
                 const int32_t* shape_in, int32_t torus_in, int32_t* out) {
  const bool torus = torus_in != 0;
  int32_t m[3] = {mesh_in[0], mesh_in[1], mesh_in[2]};
  int32_t s0[3] = {shape_in[0], shape_in[1], shape_in[2]};
  for (int a = 0; a < 3; ++a)
    if (m[a] <= 0 || s0[a] <= 0) return 0;

  int t[3];
  Prefix pre;
  build_prefix(free_mask, m, torus, t, pre);

  // Unique permutations in lexicographic order (matches the Python
  // fallback's sorted(set(permutations(shape)))).
  int32_t perm[3] = {s0[0], s0[1], s0[2]};
  std::sort(perm, perm + 3);

  int64_t best_score = -1;
  int32_t best_origin[3] = {0, 0, 0}, best_shape[3] = {0, 0, 0};

  do {
    const int32_t s[3] = {perm[0], perm[1], perm[2]};
    if (s[0] > m[0] || s[1] > m[1] || s[2] > m[2]) continue;
    const int32_t vol = s[0] * s[1] * s[2];
    const int32_t hi[3] = {torus ? m[0] : m[0] - s[0] + 1,
                           torus ? m[1] : m[1] - s[1] + 1,
                           torus ? m[2] : m[2] - s[2] + 1};
    for (int o0 = 0; o0 < hi[0]; ++o0)
      for (int o1 = 0; o1 < hi[1]; ++o1)
        for (int o2 = 0; o2 < hi[2]; ++o2) {
          if (pre.rect(o0, o0 + s[0], o1, o1 + s[1], o2, o2 + s[2]) != vol)
            continue;
          // Corner-packing score: free cells in the face slabs adjacent
          // to the box (one cross-section per face; each slab cell is
          // the unique outside neighbor of one box cell).
          const int32_t o[3] = {o0, o1, o2};
          int64_t score = 0;
          for (int a = 0; a < 3; ++a) {
            if (s[a] >= m[a]) continue;  // box spans the ring: no outside
            int l[3] = {o[0], o[1], o[2]}, h[3] = {o[0] + s[0], o[1] + s[1],
                                                   o[2] + s[2]};
            if (torus) {
              int low = (o[a] - 1 + m[a]) % m[a];
              l[a] = low; h[a] = low + 1;
              score += pre.rect(l[0], h[0], l[1], h[1], l[2], h[2]);
              // m==2, s==1: the -1 and +1 neighbor of a box cell are the
              // same chip; the reference counts it once.
              if (!(m[a] == 2 && s[a] == 1)) {
                int high = (o[a] + s[a]) % m[a];
                l[a] = high; h[a] = high + 1;
                score += pre.rect(l[0], h[0], l[1], h[1], l[2], h[2]);
              }
            } else {
              if (o[a] - 1 >= 0) {
                l[a] = o[a] - 1; h[a] = o[a];
                score += pre.rect(l[0], h[0], l[1], h[1], l[2], h[2]);
              }
              if (o[a] + s[a] < m[a]) {
                l[a] = o[a] + s[a]; h[a] = o[a] + s[a] + 1;
                score += pre.rect(l[0], h[0], l[1], h[1], l[2], h[2]);
              }
            }
          }
          if (best_score < 0 || score < best_score) {
            best_score = score;
            best_origin[0] = o0; best_origin[1] = o1; best_origin[2] = o2;
            best_shape[0] = s[0]; best_shape[1] = s[1]; best_shape[2] = s[2];
            if (score == 0) goto done;
          }
        }
  } while (std::next_permutation(perm, perm + 3));

done:
  if (best_score < 0) return 0;
  out[0] = best_origin[0]; out[1] = best_origin[1]; out[2] = best_origin[2];
  out[3] = best_shape[0]; out[4] = best_shape[1]; out[5] = best_shape[2];
  return 1;
}

}  // extern "C"
