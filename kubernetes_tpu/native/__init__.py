"""Native (C++) fast paths, loaded via ctypes.

No pybind11 in this environment, so each native component is a small
C ABI (``extern "C"``) shared object compiled on demand with g++ and
bound with :mod:`ctypes`. Every native path has a pure-Python
reference implementation that is the semantic source of truth; the
native library is an accelerator, never a behavior change
(equivalence is enforced by tests/unit/test_submesh_native.py).

Currently shipped:

- ``submesh.cpp`` — contiguous sub-mesh box search used by the
  scheduler's TPU placement (see scheduler/submesh.py).
- ``tpu_hook.cpp`` — the container runtime hook binary (NVIDIA
  Container Runtime analog) injecting TPU device nodes + libtpu env
  (see node/runtimehook.py).
- ``libtpu_probe.cpp`` — chip-enumeration probe that dlopen()s
  libtpu.so and walks the PJRT C API (the gonvml analog,
  vendor/github.com/mindprince/gonvml/bindings.go:19-30); exec'd by
  deviceplugin/tpu_plugin.py as a crash-isolated subprocess.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "submesh.cpp")
_LIB = os.path.join(_DIR, "_submesh.so")

_submesh_lib: Optional[ctypes.CDLL] = None
_submesh_tried = False


def _compile(src: str, out: str, flags: list[str], libs: list[str] = (),
             executable: bool = False, timeout: float = 120) -> None:
    """g++ src -> out atomically (tmp + rename survives races).
    ``libs`` (-l...) go after the source for correct link order."""
    fd, tmp = tempfile.mkstemp(dir=_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", *flags, src, *libs, "-o", tmp],
            check=True, capture_output=True, timeout=timeout)
        if executable:
            os.chmod(tmp, 0o755)
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _stale(out: str, src: str) -> bool:
    return (not os.path.exists(out)
            or os.path.getmtime(out) < os.path.getmtime(src))


def _build(src: str, lib: str) -> None:
    _compile(src, lib, ["-shared", "-fPIC"])


_HOOK_SRC = os.path.join(_DIR, "tpu_hook.cpp")
_HOOK_BIN = os.path.join(_DIR, "_tpu_hook")
_hook_path: Optional[str] = None
_hook_tried = False


def build_tpu_hook() -> Optional[str]:
    """Path to the runtime-hook binary, building it if needed; None
    when the toolchain is unavailable (callers use the Python
    fallback). Cached, including a negative result."""
    global _hook_path, _hook_tried
    if _hook_tried:
        return _hook_path
    _hook_tried = True
    try:
        if _stale(_HOOK_BIN, _HOOK_SRC):
            _compile(_HOOK_SRC, _HOOK_BIN, [], executable=True)
        _hook_path = _HOOK_BIN
    except Exception:
        _hook_path = None
    return _hook_path


_PROBE_SRC = os.path.join(_DIR, "libtpu_probe.cpp")
_PROBE_BIN = os.path.join(_DIR, "_libtpu_probe")
_probe_path: Optional[str] = None
_probe_tried = False


def _pjrt_include_dir() -> Optional[str]:
    """A directory containing xla/pjrt/c/pjrt_c_api.h (the PJRT C API
    is header-only; the tensorflow wheel ships it)."""
    # Explicit operator override wins (mirrors TPU_LIBRARY_PATH
    # precedence in deviceplugin/tpu_plugin.py _find_libtpu).
    candidates = [os.environ.get("PJRT_C_API_INCLUDE", "")]
    try:
        import importlib.util
        spec = importlib.util.find_spec("tensorflow")  # located, NOT imported
        if spec and spec.submodule_search_locations:
            candidates.append(os.path.join(
                list(spec.submodule_search_locations)[0], "include"))
    except (ImportError, ValueError, AttributeError):
        pass  # tensorflow absent/unlocatable: other candidates remain
    for cand in candidates:
        if cand and os.path.exists(
                os.path.join(cand, "xla", "pjrt", "c", "pjrt_c_api.h")):
            return cand
    return None


def build_libtpu_probe() -> Optional[str]:
    """Path to the libtpu probe binary, building it if needed; None
    when the toolchain or the PJRT header is unavailable (callers use
    the Python jax probe). Cached, including a negative result."""
    global _probe_path, _probe_tried
    if _probe_tried:
        return _probe_path
    _probe_tried = True
    try:
        if _stale(_PROBE_BIN, _PROBE_SRC):
            inc = _pjrt_include_dir()
            if inc is None:
                _probe_path = None
                return None
            _compile(_PROBE_SRC, _PROBE_BIN, ["-I", inc], libs=["-ldl"],
                     executable=True, timeout=300)
        _probe_path = _PROBE_BIN
    except Exception:
        _probe_path = None
    return _probe_path


def load_submesh() -> Optional[ctypes.CDLL]:
    """The submesh shared library, building it if needed.

    Returns None when g++ is unavailable or the build fails; callers
    fall back to the Python implementation. Result is cached (including
    a negative result) for the process lifetime.
    """
    global _submesh_lib, _submesh_tried
    if _submesh_tried:
        return _submesh_lib
    _submesh_tried = True
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build(_SRC, _LIB)
        lib = ctypes.CDLL(_LIB)
        lib.tpu_find_box.restype = ctypes.c_int
        lib.tpu_find_box.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),   # free mask
            ctypes.POINTER(ctypes.c_int32),   # mesh[3]
            ctypes.POINTER(ctypes.c_int32),   # shape[3]
            ctypes.c_int32,                   # torus
            ctypes.POINTER(ctypes.c_int32),   # out[6]
        ]
        _submesh_lib = lib
    except Exception:
        _submesh_lib = None
    return _submesh_lib
