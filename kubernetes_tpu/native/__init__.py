"""Native (C++) fast paths, loaded via ctypes.

No pybind11 in this environment, so each native component is a small
C ABI (``extern "C"``) shared object compiled on demand with g++ and
bound with :mod:`ctypes`. Every native path has a pure-Python
reference implementation that is the semantic source of truth; the
native library is an accelerator, never a behavior change
(equivalence is enforced by tests/unit/test_submesh_native.py).

Currently shipped:

- ``submesh.cpp`` — contiguous sub-mesh box search used by the
  scheduler's TPU placement (see scheduler/submesh.py).
- ``tpu_hook.cpp`` — the container runtime hook binary (NVIDIA
  Container Runtime analog) injecting TPU device nodes + libtpu env
  (see node/runtimehook.py).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "submesh.cpp")
_LIB = os.path.join(_DIR, "_submesh.so")

_submesh_lib: Optional[ctypes.CDLL] = None
_submesh_tried = False


def _build(src: str, lib: str) -> None:
    """Compile src -> lib atomically (tmp + rename survives races)."""
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


_HOOK_SRC = os.path.join(_DIR, "tpu_hook.cpp")
_HOOK_BIN = os.path.join(_DIR, "_tpu_hook")
_hook_path: Optional[str] = None
_hook_tried = False


def build_tpu_hook() -> Optional[str]:
    """Path to the runtime-hook binary, building it if needed; None
    when the toolchain is unavailable (callers use the Python
    fallback). Cached, including a negative result."""
    global _hook_path, _hook_tried
    if _hook_tried:
        return _hook_path
    _hook_tried = True
    try:
        if (not os.path.exists(_HOOK_BIN)
                or os.path.getmtime(_HOOK_BIN) < os.path.getmtime(_HOOK_SRC)):
            fd, tmp = tempfile.mkstemp(dir=_DIR)
            os.close(fd)
            try:
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", _HOOK_SRC, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.chmod(tmp, 0o755)
                os.replace(tmp, _HOOK_BIN)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        _hook_path = _HOOK_BIN
    except Exception:
        _hook_path = None
    return _hook_path


def load_submesh() -> Optional[ctypes.CDLL]:
    """The submesh shared library, building it if needed.

    Returns None when g++ is unavailable or the build fails; callers
    fall back to the Python implementation. Result is cached (including
    a negative result) for the process lifetime.
    """
    global _submesh_lib, _submesh_tried
    if _submesh_tried:
        return _submesh_lib
    _submesh_tried = True
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build(_SRC, _LIB)
        lib = ctypes.CDLL(_LIB)
        lib.tpu_find_box.restype = ctypes.c_int
        lib.tpu_find_box.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),   # free mask
            ctypes.POINTER(ctypes.c_int32),   # mesh[3]
            ctypes.POINTER(ctypes.c_int32),   # shape[3]
            ctypes.c_int32,                   # torus
            ctypes.POINTER(ctypes.c_int32),   # out[6]
        ]
        _submesh_lib = lib
    except Exception:
        _submesh_lib = None
    return _submesh_lib
