// tpu_hook — native container runtime hook for TPU access.
//
// Reference analog: the NVIDIA Container Runtime selected via docker
// hooks (pkg/kubelet/dockershim/docker_hooks.go:139-160) — a native
// pre-start step that injects device nodes + driver libraries into a
// container. The TPU equivalent discovers the chip device nodes
// (/dev/accel* or VFIO) and libtpu.so, and emits the env/device
// directives the runtime merges into the container config.
//
// Protocol (line-based; no JSON so the binary has zero deps):
//   stdin:   chip <chip-id>        (one per assigned chip; may be none)
//            allow-missing         (dev boxes: no devices is not fatal)
//            dev-root <path>       (tests: scan here instead of /dev)
//   stdout:  device <path>
//            env <KEY>=<VALUE>
//   exit 0 = ok; exit 1 = requested chips but no device access.
//
// Built on demand by kubernetes_tpu/native/__init__.py (g++ -O2), like
// submesh.cpp; the Python fallback in node/runtimehook.py mirrors the
// same discovery and is the semantic source of truth.

#include <dirent.h>
#include <string.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <string>
#include <vector>

static bool exists(const std::string& p) {
  struct stat st;
  return ::stat(p.c_str(), &st) == 0;
}

static std::vector<std::string> scan_devices(const std::string& dev_root) {
  std::vector<std::string> found;
  // TPU-VM device nodes: /dev/accel0..N (newer stacks) or /dev/vfio.
  DIR* d = ::opendir(dev_root.c_str());
  if (d != nullptr) {
    while (dirent* e = ::readdir(d)) {
      if (strncmp(e->d_name, "accel", 5) == 0) {
        found.push_back(dev_root + "/" + e->d_name);
      }
    }
    ::closedir(d);
  }
  if (found.empty() && exists(dev_root + "/vfio")) {
    found.push_back(dev_root + "/vfio");
  }
  return found;
}

static std::string find_libtpu() {
  const char* candidates[] = {
      "/usr/lib/libtpu.so",
      "/usr/local/lib/libtpu.so",
      "/lib/libtpu.so",
  };
  for (const char* c : candidates) {
    if (exists(c)) return c;
  }
  // pip-installed libtpu (the TPU-VM default): probe the venv.
  const char* venv = ::getenv("VIRTUAL_ENV");
  if (venv != nullptr) {
    std::string p = std::string(venv) + "/lib";
    DIR* d = ::opendir(p.c_str());
    if (d != nullptr) {
      while (dirent* e = ::readdir(d)) {
        std::string sub = p + "/" + e->d_name + "/site-packages/libtpu/libtpu.so";
        if (e->d_name[0] != '.' && exists(sub)) {
          ::closedir(d);
          return sub;
        }
      }
      ::closedir(d);
    }
  }
  return "";
}

int main() {
  std::vector<std::string> chips;
  bool allow_missing = false;
  std::string dev_root = "/dev";

  char line[4096];
  while (fgets(line, sizeof line, stdin) != nullptr) {
    std::string s(line);
    while (!s.empty() && (s.back() == '\n' || s.back() == '\r')) s.pop_back();
    if (s.rfind("chip ", 0) == 0) {
      chips.push_back(s.substr(5));
    } else if (s == "allow-missing") {
      allow_missing = true;
    } else if (s.rfind("dev-root ", 0) == 0) {
      dev_root = s.substr(9);
    }
  }

  std::vector<std::string> devices = scan_devices(dev_root);
  if (devices.empty() && !chips.empty() && !allow_missing) {
    fprintf(stderr,
            "tpu_hook: container assigned %zu chip(s) but no TPU device "
            "nodes under %s\n",
            chips.size(), dev_root.c_str());
    return 1;
  }
  for (const std::string& dev : devices) {
    printf("device %s\n", dev.c_str());
  }
  std::string libtpu = find_libtpu();
  if (!libtpu.empty()) {
    printf("env TPU_LIBRARY_PATH=%s\n", libtpu.c_str());
  }
  if (!devices.empty()) {
    printf("env TPU_RUNTIME_HOOK=native\n");
  }
  // Chip visibility is already decided by the scheduler + device
  // plugin; the hook just confirms device access exists.
  return 0;
}
