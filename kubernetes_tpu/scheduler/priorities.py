"""Scoring — rank feasible nodes.

Reference: ``plugin/pkg/scheduler/algorithm/priorities`` (least
requested, balanced allocation, selector spread, node-affinity
preference; map-reduce over nodes). TPU addition:
:func:`tpu_defrag_score` prefers nodes where the allocation keeps the
slice's free space contiguous — the scoring half of the fragmentation
fight (no reference analog; its matcher is flat).
"""
from __future__ import annotations

from ..api import types as t
from .cache import NodeInfo
from .submesh import allocate_compact, find_box

MAX_SCORE = 10.0


def least_requested(pod: t.Pod, info: NodeInfo, want=None) -> float:
    """Favor idle nodes (spreads load). ``want``: precomputed
    pod_resource_requests (prioritize() computes it once per pod; the
    per-(pod,node) recompute dominated density profiles)."""
    alloc = info.allocatable()
    if want is None:
        want = t.pod_resource_requests(pod)
    score = 0.0
    n = 0
    for res in (t.RESOURCE_CPU, t.RESOURCE_MEMORY):
        cap = alloc.get(res, 0.0)
        if cap <= 0:
            continue
        used = info.requested.get(res, 0.0) + want.get(res, 0.0)
        score += max(0.0, (cap - used) / cap) * MAX_SCORE
        n += 1
    return score / n if n else MAX_SCORE / 2


def balanced_allocation(pod: t.Pod, info: NodeInfo, want=None) -> float:
    """Penalize skew between cpu and memory utilization."""
    alloc = info.allocatable()
    if want is None:
        want = t.pod_resource_requests(pod)
    fractions = []
    for res in (t.RESOURCE_CPU, t.RESOURCE_MEMORY):
        cap = alloc.get(res, 0.0)
        if cap <= 0:
            continue
        fractions.append(min(1.0, (info.requested.get(res, 0.0) + want.get(res, 0.0)) / cap))
    if len(fractions) < 2:
        return MAX_SCORE / 2
    return (1.0 - abs(fractions[0] - fractions[1])) * MAX_SCORE


def node_affinity_preferred(pod: t.Pod, info: NodeInfo,
                            want=None) -> float:
    aff = pod.spec.affinity
    if not aff or not aff.node_preferred or info.node is None:
        return 0.0
    labels = info.node.metadata.labels
    hits = sum(1 for term in aff.node_preferred if term.matches(labels))
    return MAX_SCORE * hits / len(aff.node_preferred)


def selector_spread(pod: t.Pod, info: NodeInfo, sibling_counts: dict[str, int]) -> float:
    """Fewer same-controller pods on the node = higher score (reference:
    SelectorSpreadPriority). ``sibling_counts``: node -> count, computed
    once per scheduling cycle by the caller."""
    if not sibling_counts:
        return MAX_SCORE / 2
    if info.node is None:
        return 0.0
    mine = sibling_counts.get(info.node.metadata.name, 0)
    worst = max(sibling_counts.values())
    if worst == 0:
        return MAX_SCORE
    return MAX_SCORE * (worst - mine) / worst


def tpu_defrag_score(pod: t.Pod, info: NodeInfo,
                     chosen_chip_ids: list[str] | None = None) -> float:
    """Prefer nodes where the claim packs into corners/used regions.

    Measures how many free chips remain adjacent to the chosen set —
    fewer exposed free neighbors means tighter packing and larger
    surviving boxes. ``chosen_chip_ids``: the concrete chips the caller
    already selected (avoids recomputing the geometry; the scheduler
    passes the output of ``select_chips``).
    """
    chips = t.pod_tpu_chip_count(pod)
    if not chips:
        return MAX_SCORE / 2
    topo = info.node.status.tpu if info.node else None
    if topo is None:
        return 0.0
    coords = info.free_coords()
    if len(coords) < chips:
        return 0.0
    free = set(coords)
    if chosen_chip_ids is not None:
        by_id = {cid: coord for coord, cid in coords.items()}
        cells = [by_id[cid] for cid in chosen_chip_ids if cid in by_id]
        if len(cells) != len(chosen_chip_ids):
            return 0.0
    else:
        shaped = next((c.slice_shape for c in pod.spec.tpu_resources if c.slice_shape), None)
        cells = (find_box(free, topo.mesh_shape, shaped) if shaped
                 else allocate_compact(free, topo.mesh_shape, chips))
    if not cells:
        return 0.0
    from .submesh import _packing_score
    exposure = _packing_score(list(cells), free, tuple(topo.mesh_shape))
    worst = 2 * len(cells) * len(topo.mesh_shape)  # all faces exposed
    return MAX_SCORE * (1.0 - exposure / worst) if worst else MAX_SCORE


def serving_topology_score(slice_free: set, mesh, chosen_cells,
                           before_volume: int | None = None,
                           torus: bool = True) -> float:
    """Score a serving replica's chip claim by how little it shrinks
    the slice's largest free contiguous box (``ServingTopologyAware``
    gate; the fleet-level complement of :func:`tpu_defrag_score`'s
    within-node packing).

    Large training gangs need whole axis-aligned boxes; a serving
    replica dropped into the middle of a pristine slice shreds a box no
    defrag pass can rebuild without migration. Damage = largest free
    box volume before the claim minus after; the score prefers the
    placement (usually an already-fragmented slice, or a corner) whose
    damage is smallest:

        score = MAX_SCORE * (1 - damage / before)

    ``before_volume``: memoized largest-box volume for this slice (the
    scheduler computes it once per slice per placement pass).
    """
    from .submesh import largest_free_box_volume
    if not chosen_cells:
        return MAX_SCORE / 2
    if before_volume is None:
        before_volume = largest_free_box_volume(slice_free, mesh, torus)
    if before_volume <= 0:
        return MAX_SCORE / 2
    after = largest_free_box_volume(
        set(slice_free) - set(chosen_cells), mesh, torus)
    damage = max(before_volume - after, 0)
    return MAX_SCORE * (1.0 - damage / before_volume)


#: Weight of the gated serving anti-fragmentation term (heavier than
#: defrag: protecting a slice-wide gang box outranks node-local
#: packing niceties when both disagree).
SERVING_TOPOLOGY_WEIGHT = 3.0


def resource_limits(pod: t.Pod, info: NodeInfo, want=None) -> float:
    """Score nodes able to satisfy the pod's LIMITS (not just requests)
    — burstable pods land where their ceiling actually fits.
    Reference: ``algorithm/priorities/resource_limits.go``
    (ResourceLimitsPriorityMap, alpha-gated in the fork,
    ``algorithmprovider/defaults/defaults.go:112-116``)."""
    limits: dict[str, float] = {}
    for c in pod.spec.containers:
        for res, amount in c.resources.limits.items():
            limits[res] = limits.get(res, 0.0) + t.parse_quantity(amount)
    if not limits:
        return 0.0
    alloc = info.allocatable()
    for res in (t.RESOURCE_CPU, t.RESOURCE_MEMORY):
        ceil_amt = limits.get(res)
        if ceil_amt and alloc.get(res, 0.0) - info.requested.get(res, 0.0) < ceil_amt:
            return 0.0
    return MAX_SCORE


#: Canonical policy-file keys (see predicates.py note on why these are
#: shared constants, not inline literals).
PRI_LEAST_REQUESTED = "LeastRequested"
PRI_BALANCED = "BalancedAllocation"
PRI_NODE_AFFINITY = "NodeAffinity"
PRI_RESOURCE_LIMITS = "ResourceLimits"
PRI_SELECTOR_SPREAD = "SelectorSpread"
PRI_TPU_DEFRAG = "TpuDefrag"
PRI_INTERPOD_AFFINITY = "InterPodAffinity"

#: (name, fn(pod, info) -> 0..10, weight)
DEFAULT_PRIORITIES = [
    (PRI_LEAST_REQUESTED, least_requested, 1.0),
    (PRI_BALANCED, balanced_allocation, 1.0),
    (PRI_NODE_AFFINITY, node_affinity_preferred, 2.0),
    (PRI_RESOURCE_LIMITS, resource_limits, 1.0),
]
TPU_DEFRAG_WEIGHT = 2.0


def prioritize(pod: t.Pod, infos: list[NodeInfo],
               sibling_counts: dict[str, int] | None = None,
               chip_choices: dict[str, list[str]] | None = None,
               weights: dict[str, float] | None = None) -> dict[str, float]:
    """``chip_choices``: node name -> chip ids already selected for this
    pod (from select_chips), so the defrag score reuses the geometry.
    ``weights``: policy-file priority weights (policy.py canonical
    names; unlisted = 0); None keeps the defaults below.

    One fused pass per node producing EXACTLY the sum the individual
    priority functions above give (they remain the documented,
    unit-testable definitions): scoring is the scheduler loop's
    dominant CPU at density scale — the four separate map calls each
    re-derived allocatable/requested fractions and re-checked
    pod-level facts per (pod, node), which starved the async bind
    pipeline and showed up as bind_call p99 in BENCH rest_30k."""
    scores: dict[str, float] = {}
    # Per-priority weights hoisted once (the default path multiplies by
    # the same constants the pre-weights code had inlined).
    if weights is None:
        w_lr = w_ba = w_lim = w_spread = 1.0
        w_aff = 2.0
        w_defrag = TPU_DEFRAG_WEIGHT
    else:
        g = weights.get
        w_lr = g(PRI_LEAST_REQUESTED, 0.0)
        w_ba = g(PRI_BALANCED, 0.0)
        w_aff = g(PRI_NODE_AFFINITY, 0.0)
        w_lim = g(PRI_RESOURCE_LIMITS, 0.0)
        w_spread = g(PRI_SELECTOR_SPREAD, 0.0)
        w_defrag = g(PRI_TPU_DEFRAG, 0.0)
    # Pod-level facts hoisted out of the per-node loop.
    want = t.pod_resource_requests(pod)
    want_cpu = want.get(t.RESOURCE_CPU, 0.0)
    want_mem = want.get(t.RESOURCE_MEMORY, 0.0)
    limits: dict[str, float] = {}
    for c in pod.spec.containers:
        for res, amount in c.resources.limits.items():
            limits[res] = limits.get(res, 0.0) + t.parse_quantity(amount)
    lim_cpu = limits.get(t.RESOURCE_CPU, 0.0)
    lim_mem = limits.get(t.RESOURCE_MEMORY, 0.0)
    aff = pod.spec.affinity
    preferred = (aff.node_preferred
                 if aff is not None and aff.node_preferred else None)
    chips = t.pod_tpu_chip_count(pod)
    worst_sib = max(sibling_counts.values()) if sibling_counts else 0
    half = MAX_SCORE / 2
    for info in infos:
        node = info.node
        if node is None:
            continue
        name = node.metadata.name
        alloc = info.allocatable()
        req = info.requested
        cap_cpu = alloc.get(t.RESOURCE_CPU, 0.0)
        cap_mem = alloc.get(t.RESOURCE_MEMORY, 0.0)
        req_cpu = req.get(t.RESOURCE_CPU, 0.0)
        req_mem = req.get(t.RESOURCE_MEMORY, 0.0)
        # LeastRequested + BalancedAllocation share the fractions.
        free_sum, n_res = 0.0, 0
        frac_cpu = frac_mem = None
        if cap_cpu > 0:
            frac_cpu = (req_cpu + want_cpu) / cap_cpu
            free_sum += max(0.0, 1.0 - frac_cpu)
            n_res += 1
        if cap_mem > 0:
            frac_mem = (req_mem + want_mem) / cap_mem
            free_sum += max(0.0, 1.0 - frac_mem)
            n_res += 1
        total = w_lr * ((free_sum / n_res * MAX_SCORE) if n_res else half)
        if frac_cpu is not None and frac_mem is not None:
            total += w_ba * (1.0 - abs(min(1.0, frac_cpu)
                                       - min(1.0, frac_mem))) * MAX_SCORE
        else:
            total += w_ba * half
        if preferred and w_aff:  # NodeAffinity, default weight 2
            labels = node.metadata.labels
            hits = sum(1 for term in preferred if term.matches(labels))
            total += w_aff * MAX_SCORE * hits / len(preferred)
        if limits and w_lim:  # ResourceLimits (0 when no limits)
            fits = not ((lim_cpu and cap_cpu - req_cpu < lim_cpu)
                        or (lim_mem and cap_mem - req_mem < lim_mem))
            total += w_lim * (MAX_SCORE if fits else 0.0)
        if not w_defrag:
            pass
        elif chips:
            total += w_defrag * tpu_defrag_score(
                pod, info, (chip_choices or {}).get(name))
        else:
            total += w_defrag * half
        if sibling_counts is not None and w_spread:
            if not sibling_counts:
                total += w_spread * half
            elif worst_sib == 0:
                total += w_spread * MAX_SCORE
            else:
                total += w_spread * MAX_SCORE * (
                    worst_sib - sibling_counts.get(name, 0)) / worst_sib
        scores[name] = total
    return scores
