"""Gang scheduling + cross-host sub-mesh allocation.

No reference analog: the reference schedules one pod at a time
(``scheduler.go:430 scheduleOne``) and SURVEY.md section 2.4 calls out
gang/co-scheduling as a first-class gap. Here a PodGroup's members are
placed **all-or-nothing**:

1. pick a slice (nodes sharing ``slice_id``) whose free chips can host
   the whole gang — as one contiguous box when the group demands a
   ``slice_shape``, else as a compact set;
2. split the box's cells by host and bin-pack member pods onto hosts
   (first-fit-decreasing; a pod's chips never span hosts — ICI between
   hosts is the mesh's job, PCIe locality is the pod's);
3. verify non-TPU predicates per pod on its host;
4. emit a bind plan: (pod, node, chip bindings). The caller assumes
   all members in the cache and posts all bindings, rolling back every
   assume if any bind fails.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api import types as t
from .cache import SchedulerCache, SliceInfo
from .predicates import (PRED_NODE_CONDITION, PRED_NODE_SELECTOR,
                         PRED_RESOURCES, PRED_TAINTS, _chip_matches,
                         node_is_schedulable, pod_fits_resources,
                         pod_matches_node_selector, pod_tolerates_taints)
from .submesh import allocate_compact, find_box, find_box_containing


@dataclass
class GangPlan:
    slice_id: str = ""
    #: (pod, node_name, tpu bindings) per member.
    placements: list = field(default_factory=list)


@dataclass
class GangFailure:
    reasons: list = field(default_factory=list)


def _pod_chip_demand(pod: t.Pod) -> int:
    return t.pod_tpu_chip_count(pod)


def _non_tpu_predicates(pod: t.Pod, info, enabled=None) -> Optional[str]:
    """``enabled``: policy-selected predicate set (policy.py canonical
    keys; None = all) — gangs honor the same policy as single pods."""
    node = info.node
    if node is None:
        return "node unknown"
    on = enabled.__contains__ if enabled is not None else lambda _k: True
    for check in (
            node_is_schedulable(node) if on(PRED_NODE_CONDITION) else None,
            pod_tolerates_taints(pod, node) if on(PRED_TAINTS) else None,
            pod_matches_node_selector(pod, node)
            if on(PRED_NODE_SELECTOR) else None,
            pod_fits_resources(pod, info) if on(PRED_RESOURCES) else None):
        if check:
            return check
    return None


def plan_gang(group: t.PodGroup, pods: list[t.Pod],
              cache: SchedulerCache,
              must_include: Optional[dict] = None,
              restrict_to: Optional[dict] = None,
              enabled=None) -> GangPlan | GangFailure:
    """``must_include``: coords -> (node, chip_id) already held by bound
    gang members (partial-bind recovery). A shaped gang must then find a
    full-shape box *containing* those coords, so the recovered gang is
    still one contiguous sub-mesh; only the unbound ``pods`` are
    planned.

    ``restrict_to``: coords -> (node, chip_id) the plan may draw from —
    the live-migration steering seam (GangLiveMigration): a requeued
    migrating gang is planned INTO its own reserved target box, not
    wherever the free-space search lands first (which would happily be
    the spot it just vacated, defeating the defrag move). The caller
    falls back to an unrestricted plan when this one fails."""
    reasons: list[str] = []
    tpu_pods = [p for p in pods if _pod_chip_demand(p) > 0]
    aux_pods = [p for p in pods if _pod_chip_demand(p) == 0]
    total_chips = sum(_pod_chip_demand(p) for p in tpu_pods)

    candidate_slices = list(cache.slices.values())
    if not candidate_slices and tpu_pods:
        return GangFailure(["no TPU slices known to the scheduler"])
    if not tpu_pods:
        # Pure-CPU gang: just need co-existing feasible nodes.
        plan = _plan_aux(aux_pods, cache, {}, [], enabled=enabled)
        if isinstance(plan, GangFailure):
            return plan
        return GangPlan(placements=plan)

    # Deterministic order: smallest adequate slice first (best fit).
    candidate_slices.sort(key=lambda s: (len(s.chips), s.slice_id))
    gang_priority = max((t.pod_priority(p) for p in pods), default=0)
    # Slice-independent: computed once, not per candidate slice.
    blocked = cache.reserved_node_chips(exclude_owner=group.key(),
                                        below_priority=gang_priority)
    on = enabled.__contains__ if enabled is not None else lambda _k: True

    # Hosts no member may land on (kmon's degraded taint, cordons)
    # must leave the free set BEFORE the box search: find_box is
    # deterministic, so an infeasible host inside the first-choice box
    # otherwise wedges the gang — the per-pod predicate pass below can
    # only reject the plan, never steer the search around the host.
    node_ok: dict[str, bool] = {}

    def _node_usable(node_name: str) -> bool:
        ok = node_ok.get(node_name)
        if ok is None:
            info = cache.nodes.get(node_name)
            node = info.node if info is not None else None
            ok = node is not None and \
                (not on(PRED_NODE_CONDITION)
                 or node_is_schedulable(node) is None) and \
                (not on(PRED_TAINTS) or any(
                    pod_tolerates_taints(p, node) is None
                    for p in tpu_pods))
            node_ok[node_name] = ok
        return ok

    for sl in candidate_slices:
        if must_include and not all(sl.chips.get(c) == nc
                                    for c, nc in must_include.items()):
            continue  # survivors' chips live elsewhere
        if restrict_to and not all(sl.chips.get(c) == nc
                                   for c, nc in restrict_to.items()):
            continue  # reserved target box lives on another slice
        free = sl.free(cache)  # coords -> (node, chip_id)
        if restrict_to:
            # Migration steering: only the reserved target box is in
            # play. The box is exactly gang-shaped, so find_box below
            # returns it or nothing.
            free = {c: v for c, v in free.items() if c in restrict_to}
        # Cells held for ANOTHER preemptor (gang-preemption box or a
        # nominated pod's chips) are off-limits to equal-or-lower
        # priority plans; this group's own reservation is its to use.
        held = cache.reserved_cells(sl.slice_id,
                                    exclude_owner=group.key(),
                                    below_priority=gang_priority)
        if held:
            free = {c: v for c, v in free.items() if c not in held}
        if blocked:
            free = {c: (n, cid) for c, (n, cid) in free.items()
                    if cid not in blocked.get(n, ())}
        if tpu_pods:
            # Survivor-held coords (must_include) stay implicitly: a
            # NoSchedule taint lets bound pods remain, and
            # find_box_containing unions the required coords back in.
            free = {c: (n, cid) for c, (n, cid) in free.items()
                    if _node_usable(n)}
        if len(free) < total_chips:
            reasons.append(f"slice {sl.slice_id}: {len(free)} free chips, "
                           f"gang needs {total_chips}")
            continue
        result = _plan_on_slice(group, tpu_pods, aux_pods, sl, free, cache,
                                must_include or {}, enabled=enabled)
        if isinstance(result, GangPlan):
            result.slice_id = sl.slice_id
            return result
        reasons.extend(f"slice {sl.slice_id}: {r}" for r in result.reasons)
    return GangFailure(reasons or ["no feasible slice"])


def _plan_on_slice(group: t.PodGroup, tpu_pods: list[t.Pod], aux_pods: list[t.Pod],
                   sl: SliceInfo, free: dict, cache: SchedulerCache,
                   must_include: Optional[dict] = None,
                   enabled=None) -> GangPlan | GangFailure:
    must_include = must_include or {}
    total_chips = sum(_pod_chip_demand(p) for p in tpu_pods)
    # Claim affinity: when every claim in the gang wants the same thing
    # (the overwhelmingly common case — uniform workers), pre-filter the
    # free set so the box search only sees eligible chips. Heterogeneous
    # affinities are re-checked at carve time and fail the slice.
    claims = [c for p in tpu_pods for c in p.spec.tpu_resources]
    if claims and any(c.affinity for c in claims):
        free = {coord: (node_name, chip_id)
                for coord, (node_name, chip_id) in free.items()
                if _gang_chip_eligible(cache, node_name, chip_id, claims)}
        if len(free) < total_chips:
            return GangFailure([
                f"only {len(free)} free chips match claim affinity, "
                f"gang needs {total_chips}"])
    if group.spec.slice_shape:
        shape_txt = "x".join(map(str, group.spec.slice_shape))
        if must_include:
            cells = find_box_containing(set(free), sl.mesh_shape,
                                        group.spec.slice_shape,
                                        set(must_include))
            if cells is None:
                return GangFailure([
                    f"no contiguous {shape_txt} box containing the "
                    f"{len(must_include)} chips bound members hold"])
        else:
            cells = find_box(set(free), sl.mesh_shape, group.spec.slice_shape)
            if cells is None:
                return GangFailure([f"no contiguous {shape_txt} box free"])
        vol = len(cells) - len(must_include)
        if vol < total_chips:
            return GangFailure([f"box volume {vol} < gang demand {total_chips}"])
    else:
        cells = allocate_compact(set(free), sl.mesh_shape, total_chips)
        if cells is None:
            return GangFailure(["compact allocation failed"])

    # Split cells by host (bound survivors' cells are excluded — their
    # pods already hold those chips).
    per_node: dict[str, list[tuple, str]] = {}
    for cell in cells:
        if cell in must_include:
            continue
        node_name, chip_id = free[cell]
        per_node.setdefault(node_name, []).append((cell, chip_id))

    # First-fit-decreasing: biggest pods onto fullest hosts.
    pods_desc = sorted(tpu_pods, key=_pod_chip_demand, reverse=True)
    avail = {n: list(chips) for n, chips in per_node.items()}
    placements: list = []
    for pod in pods_desc:
        demand = _pod_chip_demand(pod)
        chosen_node = None
        for node_name in sorted(avail, key=lambda n: len(avail[n]), reverse=True):
            if len(avail[node_name]) < demand:
                continue
            info = cache.nodes.get(node_name)
            if info is None:
                continue
            err = _non_tpu_predicates(pod, _with_planned(info, placements, node_name), enabled)
            if err:
                continue
            chosen_node = node_name
            break
        if chosen_node is None:
            return GangFailure([
                f"pod {pod.metadata.name}: no host in box fits {demand} chips "
                f"+ cpu/mem predicates"])
        taken = avail[chosen_node][:demand]
        avail[chosen_node] = avail[chosen_node][demand:]
        bindings = _carve_bindings(pod, chosen_node, taken, cache)
        if bindings is None:
            return GangFailure([
                f"pod {pod.metadata.name}: chip attributes do not satisfy claim affinity"])
        placements.append((pod, chosen_node, bindings))

    aux = _plan_aux(aux_pods, cache, {n: True for n in per_node}, placements,
                    enabled=enabled)
    if isinstance(aux, GangFailure):
        return aux
    placements.extend(aux)
    return GangPlan(placements=placements)


def _gang_chip_eligible(cache: SchedulerCache, node_name: str, chip_id: str,
                        claims: list) -> bool:
    info = cache.nodes.get(node_name)
    chip = info.free_chips.get(chip_id) if info else None
    if chip is None:
        return False
    return all(_chip_matches(chip, claim) for claim in claims)


class _PlannedView:
    """NodeInfo wrapper adding not-yet-assumed planned pods' requests."""

    def __init__(self, info, extra_requests: dict):
        self.node = info.node
        self.free_chips = info.free_chips
        self._info = info
        self.requested = dict(info.requested)
        for res, amt in extra_requests.items():
            self.requested[res] = self.requested.get(res, 0.0) + amt

    def allocatable(self):
        return self._info.allocatable()


def _with_planned(info, placements: list, node_name: str):
    extra: dict = {}
    for pod, n, _ in placements:
        if n != node_name:
            continue
        for res, amt in t.pod_resource_requests(pod).items():
            extra[res] = extra.get(res, 0.0) + amt
    return _PlannedView(info, extra) if extra else info


def _carve_bindings(pod: t.Pod, node_name: str, taken: list,
                    cache: SchedulerCache) -> Optional[list[t.TpuBinding]]:
    """Distribute this host's carved chips over the pod's claims,
    honoring per-claim attribute affinity."""
    info = cache.nodes.get(node_name)
    if info is None:
        return None
    chips = {chip_id: info.free_chips.get(chip_id) for _, chip_id in taken}
    remaining = set(chips)
    bindings = []
    for claim in pod.spec.tpu_resources:
        want = claim.chip_count()
        ids = sorted(cid for cid in remaining
                     if chips[cid] is not None and _chip_matches(chips[cid], claim))[:want]
        if len(ids) < want:
            return None
        remaining -= set(ids)
        bindings.append(t.TpuBinding(name=claim.name, chip_ids=ids))
    return bindings


def _plan_aux(aux_pods: list[t.Pod], cache: SchedulerCache,
              prefer_nodes: dict, placements: list,
              enabled=None) -> list | GangFailure:
    """Place chipless gang members (coordinators, loggers): any feasible
    node, preferring the gang's slice hosts for locality. ``placements``
    carries the TPU members already planned so cpu/mem accounting sees
    the whole gang."""
    placements = list(placements)
    n_tpu = len(placements)
    for pod in aux_pods:
        chosen = None
        names = sorted(cache.nodes,
                       key=lambda n: (0 if n in prefer_nodes else 1, n))
        for node_name in names:
            info = cache.nodes.get(node_name)
            if info is None or info.node is None:
                continue
            if _non_tpu_predicates(pod, _with_planned(info, placements, node_name),
                                   enabled) is None:
                chosen = node_name
                break
        if chosen is None:
            return GangFailure([f"pod {pod.metadata.name}: no feasible node"])
        placements.append((pod, chosen, []))
    return placements[n_tpu:]
