"""Columnar fleet snapshot — the SchedulerFastPath data plane.

ROADMAP item 3a: scheduler-side per-pod CPU (``prioritize`` + the
predicate loop) now rivals the apiserver's. This module keeps the node
fleet as numpy columns — allocatable/requested cpu·mem·pods, taint and
pressure flags, free-chip counts, plus memoized per-slice free-box
stats from :mod:`.submesh` — maintained **incrementally** from
:class:`~.cache.SchedulerCache` mutations, so feasibility filtering
and priority scoring for a pod (and for a whole drained batch of
pods) become vectorized array ops instead of per-node Python loops in
``Scheduler._find_placement``.

Exactness contract: for every pod the fast path accepts, the resulting
placement (node AND chip ids) is **identical** to the scalar path's —
the mask reproduces ``run_predicates`` verdicts comparison-for-
comparison, and :meth:`score_rows` mirrors the fused ``prioritize``
arithmetic term-for-term in the same operation order, so IEEE-754
float results match bit-for-bit (pinned by the placement-equivalence
property test). Pods the columns cannot represent exactly — node
selectors, any affinity (or any anti-affinity pod in the cluster,
because of symmetry), tolerations, non-core resource requests,
TPU-claim attribute affinity, active reservations, a non-default
policy, extenders — are refused (:meth:`feasibility_mask` returns
None) and take the scalar path unchanged.

Maintenance: the cache calls :meth:`mark_dirty` on per-node accounting
changes and :meth:`mark_topo_dirty` when the node set (dict order)
changes; :meth:`refresh` then rewrites only dirty rows — O(1) per
assume/bind against an O(nodes) rebuild only on node add/remove.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..api import types as t

#: Core resources the columns represent exactly. A pod requesting
#: anything else (beside the geometrically-handled TPU resource) takes
#: the scalar path.
_CORE = (t.RESOURCE_CPU, t.RESOURCE_MEMORY, t.RESOURCE_PODS)


class FleetSnapshot:
    def __init__(self, cache) -> None:
        self.cache = cache
        self.names: list[str] = []
        self._row: dict[str, int] = {}
        self._dirty: set[str] = set()
        self._topo_dirty = True
        #: node -> slice id at last refresh (slice-stat invalidation).
        self._node_slice: dict[str, str] = {}
        #: slice id -> (free cells dict, largest free box volume).
        self._slice_stats: dict[str, tuple] = {}
        self._alloc: dict[str, np.ndarray] = {}
        self._req: dict[str, np.ndarray] = {}
        self._ok = np.zeros(0, dtype=bool)
        self._schedulable = np.zeros(0, dtype=bool)
        self._disk_pressure = np.zeros(0, dtype=bool)
        self._mem_pressure = np.zeros(0, dtype=bool)
        self._blocking_taints = np.zeros(0, dtype=bool)
        self._has_tpu = np.zeros(0, dtype=bool)
        self._tpu_free = np.zeros(0, dtype=np.int64)

    def __len__(self) -> int:
        return len(self.names)

    # -- invalidation (called from SchedulerCache mutation sites) ---------

    def mark_dirty(self, node_name: str) -> None:
        """Per-node accounting changed (pod add/remove/assume, node
        status update): refresh this row lazily before the next mask."""
        self._dirty.add(node_name)

    def mark_topo_dirty(self) -> None:
        """Node set changed (add/remove): row order must be rebuilt to
        match ``list(cache.nodes)`` — ring sampling iterates that."""
        self._topo_dirty = True

    # -- refresh ----------------------------------------------------------

    def refresh(self) -> None:
        if self._topo_dirty:
            self._rebuild()
            return
        if not self._dirty:
            return
        for name in self._dirty:
            sid = self._node_slice.get(name)
            if sid is not None:
                self._slice_stats.pop(sid, None)
            i = self._row.get(name)
            if i is None:
                # Unknown node mutated without a topo event (defensive:
                # should not happen — _node_for marks topo dirty).
                self._topo_dirty = True
                self._rebuild()
                return
            self._write_row(i, self.cache.nodes.get(name))
        self._dirty.clear()

    def _rebuild(self) -> None:
        nodes = self.cache.nodes
        self.names = list(nodes)
        n = len(self.names)
        self._row = {name: i for i, name in enumerate(self.names)}
        for res in _CORE:
            self._alloc[res] = np.zeros(n)
            self._req[res] = np.zeros(n)
        self._ok = np.zeros(n, dtype=bool)
        self._schedulable = np.zeros(n, dtype=bool)
        self._disk_pressure = np.zeros(n, dtype=bool)
        self._mem_pressure = np.zeros(n, dtype=bool)
        self._blocking_taints = np.zeros(n, dtype=bool)
        self._has_tpu = np.zeros(n, dtype=bool)
        self._tpu_free = np.zeros(n, dtype=np.int64)
        self._node_slice.clear()
        self._slice_stats.clear()
        for i, name in enumerate(self.names):
            self._write_row(i, nodes.get(name))
        self._dirty.clear()
        self._topo_dirty = False

    def _write_row(self, i: int, info) -> None:
        node = info.node if info is not None else None
        if node is None:
            self._ok[i] = False
            self._schedulable[i] = False
            self._has_tpu[i] = False
            self._tpu_free[i] = 0
            for res in _CORE:
                self._alloc[res][i] = 0.0
                self._req[res][i] = 0.0
            return
        from .predicates import node_is_schedulable
        self._ok[i] = True
        alloc = info.allocatable()
        req = info.requested
        for res in _CORE:
            self._alloc[res][i] = alloc.get(res, 0.0)
            self._req[res][i] = req.get(res, 0.0)
        self._schedulable[i] = node_is_schedulable(node) is None
        disk = t.get_node_condition(node.status, t.NODE_DISK_PRESSURE)
        self._disk_pressure[i] = disk is not None and disk.status == "True"
        mem = t.get_node_condition(node.status, t.NODE_MEMORY_PRESSURE)
        self._mem_pressure[i] = mem is not None and mem.status == "True"
        self._blocking_taints[i] = any(
            taint.effect in (t.TAINT_NO_SCHEDULE, t.TAINT_NO_EXECUTE)
            for taint in node.spec.taints)
        topo = node.status.tpu
        self._has_tpu[i] = topo is not None
        self._tpu_free[i] = len(info.free_chips)
        sid = topo.slice_id if topo is not None else ""
        if sid:
            self._node_slice[node.metadata.name] = sid
            self._slice_stats.pop(sid, None)
        else:
            self._node_slice.pop(node.metadata.name, None)

    # -- eligibility + feasibility ---------------------------------------

    def eligible(self, pod: t.Pod, requests: dict) -> bool:
        """Can the columns answer predicates for this pod EXACTLY?
        (Tolerations are fine: untainted nodes don't consult them, and
        the mask patches tainted rows through the real predicate.)"""
        spec = pod.spec
        if spec.node_selector or spec.affinity is not None:
            return False
        # Anti-affinity symmetry: ANY placed anti-affinity pod can veto
        # nodes for affinity-free pods too (podaffinity.build_context).
        if self.cache.anti_affinity_pods:
            return False
        for res in requests:
            if res != t.RESOURCE_TPU and res not in _CORE:
                return False  # "node lacks resource X" needs the scalar walk
        # TPU claim attribute affinity filters per-chip — scalar only.
        return all(not claim.affinity for claim in spec.tpu_resources)

    def feasibility_mask(self, pod: t.Pod,
                         requests: dict) -> Optional[np.ndarray]:
        """Boolean row mask equal to ``run_predicates(skip_tpu=True)``
        verdicts (plus the necessary-condition chip-count prefilter for
        TPU pods), or None when this pod is not vector-eligible.
        Callers must have :meth:`refresh`-ed first and must hold the
        no-reservations / default-policy preconditions."""
        if not self.eligible(pod, requests):
            return None
        fits = self._ok & self._schedulable & ~self._disk_pressure
        if self._blocking_taints.any():
            if not pod.spec.tolerations:
                fits = fits & ~self._blocking_taints
            else:
                # Tainted nodes consult the pod's tolerations — run the
                # REAL predicate on just those rows (typically a
                # handful per fleet); untainted rows never consult
                # tolerations, so the column verdict stands.
                from .predicates import pod_tolerates_taints
                fits = fits.copy()
                for i in np.nonzero(fits & self._blocking_taints)[0]:
                    info = self.cache.nodes.get(self.names[i])
                    if info is None or info.node is None or \
                            pod_tolerates_taints(pod, info.node) \
                            is not None:
                        fits[i] = False
        # MemoryPressure rejects best-effort (no memory request) pods.
        if not requests.get(t.RESOURCE_MEMORY):
            fits = fits & ~self._mem_pressure
        for res, want in requests.items():
            if res == t.RESOURCE_TPU:
                continue
            # Same comparison as pod_fits_resources, vectorized:
            # requested + want > allocatable + 1e-9 -> infeasible.
            fits = fits & ~(self._req[res] + want
                            > self._alloc[res] + 1e-9)
        chips = t.pod_tpu_chip_count(pod)
        if chips:
            # Necessary-condition prefilter only: nodes passing still
            # run select_chips (geometry decides, exactly as scalar).
            fits = fits & self._has_tpu & (self._tpu_free >= chips)
        return fits

    @staticmethod
    def ring_candidates(mask: np.ndarray, start_at: int,
                        enough: int) -> np.ndarray:
        """First ``enough`` mask-true row indices in ring order from
        ``start_at`` — the vector twin of the scalar sampling loop."""
        n = len(mask)
        if n == 0:
            return np.zeros(0, dtype=np.int64)
        order = np.roll(np.arange(n), -int(start_at))
        hits = order[mask[order]]
        return hits[:enough]

    # -- scoring ----------------------------------------------------------

    def score_rows(self, rows: np.ndarray, want: dict, limits: dict,
                   sibling_counts: Optional[dict],
                   w_defrag_half: float) -> np.ndarray:
        """The fused ``prioritize`` arithmetic over candidate rows, in
        the same operation order so results match the scalar pass
        bit-for-bit. Covers exactly the vector-eligible pod class: no
        preferred node affinity (eligibility refused it), zero-chip
        defrag contribution passed in as the constant
        ``w_defrag_half``."""
        half = 5.0  # MAX_SCORE / 2
        cap_cpu = self._alloc[t.RESOURCE_CPU][rows]
        cap_mem = self._alloc[t.RESOURCE_MEMORY][rows]
        req_cpu = self._req[t.RESOURCE_CPU][rows]
        req_mem = self._req[t.RESOURCE_MEMORY][rows]
        want_cpu = want.get(t.RESOURCE_CPU, 0.0)
        want_mem = want.get(t.RESOURCE_MEMORY, 0.0)
        has_cpu = cap_cpu > 0
        has_mem = cap_mem > 0
        frac_cpu = (req_cpu + want_cpu) / np.where(has_cpu, cap_cpu, 1.0)
        frac_mem = (req_mem + want_mem) / np.where(has_mem, cap_mem, 1.0)
        free_sum = (np.where(has_cpu,
                             np.maximum(0.0, 1.0 - frac_cpu), 0.0)
                    + np.where(has_mem,
                               np.maximum(0.0, 1.0 - frac_mem), 0.0))
        n_res = has_cpu.astype(np.float64) + has_mem.astype(np.float64)
        lr = np.where(n_res > 0,
                      free_sum / np.where(n_res > 0, n_res, 1.0) * 10.0,
                      half)
        total = 1.0 * lr  # w_lr
        ba = (1.0 - np.abs(np.minimum(1.0, frac_cpu)
                           - np.minimum(1.0, frac_mem))) * 10.0
        total = total + 1.0 * np.where(has_cpu & has_mem, ba, half)  # w_ba
        if limits:  # ResourceLimits, weight 1 (matches fused pass)
            lim_cpu = limits.get(t.RESOURCE_CPU, 0.0)
            lim_mem = limits.get(t.RESOURCE_MEMORY, 0.0)
            bad = np.zeros(len(rows), dtype=bool)
            if lim_cpu:
                bad = bad | (cap_cpu - req_cpu < lim_cpu)
            if lim_mem:
                bad = bad | (cap_mem - req_mem < lim_mem)
            total = total + 1.0 * np.where(bad, 0.0, 10.0)
        total = total + w_defrag_half  # TpuDefrag half-score, chips==0
        if sibling_counts is not None:  # SelectorSpread, weight 1
            if not sibling_counts:
                total = total + 1.0 * half
            else:
                worst = max(sibling_counts.values())
                if worst == 0:
                    total = total + 1.0 * 10.0
                else:
                    mine = np.fromiter(
                        (sibling_counts.get(self.names[i], 0)
                         for i in rows), dtype=np.float64,
                        count=len(rows))
                    total = total + 1.0 * (10.0 * (worst - mine) / worst)
        return total

    def select_best(self, rows: np.ndarray,
                    scores: np.ndarray) -> Optional[str]:
        """selectHost: max score, lexicographically-largest name among
        exact-score ties — identical to ``max(scores, key=(score,
        name))`` over the scalar dict."""
        if len(rows) == 0:
            return None
        top = scores.max()
        ties = rows[scores == top]
        return max(self.names[i] for i in ties)

    # -- per-slice free-box stats (submesh.py memo) -----------------------

    def slice_free_stats(self, sl) -> tuple[dict, int]:
        """(free cells dict, largest free contiguous box volume) for a
        slice, memoized until any member node's accounting changes —
        the serving-topology score's before-volume without a per-pass
        recompute."""
        st = self._slice_stats.get(sl.slice_id)
        if st is None:
            free = sl.free(self.cache)
            if sl.mesh_shape:
                from .submesh import largest_free_box_volume
                vol = largest_free_box_volume(set(free), sl.mesh_shape)
            else:
                vol = 0
            st = (free, vol)
            self._slice_stats[sl.slice_id] = st
        return st
