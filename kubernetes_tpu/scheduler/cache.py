"""Scheduler cache — node/pod state with assume semantics + chip ledger.

Reference: ``plugin/pkg/scheduler/schedulercache`` (NodeInfo,
assume/add/remove pod) and the fork's per-device ledger
``schedulercache/extended_resources.go`` (``:86 AddPod`` debits device
IDs, ``:114 RemovePod`` credits, ``:154 SetNode`` rebuilds from node
status minus all pods' Assigned lists).

TPU redesign: the ledger tracks chips *with their mesh coordinates*,
and maintains a per-slice view (nodes grouped by ``slice_id``) so gang
allocation can pack one contiguous box across hosts — the structure the
reference never needed (its devices are flat).

Nominated-capacity **reservations**: after preemption, the capacity the
victims free is HELD for the preemptor (pod or gang) until it binds or
the reservation expires — the reference keeps nominated pods visible to
lower-priority scheduling (``generic_scheduler.go`` nominated-pod
handling); without it, any pod scheduled in the next iterations steals
the freed space and the preemptor livelocks through requeues.
"""
from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Optional

from ..analysis import invariants as _inv
from ..api import types as t
from ..client.mutation_detector import CacheMutationDetector

Coord = tuple[int, ...]


@dataclass
class Reservation:
    """Capacity held for a preemptor until it binds or expires.

    Single-pod form: ``node_name`` + ``requests`` (+ the victims'
    freed ``chip_ids``). Gang form: ``slice_id`` + ``cells`` (the
    contiguous box carved by gang preemption) + ``node_requests``
    (CPU/mem held per box host — chips alone would let a CPU-only
    squatter take the host and starve the gang's predicates).
    ``priority`` gates who must respect it: placements for pods of
    priority <= the reservation's see it as consumed capacity
    (nominated-pod semantics); only a STRICTLY higher-priority pod
    may compete for the space."""

    owner: str = ""                 # preemptor pod key or gang group key
    priority: int = 0
    expires: float = 0.0            # monotonic deadline
    node_name: str = ""
    requests: dict = field(default_factory=dict)
    chip_ids: set = field(default_factory=set)
    slice_id: str = ""
    cells: dict = field(default_factory=dict)  # coord -> (node, chip_id)
    node_requests: dict = field(default_factory=dict)  # node -> requests


@dataclass
class NodeInfo:
    node: Optional[t.Node] = None
    pods: dict = field(default_factory=dict)  # key -> Pod
    requested: dict = field(default_factory=dict)  # resource -> float
    #: chip_id -> TpuChip for healthy, unassigned chips.
    free_chips: dict = field(default_factory=dict)
    #: chip_id -> pod key holding it.
    chip_owner: dict = field(default_factory=dict)
    #: controller owner uid -> count of its pods on this node
    #: (SelectorSpreadPriority input, maintained incrementally so
    #: scheduling is O(nodes), not O(nodes * pods)).
    owner_counts: dict = field(default_factory=dict)
    #: Memoized allocatable() (READ-ONLY to callers): rebuilt on
    #: set_node. The per-call dict copy was ~2M calls per 10k-pod
    #: density run — pure allocation churn on the scoring hot path.
    _alloc: Optional[dict] = field(default=None, repr=False)

    def allocatable(self) -> dict:
        if self._alloc is None:
            if self.node is None:
                return {}
            alloc = dict(self.node.status.allocatable
                         or self.node.status.capacity)
            if t.RESOURCE_PODS not in alloc:
                alloc[t.RESOURCE_PODS] = 110
            self._alloc = alloc
        return self._alloc

    def recompute_chips(self) -> None:
        """Rebuild the free-chip set from node status minus pod claims
        (SetNode semantics, ``extended_resources.go:154``). Also drops
        the allocatable memo — called exactly when node status changed."""
        self._alloc = None
        self.free_chips = {}
        self.chip_owner = {}
        topo = self.node.status.tpu if self.node else None
        if topo is None:
            return
        healthy = {c.id: c for c in topo.chips if c.health == t.TPU_HEALTHY}
        for key, pod in self.pods.items():
            for cid in t.pod_tpu_assigned(pod):
                if cid in healthy:
                    self.chip_owner[cid] = key
        self.free_chips = {cid: c for cid, c in healthy.items()
                           if cid not in self.chip_owner}

    def add_pod(self, pod: t.Pod) -> None:
        key = pod.key()
        self.pods[key] = pod
        for res, amt in t.pod_resource_requests(pod).items():
            self.requested[res] = self.requested.get(res, 0.0) + amt
        for ref in pod.metadata.owner_references:
            if ref.controller:
                self.owner_counts[ref.uid] = self.owner_counts.get(ref.uid, 0) + 1
        for cid in t.pod_tpu_assigned(pod):
            chip = self.free_chips.pop(cid, None)
            if chip is not None or cid not in self.chip_owner:
                self.chip_owner[cid] = key

    def remove_pod(self, pod: t.Pod) -> None:
        key = pod.key()
        if key not in self.pods:
            return
        del self.pods[key]
        for res, amt in t.pod_resource_requests(pod).items():
            self.requested[res] = self.requested.get(res, 0.0) - amt
            if abs(self.requested[res]) < 1e-9:
                del self.requested[res]
        for ref in pod.metadata.owner_references:
            if ref.controller and ref.uid in self.owner_counts:
                self.owner_counts[ref.uid] -= 1
                if self.owner_counts[ref.uid] <= 0:
                    del self.owner_counts[ref.uid]
        topo = self.node.status.tpu if self.node else None
        healthy = {c.id: c for c in (topo.chips if topo else [])
                   if c.health == t.TPU_HEALTHY}
        for cid in t.pod_tpu_assigned(pod):
            if self.chip_owner.get(cid) == key:
                del self.chip_owner[cid]
                if cid in healthy:
                    self.free_chips[cid] = healthy[cid]

    def free_coords(self) -> dict[Coord, str]:
        """coords -> chip_id for free chips (geometry view for submesh)."""
        return {tuple(c.coords): cid for cid, c in self.free_chips.items() if c.coords}


class ReservedNodeView:
    """A NodeInfo as seen by a pod that must honor reservations:
    reserved requests debited from headroom, reserved chips removed
    from the free set. Predicates/select_chips read only these
    attributes, so the view is cheap and copy-free."""

    def __init__(self, info: "NodeInfo", extra_requests: dict,
                 blocked_chips: set):
        self._info = info
        self.node = info.node
        self.pods = info.pods
        self.owner_counts = info.owner_counts
        self.chip_owner = info.chip_owner
        self.requested = dict(info.requested)
        for res, amt in extra_requests.items():
            self.requested[res] = self.requested.get(res, 0.0) + amt
        self.free_chips = (
            {cid: c for cid, c in info.free_chips.items()
             if cid not in blocked_chips}
            if blocked_chips else info.free_chips)

    def allocatable(self) -> dict:
        return self._info.allocatable()

    def free_coords(self) -> dict:
        return {tuple(c.coords): cid for cid, c in self.free_chips.items()
                if c.coords}


@dataclass
class SliceInfo:
    """All nodes of one multi-host slice, merged into one geometry."""

    slice_id: str = ""
    chip_type: str = ""
    mesh_shape: tuple = ()
    #: coords -> (node_name, chip_id) for every healthy chip.
    chips: dict = field(default_factory=dict)
    node_names: set = field(default_factory=set)

    def free(self, cache: "SchedulerCache") -> dict[Coord, tuple[str, str]]:
        out = {}
        for coord, (node_name, chip_id) in self.chips.items():
            info = cache.nodes.get(node_name)
            if info and chip_id in info.free_chips:
                out[coord] = (node_name, chip_id)
        return out


class SchedulerCache:
    def __init__(self) -> None:
        from .equivalence import EquivalenceCache
        self.nodes: dict[str, NodeInfo] = {}
        self.slices: dict[str, SliceInfo] = {}
        #: Predicate equivalence cache (equivalence_cache.go analog);
        #: invalidated per node on every accounting mutation below.
        self.equiv = EquivalenceCache()
        #: pod key -> node name for assumed (bound-in-flight) pods.
        self.assumed: dict[str, str] = {}
        #: pod key -> node name for every pod known to the cache
        #: (assumed or informer-added).
        self._pod_node: dict[str, str] = {}
        #: pod key -> pod, for pods carrying REQUIRED anti-affinity
        #: terms (the symmetry check in podaffinity.py scans only
        #: these; empty in affinity-free clusters -> zero cost).
        self.anti_affinity_pods: dict[str, t.Pod] = {}
        #: owner (pod key / gang group key) -> Reservation.
        self.reservations: dict[str, Reservation] = {}
        #: Env-gated (TPU_CACHE_MUTATION_DETECTOR): pods/nodes entering
        #: the cache are digest-snapshotted; read-back via bound_copy
        #: asserts nobody mutated them in place.
        self.mutation_detector = CacheMutationDetector("scheduler-cache")
        #: Optional columnar mirror (fleetarray.FleetSnapshot) under the
        #: SchedulerFastPath gate: every accounting mutation below marks
        #: the touched node dirty; node add/remove marks topology dirty
        #: (row order must track this dict's insertion order). None =
        #: zero-cost, byte-identical to the ungated cache.
        self.snapshot = None

    # -- reservations ------------------------------------------------------

    def reserve(self, res: Reservation, ttl: float = 120.0) -> None:
        res.expires = _time.monotonic() + ttl
        self.reservations[res.owner] = res
        for name in ({res.node_name} | {n for n, _ in res.cells.values()}):
            if name:
                self.equiv.invalidate_node(name)
        # tpusan migration-no-strand seam (no-op unless armed).
        _inv.note_reservation(
            res.owner,
            [(n, cid) for n, cid in res.cells.values()]
            + [(res.node_name, cid) for cid in res.chip_ids])

    def release_reservation(self, owner: str) -> None:
        res = self.reservations.pop(owner, None)
        if res is not None:
            for name in ({res.node_name} | {n for n, _ in res.cells.values()}):
                if name:
                    self.equiv.invalidate_node(name)
            # TTL expiry (_live_reservations) flows through here too —
            # the sanitizer sees every way a reservation can die.
            _inv.note_reservation_gone(owner)

    def _live_reservations(self):
        now = _time.monotonic()
        dead = [k for k, r in self.reservations.items() if r.expires <= now]
        for k in dead:
            self.release_reservation(k)
        return self.reservations.values()

    def node_reserved(self, node_name: str, exclude_owner: str = "",
                      below_priority: Optional[int] = None
                      ) -> tuple[dict, set]:
        """(requests, chip_ids) held on ``node_name`` by live
        reservations a pod of priority ``below_priority`` must honor
        (reservation.priority >= pod priority). ``exclude_owner``: the
        preemptor itself — its own hold is its to consume."""
        req: dict = {}
        chips: set = set()
        for r in self._live_reservations():
            if r.owner == exclude_owner:
                continue
            if below_priority is not None and r.priority < below_priority:
                continue
            if r.node_name == node_name:
                for res_name, amt in r.requests.items():
                    req[res_name] = req.get(res_name, 0.0) + amt
                chips |= r.chip_ids
            for res_name, amt in r.node_requests.get(node_name,
                                                     {}).items():
                req[res_name] = req.get(res_name, 0.0) + amt
            for coord, (n, chip_id) in r.cells.items():
                if n == node_name:
                    chips.add(chip_id)
        return req, chips

    def reserved_cells(self, slice_id: str, exclude_owner: str = "",
                       below_priority: Optional[int] = None) -> set:
        """Box cells a gang plan must avoid on this slice."""
        out: set = set()
        for r in self._live_reservations():
            if r.owner == exclude_owner or r.slice_id != slice_id:
                continue
            if below_priority is not None and r.priority < below_priority:
                continue
            out |= set(r.cells)
        return out

    def reserved_node_chips(self, exclude_owner: str = "",
                            below_priority: Optional[int] = None
                            ) -> dict[str, set]:
        """node -> chip ids held by single-pod (nominated) reservations
        — the per-chip complement of :meth:`reserved_cells` for gang
        planning over slices."""
        out: dict[str, set] = {}
        for r in self._live_reservations():
            if r.owner == exclude_owner or not r.chip_ids:
                continue
            if below_priority is not None and r.priority < below_priority:
                continue
            out.setdefault(r.node_name, set()).update(r.chip_ids)
        return out

    def has_reservations(self) -> bool:
        return bool(self.reservations)

    def knows_pod(self, key: str) -> bool:
        """True when the cache already tracks this pod (assumed or added)."""
        return key in self.assumed or key in self._pod_node

    def verify_cached(self) -> None:
        """Re-check every snapshotted node and pod against its
        upsert-time digest (client-go's periodic CompareObjects sweep;
        the scheduler runs this once per scheduling cycle when the
        detector is armed). Raises CacheMutationDetectedError."""
        det = self.mutation_detector
        if not det.enabled:
            return
        for name, info in self.nodes.items():
            if info.node is not None:
                det.verify(f"node/{name}", info.node)
            for key, pod in info.pods.items():
                det.verify(key, pod)

    def bound_copy(self, key: str):
        """The cache's copy of a bound/assumed pod (carries the chip
        assignment debited at assume time), or None. The cache is
        updated synchronously at bind — ahead of the informer — so
        gang recovery reads it first."""
        node_name = self._pod_node.get(key)
        if node_name is None:
            return None
        info = self.nodes.get(node_name)
        pod = info.pods.get(key) if info else None
        if pod is not None and self.mutation_detector.enabled:
            self.mutation_detector.verify(key, pod)
        return pod

    # -- nodes ------------------------------------------------------------

    def set_node(self, node: t.Node) -> None:
        info = self.nodes.get(node.metadata.name)
        if info is None:
            info = NodeInfo(node=node)
            self.nodes[node.metadata.name] = info
            if self.snapshot is not None:
                self.snapshot.mark_topo_dirty()
        else:
            info.node = node
            if self.snapshot is not None:
                self.snapshot.mark_dirty(node.metadata.name)
        info.recompute_chips()
        self._rebuild_slice_for(node)
        self.equiv.invalidate_node(node.metadata.name)
        if self.mutation_detector.enabled:
            self.mutation_detector.capture(f"node/{node.metadata.name}", node)

    def remove_node(self, name: str) -> None:
        if self.snapshot is not None:
            self.snapshot.mark_topo_dirty()
        self.equiv.invalidate_node(name)
        self.mutation_detector.forget(f"node/{name}")
        info = self.nodes.pop(name, None)
        if info is not None:
            # The node's pods leave the verifiable cache with it; drop
            # their snapshots or the detector leaks one per departed pod.
            for key in info.pods:
                self.mutation_detector.forget(key)
        if info and info.node and info.node.status.tpu:
            sid = info.node.status.tpu.slice_id
            sl = self.slices.get(sid)
            if sl:
                sl.node_names.discard(name)
                sl.chips = {c: v for c, v in sl.chips.items() if v[0] != name}
                if not sl.node_names:
                    del self.slices[sid]

    def _rebuild_slice_for(self, node: t.Node) -> None:
        topo = node.status.tpu
        if topo is None or not topo.slice_id:
            return
        sl = self.slices.get(topo.slice_id)
        if sl is None:
            sl = SliceInfo(slice_id=topo.slice_id, chip_type=topo.chip_type,
                           mesh_shape=tuple(topo.mesh_shape))
            self.slices[topo.slice_id] = sl
        sl.mesh_shape = tuple(topo.mesh_shape)
        sl.chip_type = topo.chip_type
        sl.node_names.add(node.metadata.name)
        # Replace this node's chips in the slice geometry.
        sl.chips = {c: v for c, v in sl.chips.items() if v[0] != node.metadata.name}
        for chip in topo.chips:
            if chip.health == t.TPU_HEALTHY and chip.coords:
                sl.chips[tuple(chip.coords)] = (node.metadata.name, chip.id)

    # -- pods -------------------------------------------------------------

    def _node_for(self, node_name: str) -> NodeInfo:
        info = self.nodes.get(node_name)
        if info is None:
            info = NodeInfo()  # node not seen yet; pods can arrive first
            self.nodes[node_name] = info
            if self.snapshot is not None:
                self.snapshot.mark_topo_dirty()
        return info

    def add_pod(self, pod: t.Pod) -> None:
        key = pod.key()
        node_name = pod.spec.node_name
        if not node_name:
            return
        if key in self.assumed:
            # Confirmation of an assumed pod: replace the assumed copy.
            prev_node = self.assumed.pop(key)
            if prev_node != node_name:
                prev = self.nodes.get(prev_node)
                if prev and key in prev.pods:
                    prev.remove_pod(prev.pods[key])
                self.equiv.invalidate_node(prev_node)
                if self.snapshot is not None:
                    self.snapshot.mark_dirty(prev_node)
            else:
                info = self.nodes[node_name]
                if key in info.pods:
                    info.remove_pod(info.pods[key])
        elif key in self._pod_node:
            old_node = self._pod_node[key]
            old_info = self.nodes.get(old_node)
            if old_info and key in old_info.pods:
                old_info.remove_pod(old_info.pods[key])
            self.equiv.invalidate_node(old_node)
            if self.snapshot is not None:
                self.snapshot.mark_dirty(old_node)
        self._node_for(node_name).add_pod(pod)
        if self.snapshot is not None:
            self.snapshot.mark_dirty(node_name)
        self._pod_node[key] = node_name
        aff = pod.spec.affinity
        if aff is not None and aff.pod_anti_affinity:
            self.anti_affinity_pods[key] = pod
        else:
            self.anti_affinity_pods.pop(key, None)
        self.equiv.invalidate_node(node_name)
        if self.mutation_detector.enabled:
            self.mutation_detector.capture(key, pod)

    def update_pod(self, pod: t.Pod) -> None:
        self.add_pod(pod)

    def remove_pod(self, pod: t.Pod) -> None:
        key = pod.key()
        self.release_reservation(key)  # deleted preemptor frees its hold
        node_name = self._pod_node.pop(key, None) or pod.spec.node_name
        self.assumed.pop(key, None)
        self.anti_affinity_pods.pop(key, None)
        info = self.nodes.get(node_name) if node_name else None
        if info:
            existing = info.pods.get(key, pod)
            info.remove_pod(existing)
        if node_name:
            self.equiv.invalidate_node(node_name)
            if self.snapshot is not None:
                self.snapshot.mark_dirty(node_name)
        self.mutation_detector.forget(key)

    # -- assume / forget (bind-in-flight bookkeeping) ---------------------

    def assume_pod(self, pod: t.Pod, node_name: str) -> None:
        """Debit resources optimistically before the bind RPC returns
        (reference: ``scheduler.go`` assume + ER manager AddPod)."""
        # The preemptor landed: its nominated hold has served.
        self.release_reservation(pod.key())
        pod.spec.node_name = node_name
        self._node_for(node_name).add_pod(pod)
        self.assumed[pod.key()] = node_name
        self._pod_node[pod.key()] = node_name
        aff = pod.spec.affinity
        if aff is not None and aff.pod_anti_affinity:
            self.anti_affinity_pods[pod.key()] = pod
        self.equiv.invalidate_node(node_name)
        if self.snapshot is not None:
            self.snapshot.mark_dirty(node_name)
        if self.mutation_detector.enabled:
            self.mutation_detector.capture(pod.key(), pod)

    def forget_pod(self, pod: t.Pod) -> None:
        """Bind failed: credit everything back."""
        key = pod.key()
        node_name = self.assumed.pop(key, None)
        if node_name is None:
            return
        self._pod_node.pop(key, None)
        self.anti_affinity_pods.pop(key, None)
        info = self.nodes.get(node_name)
        if info and key in info.pods:
            info.remove_pod(info.pods[key])
        self.equiv.invalidate_node(node_name)
        if self.snapshot is not None:
            self.snapshot.mark_dirty(node_name)
        self.mutation_detector.forget(key)
