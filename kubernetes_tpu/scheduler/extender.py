"""Scheduler extender — out-of-process filter/prioritize webhooks.

Reference: ``plugin/pkg/scheduler/core/extender.go`` (HTTPExtender) +
the policy file's ``extenders`` stanza: after built-in predicates run,
each extender's ``filter`` verb gets {pod, node names} and returns the
survivors + per-node failure reasons; ``prioritize`` returns host
priorities merged into the score map with the extender's weight.

Wire format mirrors the reference's ExtenderArgs / ExtenderFilterResult
/ HostPriorityList shapes (JSON over POST), so an existing extender
webhook ports by swapping field spellings only:

    POST <url_prefix>/<filter_verb>     {"pod": {...}, "node_names": [...]}
      -> {"node_names": [...], "failed_nodes": {name: reason}, "error": ""}
    POST <url_prefix>/<prioritize_verb> {"pod": {...}, "node_names": [...]}
      -> [{"host": name, "score": float}, ...]

Failure policy (reference semantics): a failing FILTER aborts the
placement attempt (retried with backoff) unless ``ignorable`` — an
ignorable extender degrades to a no-op; prioritize errors are dropped
either way (scores are best-effort).
"""
from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from ..api import types as t
from ..api.scheme import to_dict

log = logging.getLogger("scheduler.extender")


@dataclass
class SchedulerExtender:
    url_prefix: str
    filter_verb: str = "filter"
    prioritize_verb: str = "prioritize"
    weight: float = 1.0
    #: Managed resources gate (reference: ManagedResources) — when set,
    #: only pods requesting one of these resources consult the extender.
    managed_resources: tuple = ()
    timeout: float = 5.0
    ignorable: bool = False

    _session = None  # lazy aiohttp session, shared per extender

    def interested(self, pod: t.Pod) -> bool:
        if not self.managed_resources:
            return True
        requests = t.pod_resource_requests(pod)
        return any(res in requests for res in self.managed_resources)

    async def _post(self, verb: str, pod: t.Pod, node_names: list[str]):
        import aiohttp
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        url = f"{self.url_prefix.rstrip('/')}/{verb}"
        async with self._session.post(
                url, json={"pod": to_dict(pod), "node_names": node_names},
                timeout=aiohttp.ClientTimeout(total=self.timeout)) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def filter(self, pod: t.Pod, node_names: list[str]
                     ) -> tuple[list[str], dict[str, str]]:
        """(surviving names, {failed name: reason}). Raises on
        transport/extender error — the scheduler applies the
        ignorable policy."""
        if not self.filter_verb:
            return node_names, {}
        body = await self._post(self.filter_verb, pod, node_names)
        if body.get("error"):
            raise RuntimeError(body["error"])
        survivors = body.get("node_names")
        failed = dict(body.get("failed_nodes") or {})
        if survivors is None:
            survivors = [n for n in node_names if n not in failed]
        # Never trust names we didn't submit: a stale/buggy extender
        # must not resurrect nodes the built-in predicates rejected.
        sent = set(node_names)
        return [n for n in survivors if n in sent], failed

    async def prioritize(self, pod: t.Pod,
                         node_names: list[str]) -> dict[str, float]:
        if not self.prioritize_verb:
            return {}
        body = await self._post(self.prioritize_verb, pod, node_names)
        return {e["host"]: float(e.get("score", 0)) for e in body
                if e.get("host") in node_names}

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
