from .scheduler import Scheduler  # noqa: F401
