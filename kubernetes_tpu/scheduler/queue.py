"""Scheduling queue — priority-ordered, gang-aware.

Reference: ``plugin/pkg/scheduler/core/scheduling_queue.go`` (FIFO +
priority queue with an unschedulable parking lot flushed on cluster
events). TPU addition: a **gang staging area** — members of a PodGroup
park until ``min_member`` are present, then the whole gang pops as one
unit, so partial gangs never consume scheduling cycles or chips.
"""
from __future__ import annotations

import asyncio
import heapq
import itertools
from dataclasses import dataclass, field
from typing import Optional, Union

from ..analysis import interleave, loopsan
from ..api import types as t
from ..util.tasks import spawn


@dataclass(order=True)
class _Entry:
    sort_key: tuple
    item: object = field(compare=False)
    cancelled: bool = field(default=False, compare=False)


@dataclass
class GangUnit:
    group_key: str  # namespace/name of the PodGroup
    pods: list = field(default_factory=list)


QueueItem = Union[t.Pod, GangUnit]


class SchedulingQueue:
    def __init__(self):
        self._heap: list[_Entry] = []
        self._entries: dict[str, _Entry] = {}
        self._seq = itertools.count()
        self._cond = asyncio.Condition()
        #: gang key -> {pod key -> pod} staged (unbound) members.
        self._gangs: dict[str, dict[str, t.Pod]] = {}
        #: gang key -> required member count (from PodGroup.spec.min_member).
        self._gang_min: dict[str, int] = {}
        #: gang key -> pod keys already bound. Quorum counts bound +
        #: staged so a partially-bound gang keeps releasing its remainder.
        self._gang_bound: dict[str, set[str]] = {}
        #: Gangs held back by queue admission (queueing/): a suspended
        #: gang's members stage as usual but the GangUnit NEVER enters
        #: the heap until admission clears the flag.
        self._gang_suspended: set[str] = set()
        self._closed = False
        #: Strong refs to in-flight wake tasks (the loop holds tasks
        #: only weakly; an unreferenced notify task can vanish before
        #: running).
        self._wake_tasks: set = set()
        #: One coalesced notify task outstanding at a time (sync adds).
        self._wake_pending = False

    # -- producers --------------------------------------------------------

    def _sort_key(self, pod: t.Pod):
        return (-(t.pod_priority(pod)), next(self._seq))

    async def add_pod(self, pod: t.Pod) -> None:
        async with self._cond:
            if pod.spec.gang:
                self._stage_gang_pod(pod)
            else:
                self._push_entry(pod.key(), self._sort_key(pod), pod)
            self._cond.notify()

    def add_pod_sync(self, pod: t.Pod) -> None:
        """Synchronous enqueue from an informer handler (the
        SchedulerFastPath ingest: one task PER POD EVENT — spawn +
        lock + notify — was measurable at 30k scale). Single-threaded
        asyncio makes the heap mutation atomic without the condition
        lock; the wake rides one coalesced notify task per burst
        (``_wake_soon`` batches via ``_wake_pending``)."""
        if pod.spec.gang:
            self._stage_gang_pod(pod)
            self._wake_soon()
        else:
            self._push_entry(pod.key(), self._sort_key(pod), pod)
            self._wake_soon()

    def _push_entry(self, key: str, sort_key, item) -> None:
        old = self._entries.get(key)
        if old is not None:
            old.cancelled = True
        e = _Entry(sort_key, item)
        self._entries[key] = e
        heapq.heappush(self._heap, e)

    def _stage_gang_pod(self, pod: t.Pod) -> None:
        gk = f"{pod.metadata.namespace}/{pod.spec.gang}"
        self._gangs.setdefault(gk, {})[pod.key()] = pod
        self._maybe_release_gang(gk)

    def set_gang_min(self, group_key: str, min_member: int) -> None:
        """Called when the PodGroup object is seen/updated."""
        self._gang_min[group_key] = min_member
        if self._maybe_release_gang(group_key):
            self._wake_soon()

    def set_gang_suspended(self, group_key: str, suspended: bool) -> None:
        """Admission gate (sync informer context). Suspending cancels
        any already-released (unpopped) gang unit; releasing
        re-evaluates quorum and wakes the consumer — the
        admission-release wake path."""
        if suspended:
            if group_key in self._gang_suspended:
                return
            self._gang_suspended.add(group_key)
            ge = self._entries.pop(f"gang:{group_key}", None)
            if ge is not None:
                ge.cancelled = True
        else:
            if group_key not in self._gang_suspended:
                return
            self._gang_suspended.discard(group_key)
            if self._maybe_release_gang(group_key):
                self._wake_soon()

    def _maybe_release_gang(self, gk: str) -> bool:
        """Push the gang unit if quorum is staged; True when pushed.
        SYNC callers (informer handlers) must then :meth:`_wake_soon`
        — pushing without a notify left the consumer asleep on a
        non-empty heap whenever the PodGroup's watch event arrived
        AFTER its pods (a relist after a dropped watch reorders
        exactly that way; found by the chaos harness)."""
        interleave.touch(f"gang:{gk}")  # tpusan DPOR hint: release path
        # loopsan child seam: gang-release wakeups were folded into the
        # parent queue-stage share — carving them out is what lets the
        # occupancy table say whether pop, decode, or THIS dominates.
        with loopsan.seam("scheduler.queue.gang_wake"):
            if gk in self._gang_suspended:
                return False  # unadmitted: the admission gate (queueing/)
            staged = self._gangs.get(gk)
            need = self._gang_min.get(gk)
            bound = len(self._gang_bound.get(gk, ()))
            if not staged or need is None or len(staged) + bound < need:
                return False
            pods = list(staged.values())
            best = max(t.pod_priority(p) for p in pods)
            self._push_entry(f"gang:{gk}", (-best, next(self._seq)),
                             GangUnit(group_key=gk, pods=pods))
            return True

    def _wake_soon(self) -> None:
        """Notify the consumer from a sync (informer handler) context.
        Coalesced: a burst of sync pushes rides ONE notify task (the
        flag clears inside the task, so any push after it ran gets a
        fresh wake)."""
        if self._wake_pending:
            return
        async def _notify():
            self._wake_pending = False
            async with self._cond:
                self._cond.notify_all()
        try:
            task = asyncio.get_running_loop().create_task(_notify())
        except RuntimeError:
            return  # no loop (teardown): nothing to wake
        self._wake_pending = True
        self._wake_tasks.add(task)
        task.add_done_callback(self._wake_tasks.discard)

    async def remove_pod(self, pod: t.Pod) -> None:
        async with self._cond:
            key = pod.key()
            e = self._entries.pop(key, None)
            if e:
                e.cancelled = True
            if pod.spec.gang:
                gk = f"{pod.metadata.namespace}/{pod.spec.gang}"
                staged = self._gangs.get(gk)
                if staged:
                    staged.pop(key, None)
                bound = self._gang_bound.get(gk)
                if bound:
                    bound.discard(key)
                ge = self._entries.get(f"gang:{gk}")
                if ge and not ge.cancelled:
                    ge.cancelled = True
                    if staged and self._maybe_release_gang(gk):
                        self._cond.notify()

    async def requeue(self, item: QueueItem, backoff: float = 0.0) -> None:
        """Unschedulable item returns to the queue after ``backoff``."""
        if backoff > 0:
            loop = asyncio.get_running_loop()
            loop.call_later(backoff,
                            lambda: spawn(self._requeue_now(item),
                                          name="queue-requeue"))
        else:
            await self._requeue_now(item)

    async def _requeue_now(self, item: QueueItem) -> None:
        async with self._cond:
            if isinstance(item, GangUnit):
                gk = item.group_key
                staged = self._gangs.get(gk)
                if staged:  # releases with current membership
                    self._maybe_release_gang(gk)
            else:
                self._push_entry(item.key(), self._sort_key(item), item)
            self._cond.notify()

    def gang_pod_confirmed(self, pod: t.Pod) -> None:
        """A gang member got bound: move it from staging to the bound set
        so quorum still counts it and the remainder keeps releasing."""
        gk = f"{pod.metadata.namespace}/{pod.spec.gang}"
        interleave.touch(f"gang:{gk}")  # tpusan DPOR hint: bind path
        self._gang_bound.setdefault(gk, set()).add(pod.key())
        staged = self._gangs.get(gk)
        if staged:
            staged.pop(pod.key(), None)
            if not staged:
                del self._gangs[gk]
            elif self._maybe_release_gang(gk):
                self._wake_soon()

    def gang_pod_lost(self, pod: t.Pod) -> None:
        """A bound member went terminal (evicted/failed): it no longer
        counts toward quorum — and, under the elastic cap, a stale
        bound count would permanently park the replacement members
        (bound ghosts consumed the whole target)."""
        gk = f"{pod.metadata.namespace}/{pod.spec.gang}"
        bound = self._gang_bound.get(gk)
        if bound is not None:
            bound.discard(pod.key())

    def gang_bound_count(self, gk: str) -> int:
        return len(self._gang_bound.get(gk, ()))

    # -- consumer ---------------------------------------------------------

    async def pop(self) -> Optional[QueueItem]:
        async with self._cond:
            while True:
                # Seam wraps only the sync drain, never the cond wait
                # (spans cannot cross awaits; wait time is idle, not
                # queue-stage busy).
                with loopsan.seam("scheduler.queue.pop"):
                    item = self._pop_ready_locked()
                if item is not None:
                    return item
                if self._closed:
                    return None
                await self._cond.wait()

    def _peek_ready_locked(self) -> Optional[QueueItem]:
        """Purge cancelled entries; the live heap top (not popped), or
        None when empty (lock held)."""
        while self._heap and self._heap[0].cancelled:
            heapq.heappop(self._heap)
        return self._heap[0].item if self._heap else None

    def _take_head_locked(self) -> QueueItem:
        """Pop the (already-purged, non-empty) heap top. Callers must
        have just run :meth:`_peek_ready_locked` — splitting peek from
        take is what lets :meth:`pop_batch` pay ONE purge scan per
        item where peek-then-pop paid two (the re-purge + isinstance
        re-check was the loopsan-attributed top queue-stage item at
        30k density)."""
        e = heapq.heappop(self._heap)
        if isinstance(e.item, GangUnit):
            self._entries.pop(f"gang:{e.item.group_key}", None)
            # Refresh membership at pop time.
            staged = self._gangs.get(e.item.group_key)
            if staged:
                e.item.pods = list(staged.values())
        else:
            self._entries.pop(e.item.key(), None)
        return e.item

    def _pop_ready_locked(self) -> Optional[QueueItem]:
        """One live item off the heap, or None when empty (lock held)."""
        if self._peek_ready_locked() is None:
            return None
        return self._take_head_locked()

    async def pop_batch(self, limit: int = 64) -> Optional[list]:
        """Drain up to ``limit`` ready items in priority order with ONE
        condition acquisition (the SchedulerFastPath batch drain) —
        byte-identical item sequence to ``limit`` consecutive
        :meth:`pop` calls with no producer in between. A GangUnit ends
        the batch: it either opens the batch alone or stays at the
        heap top for the next drain, so gang scheduling keeps its
        one-unit-at-a-time atomicity under tpusan. None = closed."""
        async with self._cond:
            while True:
                with loopsan.seam("scheduler.queue.pop"):
                    out: list = []
                    while len(out) < limit:
                        head = self._peek_ready_locked()
                        if head is None:
                            break
                        if isinstance(head, GangUnit) and out:
                            break
                        out.append(self._take_head_locked())
                        if isinstance(head, GangUnit):
                            break
                if out:
                    return out
                if self._closed:
                    return None
                await self._cond.wait()

    async def close(self) -> None:
        async with self._cond:
            self._closed = True
            self._cond.notify_all()

    def __len__(self) -> int:
        # _entries holds exactly the live (non-cancelled) heap entries
        # — _push_entry maps, pop/remove/replace unmap — so this is
        # O(1) where scanning the heap was O(pending) per loop
        # iteration (it showed up at density scale).
        return len(self._entries)
