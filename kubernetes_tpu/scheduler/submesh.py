"""Contiguous ICI sub-mesh allocation on a 3D (torus) chip mesh.

This is the TPU-first replacement for the reference's flat
extended-resource matcher (``plugin/pkg/scheduler/core/
extended_resources.go:113-150 allocateResources`` — count + attribute
matching with no notion of inter-device distance). On TPU, a JAX mesh
only gets full ICI bandwidth if its chips form a *contiguous axis-
aligned box* of the slice's 3D mesh (wrap-around links make each axis a
ring on full-axis slices), so allocation here is geometric:

- **Shaped requests** (``slice_shape=[a,b,c]``): find an axis-aligned
  a*b*c box of free chips, trying all axis permutations of the shape
  and all origins, with torus wrap-around per axis. First fit wins
  among candidates with the best packing score.
- **Count requests** (``chips=N``): greedy BFS over the free-chip
  neighbor graph from the most corner-packed free chip, so the chosen
  set is as compact as connectivity allows.
- **Scoring** prefers allocations that touch already-used regions
  (corner packing) to fight fragmentation — the NP-hard part of
  SURVEY.md section 7, handled with a cheap, deterministic heuristic.

Pure geometry, no API-object types: the scheduler cache feeds it free
coordinate sets. A C++ fast path (native/submesh.cpp) accelerates the
box search for big slices; this module is the reference implementation
and fallback.
"""
from __future__ import annotations

import itertools
from typing import Iterable, Optional, Sequence

Coord = tuple[int, ...]


def normalize_shape(shape: Sequence[int], rank: int) -> tuple[int, ...]:
    """Pad a request shape with 1s up to the mesh rank: [4] -> (4,1,1)."""
    s = tuple(int(d) for d in shape)
    if len(s) > rank:
        # Drop trailing 1s if possible ([2,2,1] on a 2D mesh -> (2,2)).
        while len(s) > rank and s[-1] == 1:
            s = s[:-1]
        if len(s) > rank:
            return s  # unsatisfiable; caller sees volume/dim mismatch
    return s + (1,) * (rank - len(s))


def box_coords(origin: Coord, shape: Coord, mesh: Coord, torus: bool) -> Optional[list[Coord]]:
    """Cells of the axis-aligned box at ``origin``; None if out of bounds."""
    for o, s, m in zip(origin, shape, mesh):
        if not torus and o + s > m:
            return None
        if s > m:
            return None
    ranges = []
    for o, s, m in zip(origin, shape, mesh):
        ranges.append([(o + i) % m for i in range(s)])
    return [tuple(c) for c in itertools.product(*ranges)]


def _packing_score(cells: list[Coord], free: set[Coord], mesh: Coord) -> float:
    """Lower is better: prefer boxes whose neighbors are NOT free (touching
    walls or used regions), keeping the free space consolidated."""
    cellset = set(cells)
    free_neighbors = 0
    for c in cells:
        for n in _neighbors(c, mesh, True):
            if n not in cellset and n in free:
                free_neighbors += 1
    return free_neighbors


def find_box(free: set[Coord], mesh: Sequence[int], shape: Sequence[int],
             torus: bool = True) -> Optional[list[Coord]]:
    """Best free axis-aligned box of ``shape`` (any axis permutation).

    Returns the cell list or None. Deterministic: scans origins in
    lexicographic order, keeps the best packing score.
    """
    mesh = tuple(int(m) for m in mesh)
    rank = len(mesh)
    shape_n = normalize_shape(shape, rank)
    if len(shape_n) != rank:
        return None
    vol = 1
    for d in shape_n:
        vol *= d
    if vol > len(free):
        return None

    tried: set[tuple[int, ...]] = set()
    best: Optional[list[Coord]] = None
    best_score = float("inf")
    for perm in set(itertools.permutations(shape_n)):
        if perm in tried:
            continue
        tried.add(perm)
        if any(p > m for p, m in zip(perm, mesh)):
            continue
        # Wrap origins are only meaningful on axes where the box doesn't
        # already span the whole ring.
        for origin in itertools.product(*(range(m) for m in mesh)):
            if not torus and any(o + s > m for o, s, m in zip(origin, perm, mesh)):
                continue
            cells = box_coords(origin, perm, mesh, torus)
            if cells is None or any(c not in free for c in cells):
                continue
            score = _packing_score(cells, free, mesh)
            if score < best_score:
                best, best_score = cells, score
                if score == 0:
                    return best
    return best


def _neighbors(c: Coord, mesh: Coord, torus: bool) -> Iterable[Coord]:
    seen = {c}  # wrap on size-1/2 axes maps ±1 to self / one cell: dedupe
    for axis in range(len(mesh)):
        for d in (-1, 1):
            n = list(c)
            if torus:
                n[axis] = (n[axis] + d) % mesh[axis]
            else:
                n[axis] += d
                if not (0 <= n[axis] < mesh[axis]):
                    continue
            nt = tuple(n)
            if nt not in seen:
                seen.add(nt)
                yield nt


def allocate_compact(free: set[Coord], mesh: Sequence[int], count: int,
                     torus: bool = True) -> Optional[list[Coord]]:
    """Pick ``count`` free chips as compactly as connectivity allows.

    Greedy BFS from the free chip with the fewest free neighbors (most
    corner-packed), expanding toward cells adjacent to the chosen set.
    Falls back to lexicographic fill if the free set is disconnected.
    """
    if count <= 0:
        return []
    if count > len(free):
        return None
    mesh = tuple(int(m) for m in mesh)

    # Seed: most-constrained free cell (ties broken lexicographically).
    def free_degree(c: Coord) -> int:
        return sum(1 for n in _neighbors(c, mesh, torus) if n in free)

    seed = min(sorted(free), key=free_degree)
    chosen: list[Coord] = [seed]
    chosen_set = {seed}
    frontier: set[Coord] = {n for n in _neighbors(seed, mesh, torus) if n in free}
    while len(chosen) < count:
        if frontier:
            # Prefer frontier cells with most chosen neighbors (compactness),
            # then fewest free neighbors (corner packing).
            def key(c: Coord):
                chosen_adj = sum(1 for n in _neighbors(c, mesh, torus) if n in chosen_set)
                return (-chosen_adj, free_degree(c), c)

            nxt = min(frontier, key=key)
            frontier.discard(nxt)
        else:
            remaining = sorted(free - chosen_set)
            if not remaining:
                return None
            nxt = remaining[0]
        chosen.append(nxt)
        chosen_set.add(nxt)
        for n in _neighbors(nxt, mesh, torus):
            if n in free and n not in chosen_set:
                frontier.add(n)
    return chosen


def shape_for_count(count: int, mesh: Sequence[int]) -> Optional[tuple[int, ...]]:
    """Smallest-surface box shape with exactly ``count`` cells fitting in
    ``mesh`` (used to upgrade count requests to shaped ones when exact)."""
    mesh = tuple(int(m) for m in mesh)
    best = None
    best_surface = None

    def boxes(n: int, dims: int):
        if dims == 1:
            yield (n,)
            return
        for d in range(1, n + 1):
            if n % d == 0:
                for rest in boxes(n // d, dims - 1):
                    yield (d,) + rest

    for shape in boxes(count, len(mesh)):
        if any(s > m for s, m in zip(sorted(shape, reverse=True),
                                     sorted(mesh, reverse=True))):
            continue
        # surface area ~ communication cost of the bounding box
        surface = 0
        for i in range(len(shape)):
            face = 1
            for j, s in enumerate(shape):
                if j != i:
                    face *= s
            surface += 2 * face
        if best is None or surface < best_surface:
            best, best_surface = shape, surface
    return best
