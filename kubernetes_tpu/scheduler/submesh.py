"""Contiguous ICI sub-mesh allocation on a 3D (torus) chip mesh.

This is the TPU-first replacement for the reference's flat
extended-resource matcher (``plugin/pkg/scheduler/core/
extended_resources.go:113-150 allocateResources`` — count + attribute
matching with no notion of inter-device distance). On TPU, a JAX mesh
only gets full ICI bandwidth if its chips form a *contiguous axis-
aligned box* of the slice's 3D mesh (wrap-around links make each axis a
ring on full-axis slices), so allocation here is geometric:

- **Shaped requests** (``slice_shape=[a,b,c]``): find an axis-aligned
  a*b*c box of free chips, trying all axis permutations of the shape
  and all origins, with torus wrap-around per axis. First fit wins
  among candidates with the best packing score.
- **Count requests** (``chips=N``): greedy BFS over the free-chip
  neighbor graph from the most corner-packed free chip, so the chosen
  set is as compact as connectivity allows.
- **Scoring** prefers allocations that touch already-used regions
  (corner packing) to fight fragmentation — the NP-hard part of
  SURVEY.md section 7, handled with a cheap, deterministic heuristic.

Pure geometry, no API-object types: the scheduler cache feeds it free
coordinate sets. Three implementations share one contract:

- ``kubernetes_tpu/native/submesh.cpp`` — C++ summed-area-table scan,
  O(volume) per shape permutation; the production path (p99 well under
  10ms at 8k-chip slices, see tests/unit/test_submesh_native.py).
- :func:`_find_box_numpy` — the same algorithm vectorized with numpy;
  fallback when the native build is unavailable.
- :func:`_find_box_reference` — the original O(volume) - per-origin
  brute force; semantic source of truth, used by equivalence tests.
"""
from __future__ import annotations

import itertools
from typing import Iterable, Optional, Sequence

import numpy as np

Coord = tuple[int, ...]


def normalize_shape(shape: Sequence[int], rank: int) -> tuple[int, ...]:
    """Pad a request shape with 1s up to the mesh rank: [4] -> (4,1,1)."""
    s = tuple(int(d) for d in shape)
    if len(s) > rank:
        # Drop trailing 1s if possible ([2,2,1] on a 2D mesh -> (2,2)).
        while len(s) > rank and s[-1] == 1:
            s = s[:-1]
        if len(s) > rank:
            return s  # unsatisfiable; caller sees volume/dim mismatch
    return s + (1,) * (rank - len(s))


def box_coords(origin: Coord, shape: Coord, mesh: Coord, torus: bool) -> Optional[list[Coord]]:
    """Cells of the axis-aligned box at ``origin``; None if out of bounds."""
    for o, s, m in zip(origin, shape, mesh):
        if not torus and o + s > m:
            return None
        if s > m:
            return None
    ranges = []
    for o, s, m in zip(origin, shape, mesh):
        ranges.append([(o + i) % m for i in range(s)])
    return [tuple(c) for c in itertools.product(*ranges)]


def _packing_score(cells: list[Coord], free: set[Coord], mesh: Coord,
                   torus: bool = True) -> float:
    """Lower is better: prefer boxes whose neighbors are NOT free (touching
    walls or used regions), keeping the free space consolidated. Adjacency
    honors the torus flag: a non-torus slice has no wrap links, so cells
    across the seam are not neighbors."""
    cellset = set(cells)
    free_neighbors = 0
    for c in cells:
        for n in _neighbors(c, mesh, torus):
            if n not in cellset and n in free:
                free_neighbors += 1
    return free_neighbors


def find_box(free: set[Coord], mesh: Sequence[int], shape: Sequence[int],
             torus: bool = True) -> Optional[list[Coord]]:
    """Best free axis-aligned box of ``shape`` (any axis permutation).

    Returns the cell list or None. Deterministic: scans shape
    permutations in sorted order and origins in lexicographic order,
    keeps the first best packing score. Dispatches to the C++ fast path
    when available (3D and below), else the numpy implementation.
    """
    mesh = tuple(int(m) for m in mesh)
    rank = len(mesh)
    shape_n = normalize_shape(shape, rank)
    if len(shape_n) != rank:
        return None
    vol = 1
    for d in shape_n:
        vol *= d
    if vol > len(free):
        return None

    if rank <= 3:
        from ..util.features import GATES
        if GATES.enabled("NativeSubmeshFastPath"):
            result = _find_box_native(free, mesh, shape_n, torus)
            if result is not NotImplemented:
                return result
    return _find_box_numpy(free, mesh, shape_n, torus)


def _find_box_native(free: set[Coord], mesh: Coord, shape_n: Coord,
                     torus: bool):
    """C++ fast path; NotImplemented when the library is unavailable."""
    import ctypes

    from kubernetes_tpu.native import load_submesh
    lib = load_submesh()
    if lib is None:
        return NotImplemented
    rank = len(mesh)
    mesh3 = mesh + (1,) * (3 - rank)
    shape3 = shape_n + (1,) * (3 - rank)
    mask = np.zeros(mesh3, dtype=np.uint8)
    for c in free:
        mask[c + (0,) * (3 - rank)] = 1
    out = (ctypes.c_int32 * 6)()
    found = lib.tpu_find_box(
        mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        (ctypes.c_int32 * 3)(*mesh3),
        (ctypes.c_int32 * 3)(*shape3),
        1 if torus else 0, out)
    if not found:
        return None
    origin = tuple(out[:rank])
    perm = tuple(out[3:3 + rank])
    return box_coords(origin, perm, mesh, torus)


def _windowed_sums(tiled: np.ndarray, win: Sequence[int]) -> np.ndarray:
    """Sliding-window box sums: out[o] = sum of tiled[o : o+win).

    Successive 1-D cumsum differences along each axis — O(cells) per
    axis regardless of window size (the trick the C++ path implements
    with one 3D summed-area table).
    """
    a = tiled.astype(np.int32, copy=False)
    for ax, w in enumerate(win):
        c = np.cumsum(a, axis=ax)
        pad = np.zeros_like(np.take(c, [0], axis=ax))
        c = np.concatenate([pad, c], axis=ax)
        n = c.shape[ax]
        hi = [slice(None)] * a.ndim
        lo = [slice(None)] * a.ndim
        hi[ax] = slice(w, n)
        lo[ax] = slice(0, n - w)
        a = c[tuple(hi)] - c[tuple(lo)]
    return a


def _find_box_numpy(free: set[Coord], mesh: Coord, shape_n: Coord,
                    torus: bool) -> Optional[list[Coord]]:
    """Vectorized find_box: same scan order and scoring as the C++ path."""
    rank = len(mesh)
    mask = np.zeros(mesh, dtype=np.uint8)
    for c in free:
        mask[c] = 1
    tiled = np.tile(mask, (2,) * rank) if torus else mask
    core = tuple(slice(0, m) for m in mesh)

    best_score = None
    best: Optional[tuple[Coord, Coord]] = None  # (origin, perm)
    for perm in sorted(set(itertools.permutations(shape_n))):
        if any(p > m for p, m in zip(perm, mesh)):
            continue
        vol = 1
        for d in perm:
            vol *= d
        sums = _windowed_sums(tiled, perm)
        if torus:
            free_at = sums[core] == vol          # origins: full mesh grid
        else:
            free_at = sums == vol                # origins: mesh - perm + 1
        if not free_at.any():
            continue

        score = np.zeros(free_at.shape, dtype=np.int64)
        for ax in range(rank):
            if perm[ax] >= mesh[ax]:
                continue  # box spans the whole ring: no outside neighbors
            xsec = list(perm)
            xsec[ax] = 1
            w = _windowed_sums(tiled, xsec)
            if torus:
                w = w[core]
                low = np.roll(w, 1, axis=ax)
                score += low
                if not (mesh[ax] == 2 and perm[ax] == 1):
                    # m==2/s==1: -1 and +1 reach the same chip; count once.
                    score += np.roll(w, -perm[ax], axis=ax)
            else:
                pad_shape = list(free_at.shape)
                pad_shape[ax] = 1
                zero = np.zeros(pad_shape, dtype=w.dtype)
                npos = free_at.shape[ax]
                sl = [slice(None)] * rank
                sl[ax] = slice(0, npos)
                score += np.concatenate([zero, w], axis=ax)[tuple(sl)]
                sl[ax] = slice(perm[ax], perm[ax] + npos)
                score += np.concatenate([w, zero], axis=ax)[tuple(sl)]

        masked = np.where(free_at, score, np.iinfo(np.int64).max)
        flat = int(np.argmin(masked))  # C order => lexicographic first
        s = int(masked.reshape(-1)[flat])
        if s == np.iinfo(np.int64).max:
            continue
        origin = tuple(int(i) for i in np.unravel_index(flat, masked.shape))
        if best_score is None or s < best_score:
            best_score, best = s, (origin, perm)
            if s == 0:
                break
    if best is None:
        return None
    return box_coords(best[0], best[1], mesh, torus)


def _find_box_reference(free: set[Coord], mesh: Sequence[int],
                        shape: Sequence[int],
                        torus: bool = True) -> Optional[list[Coord]]:
    """Original brute-force scan — semantic source of truth for tests."""
    mesh = tuple(int(m) for m in mesh)
    rank = len(mesh)
    shape_n = normalize_shape(shape, rank)
    if len(shape_n) != rank:
        return None
    vol = 1
    for d in shape_n:
        vol *= d
    if vol > len(free):
        return None

    best: Optional[list[Coord]] = None
    best_score = float("inf")
    for perm in sorted(set(itertools.permutations(shape_n))):
        if any(p > m for p, m in zip(perm, mesh)):
            continue
        for origin in itertools.product(*(range(m) for m in mesh)):
            if not torus and any(o + s > m for o, s, m in zip(origin, perm, mesh)):
                continue
            cells = box_coords(origin, perm, mesh, torus)
            if cells is None or any(c not in free for c in cells):
                continue
            score = _packing_score(cells, free, mesh, torus)
            if score < best_score:
                best, best_score = cells, score
                if score == 0:
                    return best
    return best


def _neighbors(c: Coord, mesh: Coord, torus: bool) -> Iterable[Coord]:
    seen = {c}  # wrap on size-1/2 axes maps ±1 to self / one cell: dedupe
    for axis in range(len(mesh)):
        for d in (-1, 1):
            n = list(c)
            if torus:
                n[axis] = (n[axis] + d) % mesh[axis]
            else:
                n[axis] += d
                if not (0 <= n[axis] < mesh[axis]):
                    continue
            nt = tuple(n)
            if nt not in seen:
                seen.add(nt)
                yield nt


def allocate_compact(free: set[Coord], mesh: Sequence[int], count: int,
                     torus: bool = True) -> Optional[list[Coord]]:
    """Pick ``count`` free chips as compactly as connectivity allows.

    Greedy BFS from the free chip with the fewest free neighbors (most
    corner-packed), expanding toward cells adjacent to the chosen set.
    Falls back to lexicographic fill if the free set is disconnected.
    """
    if count <= 0:
        return []
    if count > len(free):
        return None
    mesh = tuple(int(m) for m in mesh)

    # Seed: most-constrained free cell (ties broken lexicographically).
    def free_degree(c: Coord) -> int:
        return sum(1 for n in _neighbors(c, mesh, torus) if n in free)

    seed = min(sorted(free), key=free_degree)
    chosen: list[Coord] = [seed]
    chosen_set = {seed}
    frontier: set[Coord] = {n for n in _neighbors(seed, mesh, torus) if n in free}
    while len(chosen) < count:
        if frontier:
            # Prefer frontier cells with most chosen neighbors (compactness),
            # then fewest free neighbors (corner packing).
            def key(c: Coord):
                chosen_adj = sum(1 for n in _neighbors(c, mesh, torus) if n in chosen_set)
                return (-chosen_adj, free_degree(c), c)

            nxt = min(frontier, key=key)
            frontier.discard(nxt)
        else:
            remaining = sorted(free - chosen_set)
            if not remaining:
                return None
            nxt = remaining[0]
        chosen.append(nxt)
        chosen_set.add(nxt)
        for n in _neighbors(nxt, mesh, torus):
            if n in free and n not in chosen_set:
                frontier.add(n)
    return chosen


def shape_for_count(count: int, mesh: Sequence[int]) -> Optional[tuple[int, ...]]:
    """Smallest-surface box shape with exactly ``count`` cells fitting in
    ``mesh`` (used to upgrade count requests to shaped ones when exact)."""
    mesh = tuple(int(m) for m in mesh)
    best = None
    best_surface = None

    def boxes(n: int, dims: int):
        if dims == 1:
            yield (n,)
            return
        for d in range(1, n + 1):
            if n % d == 0:
                for rest in boxes(n // d, dims - 1):
                    yield (d,) + rest

    for shape in boxes(count, len(mesh)):
        if any(s > m for s, m in zip(sorted(shape, reverse=True),
                                     sorted(mesh, reverse=True))):
            continue
        # surface area ~ communication cost of the bounding box
        surface = 0
        for i in range(len(shape)):
            face = 1
            for j, s in enumerate(shape):
                if j != i:
                    face *= s
            surface += 2 * face
        if best is None or surface < best_surface:
            best, best_surface = shape, surface
    return best


def largest_free_box_volume(free: set[Coord], mesh: Sequence[int],
                            torus: bool = True) -> int:
    """Volume of the largest axis-aligned box of free cells in the mesh
    — the "how big a gang could this slice still host?" number the
    serving-placement score protects.

    Scans candidate shapes in descending volume over the same windowed
    box-sum machinery as :func:`_find_box_numpy`; the shape space is
    ``prod(mesh)`` candidates (e.g. 64 for a 4x4x4 slice), each checked
    in O(cells), so the cost is small at the slice sizes placement
    scoring touches (and callers memoize per scheduling pass anyway).
    """
    if not free:
        return 0
    mesh_t = tuple(int(m) for m in mesh)
    rank = len(mesh_t)
    mask = np.zeros(mesh_t, dtype=np.uint8)
    for c in free:
        mask[c] = 1
    tiled = np.tile(mask, (2,) * rank) if torus else mask
    core = tuple(slice(0, m) for m in mesh_t)
    shapes = sorted(
        itertools.product(*(range(1, m + 1) for m in mesh_t)),
        key=lambda sh: (-int(np.prod(sh)), sh))
    upper = len(free)
    for shape in shapes:
        vol = int(np.prod(shape))
        if vol > upper:
            continue
        sums = _windowed_sums(tiled, shape)
        if torus:
            sums = sums[core]
        if bool((sums == vol).any()):
            return vol
    return 1  # free is non-empty: a 1-cell box always exists


def fragmentation(free: set[Coord], mesh: Sequence[int],
                  torus: bool = True) -> float:
    """THE fleet fragmentation definition, shared by the defrag
    planner (controllers/migrate.py), the ClusterMonitor
    ``tpu_cluster_fragmentation`` gauge, kmon recording rules, and
    ``ktl top nodes``: ``1 - largest free contiguous box / free
    chips``. 0.0 = every free chip reachable as one box (including the
    empty slice — nothing to defragment); approaching 1.0 = free
    capacity shredded into unusably small boxes.
    """
    if not free:
        return 0.0
    return 1.0 - largest_free_box_volume(free, mesh, torus) / len(free)


def find_box_containing(available: set[Coord], mesh: Sequence[int],
                        shape: Sequence[int], required: Iterable[Coord],
                        torus: bool = True) -> Optional[list[Coord]]:
    """Box of ``shape`` (any axis permutation) covering every coord in
    ``required``, with all cells drawn from ``available``.

    Gang partial-bind recovery uses this: already-bound members hold
    chips at ``required`` coords, and the recovered gang must still be
    one contiguous box — so the remainder is planned inside a full-shape
    box anchored on the survivors. The required coords prune the origin
    space to a handful of candidates per axis, so a plain scan suffices
    even at large mesh sizes.
    """
    req = {tuple(int(c) for c in r) for r in required}
    if not req:
        return find_box(available, mesh, shape, torus)
    mesh_t = tuple(int(m) for m in mesh)
    rank = len(mesh_t)
    shape_n = normalize_shape(shape, rank)
    if len(shape_n) != rank or any(len(r) != rank for r in req):
        return None
    avail = set(available) | req

    for perm in sorted(set(itertools.permutations(shape_n))):
        dim_opts: list[list[int]] = []
        for d in range(rank):
            s, m = perm[d], mesh_t[d]
            coords_d = {r[d] for r in req}
            if s > m:
                break  # infeasible axis assignment
            if s == m:
                opts = [0]
            elif torus:
                opts = [o for o in range(m)
                        if all((c - o) % m < s for c in coords_d)]
            else:
                lo = max(max(coords_d) - s + 1, 0)
                hi = min(min(coords_d), m - s)
                opts = list(range(lo, hi + 1))
            if not opts:
                break
            dim_opts.append(opts)
        if len(dim_opts) != rank:
            continue
        for origin in itertools.product(*dim_opts):
            cells = [tuple((origin[d] + off[d]) % mesh_t[d]
                           for d in range(rank))
                     for off in itertools.product(*(range(s) for s in perm))]
            if all(c in avail for c in cells):
                return cells
    return None
