"""Fit predicates — can this pod run on this node at all?

Reference: ``plugin/pkg/scheduler/algorithm/predicates/predicates.go``
(PodFitsResources, PodMatchNodeSelector, PodToleratesNodeTaints,
NodeCondition checks) plus the fork's per-device phase
(``core/extended_resources.go:83 hasExtendedResources``). The TPU phase
here checks chip availability *and geometry*: a shaped claim must have
a free contiguous box on the node (single-node claims) — counted
chips alone are not enough.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..api import types as t
from .cache import NodeInfo
from .submesh import allocate_compact, find_box


#: Canonical policy-file keys for each predicate (policy.py maps the
#: reference spellings onto these; every gate site imports these names
#: so a typo is an ImportError, not a silently-skipped predicate).
PRED_NODE_CONDITION = "CheckNodeCondition"
PRED_NODE_PRESSURE = "CheckNodePressure"
PRED_TAINTS = "PodToleratesNodeTaints"
PRED_NODE_SELECTOR = "MatchNodeSelector"
PRED_RESOURCES = "PodFitsResources"
PRED_INTERPOD_AFFINITY = "MatchInterPodAffinity"


@dataclass
class PredicateResult:
    fits: bool
    reasons: list[str]


def pod_fits_resources(pod: t.Pod, info: NodeInfo,
                       requests=None) -> Optional[str]:
    alloc = info.allocatable()
    if requests is None:
        requests = t.pod_resource_requests(pod)
    for res, want in requests.items():
        if res == t.RESOURCE_TPU:
            continue  # handled geometrically below
        have = alloc.get(res)
        if have is None:
            if res in (t.RESOURCE_CPU, t.RESOURCE_MEMORY, t.RESOURCE_PODS):
                have = 0.0
            else:
                return f"node lacks resource {res}"
        if info.requested.get(res, 0.0) + want > have + 1e-9:
            return (f"insufficient {res}: requested {info.requested.get(res, 0.0):g}"
                    f"+{want:g} > allocatable {have:g}")
    return None


def pod_matches_node_selector(pod: t.Pod, node: t.Node) -> Optional[str]:
    labels = node.metadata.labels
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return f"node selector {k}={v} does not match"
    aff = pod.spec.affinity
    if aff and aff.node_required:
        if not any(term.matches(labels) for term in aff.node_required):
            return "node affinity required terms do not match"
    return None


def pod_tolerates_taints(pod: t.Pod, node: t.Node) -> Optional[str]:
    for taint in node.spec.taints:
        if taint.effect not in (t.TAINT_NO_SCHEDULE, t.TAINT_NO_EXECUTE):
            continue
        if not any(tol.tolerates(taint) for tol in pod.spec.tolerations):
            return f"untolerated taint {taint.key}:{taint.effect}"
    return None


def node_is_schedulable(node: t.Node) -> Optional[str]:
    if node.spec.unschedulable:
        return "node is unschedulable (cordoned)"
    cond = t.get_node_condition(node.status, t.NODE_READY)
    if cond is not None and cond.status != "True":
        return "node is not Ready"
    return None


def node_pressure_allows(pod: t.Pod, node: t.Node) -> Optional[str]:
    """CheckNodeMemoryPressure / CheckNodeDiskPressure (reference:
    ``algorithm/predicates/predicates.go``): under MemoryPressure only
    pods with memory requests (non-BestEffort) may land; under
    DiskPressure nothing may."""
    disk = t.get_node_condition(node.status, t.NODE_DISK_PRESSURE)
    if disk is not None and disk.status == "True":
        return "node has DiskPressure"
    mem = t.get_node_condition(node.status, t.NODE_MEMORY_PRESSURE)
    if mem is not None and mem.status == "True":
        requests = t.pod_resource_requests(pod)
        if not requests.get(t.RESOURCE_MEMORY):
            return "node has MemoryPressure (best-effort pod rejected)"
    return None


def _chip_matches(chip: t.TpuChip, claim: t.PodTpuRequest) -> bool:
    # Attribute affinity (fork: extended_resources.go:152 isDeviceAMatch).
    return all(r.matches(chip.attributes) for r in claim.affinity)


def pod_fits_tpus(pod: t.Pod, info: NodeInfo) -> Optional[str]:
    """Per-claim geometric fit. Single-node path: each claim must be
    satisfiable from this node's free chips alone (gangs use the slice
    path in gang.py instead)."""
    if not pod.spec.tpu_resources:
        return None
    topo = info.node.status.tpu if info.node else None
    if topo is None:
        return "node has no TPUs"
    # Claims are checked independently but must not share chips.
    taken: set[str] = set()
    for claim in pod.spec.tpu_resources:
        eligible = {cid: c for cid, c in info.free_chips.items()
                    if cid not in taken and _chip_matches(c, claim)}
        want = claim.chip_count()
        if len(eligible) < want:
            return (f"claim {claim.name!r}: {len(eligible)} matching free "
                    f"chips, want {want}")
        coords = {tuple(c.coords): cid for cid, c in eligible.items() if c.coords}
        if claim.slice_shape:
            if len(coords) < want:
                return f"claim {claim.name!r}: chips lack mesh coordinates"
            cells = find_box(set(coords), topo.mesh_shape, claim.slice_shape)
            if cells is None:
                return (f"claim {claim.name!r}: no free contiguous "
                        f"{'x'.join(map(str, claim.slice_shape))} sub-mesh")
            for cell in cells:
                taken.add(coords[cell])
        else:
            if len(coords) >= want:
                cells = allocate_compact(set(coords), topo.mesh_shape, want)
                for cell in cells or []:
                    taken.add(coords[cell])
            else:  # coordless chips (stub plugins): plain counting
                for cid in list(eligible)[:want]:
                    taken.add(cid)
    return None


def select_chips(pod: t.Pod, info: NodeInfo) -> Optional[list[t.TpuBinding]]:
    """Concrete chip choice for a feasible single-node pod (the fork's
    ``allocateResources``, ``extended_resources.go:113``)."""
    if not pod.spec.tpu_resources:
        return []
    topo = info.node.status.tpu if info.node else None
    if topo is None:
        return None
    bindings: list[t.TpuBinding] = []
    taken: set[str] = set()
    for claim in pod.spec.tpu_resources:
        eligible = {cid: c for cid, c in info.free_chips.items()
                    if cid not in taken and _chip_matches(c, claim)}
        want = claim.chip_count()
        coords = {tuple(c.coords): cid for cid, c in eligible.items() if c.coords}
        chosen: list[str] = []
        if claim.slice_shape and len(coords) >= want:
            cells = find_box(set(coords), topo.mesh_shape, claim.slice_shape)
            if cells is None:
                return None
            chosen = [coords[c] for c in cells]
        elif len(coords) >= want:
            cells = allocate_compact(set(coords), topo.mesh_shape, want)
            if cells is None:
                return None
            chosen = [coords[c] for c in cells]
        else:
            if len(eligible) < want:
                return None
            chosen = sorted(eligible)[:want]
        taken.update(chosen)
        bindings.append(t.TpuBinding(name=claim.name, chip_ids=sorted(chosen)))
    return bindings


#: Ordered predicate set (cheap checks first, like the reference's
#: predicates ordering).
def run_predicates(pod: t.Pod, info: NodeInfo,
                   skip_tpu: bool = False,
                   requests=None,
                   enabled=None) -> PredicateResult:
    """``skip_tpu=True`` lets the caller run :func:`select_chips` itself
    (one geometry computation serving fit, score, and selection).
    ``requests``: precomputed pod_resource_requests, computed once per
    pod by the scheduler instead of once per (pod, node).
    ``enabled``: policy-selected predicate set (policy.py canonical
    keys); None runs everything. The TPU phase is structural and not
    gated (see policy.py module docstring)."""
    node = info.node
    if node is None:
        return PredicateResult(False, ["node unknown"])
    on = enabled.__contains__ if enabled is not None else lambda _k: True
    checks = [
        node_is_schedulable(node) if on(PRED_NODE_CONDITION) else None,
        node_pressure_allows(pod, node) if on(PRED_NODE_PRESSURE) else None,
        pod_tolerates_taints(pod, node) if on(PRED_TAINTS) else None,
        pod_matches_node_selector(pod, node)
        if on(PRED_NODE_SELECTOR) else None,
        pod_fits_resources(pod, info, requests)
        if on(PRED_RESOURCES) else None,
    ]
    if not skip_tpu:
        checks.append(pod_fits_tpus(pod, info))
    reasons = [c for c in checks if c]
    return PredicateResult(not reasons, reasons)
