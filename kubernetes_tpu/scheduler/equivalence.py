"""Predicate equivalence cache.

Reference: ``plugin/pkg/scheduler/core/equivalence_cache.go`` — pods
that are interchangeable for predicate purposes (same requests,
selectors, tolerations, affinity) share cached per-node fit results,
so scheduling N identical replicas costs one predicate pass plus cache
hits instead of N full scans. Entries are invalidated per node on ANY
accounting change there (add/remove/assume/forget/node update) —
correctness first, the hit rate comes from the untouched nodes.

TPU pods are NEVER cached: chip geometry changes with every allocation
on the node, so their fit answer is inherently per-state.
"""
from __future__ import annotations

import json
from typing import Optional

from ..api import types as t
from ..api.scheme import to_dict


def equivalence_hash(pod: t.Pod) -> Optional[int]:
    """Equivalence-class key, or None when the pod must not be cached.
    The payload must cover EVERY pod field any predicate reads
    (requests, selectors, tolerations, affinity, pressure-relevant
    requests) — adding a predicate that reads a new field means
    extending this payload."""
    if pod.spec.tpu_resources:
        return None
    payload = {
        "req": t.pod_resource_requests(pod),
        "sel": pod.spec.node_selector,
        "tol": [(x.key, x.operator, x.value, x.effect)
                for x in pod.spec.tolerations],
        "aff": to_dict(pod.spec.affinity) if pod.spec.affinity else None,
    }
    # The dumps IS the cache key: one serialization here saves a
    # full-fleet predicate pass on every equivalence-class hit.
    return hash(json.dumps(payload, sort_keys=True, default=str))  # tpuvet: ignore[hot-path-cost]


class EquivalenceCache:
    #: Max equivalence classes kept per node — one-off pods each mint a
    #: fresh class, and accounting-quiet (full/cordoned) nodes never
    #: invalidate, so an unbounded map grows monotonically. FIFO evict.
    MAX_CLASSES_PER_NODE = 128

    def __init__(self):
        #: node name -> {eq hash: (fits, reasons)} (insertion-ordered)
        self._by_node: dict[str, dict[int, tuple[bool, list[str]]]] = {}
        self.hits = 0
        self.misses = 0

    def lookup(self, node_name: str, eq: int) -> Optional[tuple[bool, list]]:
        got = self._by_node.get(node_name, {}).get(eq)
        if got is None:
            self.misses += 1
        else:
            self.hits += 1
        return got

    def store(self, node_name: str, eq: int, fits: bool,
              reasons: list) -> None:
        entries = self._by_node.setdefault(node_name, {})
        while len(entries) >= self.MAX_CLASSES_PER_NODE:
            entries.pop(next(iter(entries)))
        entries[eq] = (fits, list(reasons))

    def invalidate_node(self, node_name: str) -> None:
        self._by_node.pop(node_name, None)

    def invalidate_all(self) -> None:
        self._by_node.clear()
