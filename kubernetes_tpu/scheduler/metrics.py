"""Scheduler metrics — the north-star latency histograms.

Reference: ``plugin/pkg/scheduler/metrics/metrics.go:31-66``
(E2eSchedulingLatency, SchedulingAlgorithmLatency, BindingLatency).
BASELINE.md designates pod-schedule p50 as the headline metric; these
histograms are what bench.py and the e2e suite read.
"""
from ..metrics.registry import Counter, Gauge, Histogram

_LAT_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.5,
                5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1200.0)

E2E_SCHEDULING_LATENCY = Histogram(
    "scheduler_e2e_scheduling_latency_seconds",
    "Queue-pop to bind-acknowledged latency per pod",
    buckets=_LAT_BUCKETS)

ALGORITHM_LATENCY = Histogram(
    "scheduler_algorithm_latency_seconds",
    "Predicate+priority+assign phase latency",
    buckets=_LAT_BUCKETS)

BINDING_LATENCY = Histogram(
    "scheduler_binding_latency_seconds",
    "Binding subresource POST latency",
    buckets=_LAT_BUCKETS,
    # Raw samples so the density harness reports TRUE bind-call
    # percentiles, not bucket upper bounds (the 250.0/100.0ms
    # artifacts); 100k floats cap ~0.8MB, reset() between runs.
    sample_limit=100_000)

GANG_SCHEDULING_LATENCY = Histogram(
    "scheduler_gang_e2e_latency_seconds",
    "Gang release to all-members-bound latency",
    buckets=_LAT_BUCKETS)

PREEMPTION_LATENCY = Histogram(
    "scheduler_preemption_latency_seconds",
    "Preemption decision to all-members-bound latency per gang "
    "(victim eviction + box reservation + re-plan + bind)",
    buckets=_LAT_BUCKETS)

PODS_SCHEDULED = Counter(
    "scheduler_pods_scheduled_total", "Successfully bound pods",
    labels=("result",))

PREEMPTION_VICTIMS = Counter(
    "scheduler_preemption_victims_total", "Pods evicted by preemption")

PENDING_PODS = Gauge("scheduler_pending_pods", "Pods waiting in queue")

#: Loop-lag probe family (util/loopprobe.py — the apiserver
#: router/shard probes' scheduler sibling, PR 9 instrumented only
#: those): how late the scheduler's event loop runs per tick. The
#: density harness reports the busy fraction beside the apiserver's —
#: ROADMAP item 3 names scheduler-side CPU as the next wall.
LOOP_LAG = Histogram(
    "scheduler_loop_lag_ms",
    "Event-loop scheduling lag per probe tick on the scheduler loop",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
             250.0, 500.0, 1000.0),
    sample_limit=20_000)

LOOP_BUSY = Gauge(
    "scheduler_loop_busy_fraction",
    "EWMA busy fraction of the scheduler event loop (loop-lag derived)")

#: SchedulerFastPath batch-drain family: with the gate on, the main
#: loop drains the queue in batches and places eligible pods through
#: the columnar snapshot (fleetarray.py); these make the split
#: vector/masked/scalar visible so a fleet profile can tell whether
#: the fast path actually engaged.
BATCH_SIZE = Histogram(
    "scheduler_batch_size_pods",
    "Queue items drained per scheduling-loop batch (SchedulerFastPath)",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0))

BATCH_FASTPATH = Counter(
    "scheduler_batch_fastpath_total",
    "Placement attempts by path under SchedulerFastPath: vector "
    "(fully columnar), masked (columnar predicate prefilter + scalar "
    "chip geometry), scalar (exact fallback)",
    labels=("path",))
