"""Scheduler policy file — operator-selected predicates/priorities/extenders.

Reference: ``plugin/pkg/scheduler/api/types.go`` (Policy,
PredicatePolicy, PriorityPolicy, ExtenderConfig) loaded by
``factory.go CreateFromConfig``: a JSON/YAML document that names which
fit predicates run, which priorities score (with weights), and which
out-of-process extenders participate. The TPU chip fit/selection phase
is NOT policy-selectable — like the reference's extended-resources
assigner (``core/extended_resources.go``, invoked unconditionally after
predicates in ``core/generic_scheduler.go``), it is structural: the
binding needs concrete chip IDs, so there is no meaningful scheduler
without it.

File shape (both snake_case and the reference's camelCase accepted)::

    kind: Policy
    predicates:
      - name: PodFitsResources
      - name: PodToleratesNodeTaints
    priorities:
      - name: LeastRequestedPriority
        weight: 1
    extenders:
      - urlPrefix: http://127.0.0.1:9998/scheduler
        filterVerb: filter
        prioritizeVerb: prioritize
        weight: 2
        managedResources: [example.com/widget]
        ignorable: true

Omitting ``predicates``/``priorities`` entirely keeps the defaults;
an empty list means "none of them" (reference semantics: the policy is
the complete list).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

from .extender import SchedulerExtender
from .predicates import (PRED_INTERPOD_AFFINITY, PRED_NODE_CONDITION,
                         PRED_NODE_PRESSURE, PRED_NODE_SELECTOR,
                         PRED_RESOURCES, PRED_TAINTS)
from .priorities import (DEFAULT_PRIORITIES, PRI_BALANCED,
                         PRI_INTERPOD_AFFINITY, PRI_LEAST_REQUESTED,
                         PRI_NODE_AFFINITY, PRI_RESOURCE_LIMITS,
                         PRI_SELECTOR_SPREAD, PRI_TPU_DEFRAG,
                         TPU_DEFRAG_WEIGHT)

#: Canonical predicate key -> accepted policy-file spellings.
#: Canonical keys are what predicates.run_predicates gates on.
PREDICATE_ALIASES: dict[str, tuple[str, ...]] = {
    PRED_NODE_CONDITION: (PRED_NODE_CONDITION, "NodeSchedulable"),
    PRED_NODE_PRESSURE: (PRED_NODE_PRESSURE, "CheckNodeMemoryPressure",
                         "CheckNodeDiskPressure"),
    PRED_TAINTS: (PRED_TAINTS,),
    PRED_NODE_SELECTOR: (PRED_NODE_SELECTOR, "PodMatchNodeSelector"),
    PRED_RESOURCES: (PRED_RESOURCES,),
    PRED_INTERPOD_AFFINITY: (PRED_INTERPOD_AFFINITY,),
}

#: Canonical priority key -> accepted spellings (reference names end in
#: "Priority"; the short forms are this repo's DEFAULT_PRIORITIES keys).
PRIORITY_ALIASES: dict[str, tuple[str, ...]] = {
    PRI_LEAST_REQUESTED: (PRI_LEAST_REQUESTED, "LeastRequestedPriority"),
    PRI_BALANCED: (PRI_BALANCED, "BalancedResourceAllocation"),
    PRI_NODE_AFFINITY: (PRI_NODE_AFFINITY, "NodeAffinityPriority",
                        "NodePreferAvoidPodsPriority"),
    PRI_RESOURCE_LIMITS: (PRI_RESOURCE_LIMITS, "ResourceLimitsPriority"),
    PRI_SELECTOR_SPREAD: (PRI_SELECTOR_SPREAD, "SelectorSpreadPriority"),
    PRI_TPU_DEFRAG: (PRI_TPU_DEFRAG, "TpuDefragPriority"),
    PRI_INTERPOD_AFFINITY: (PRI_INTERPOD_AFFINITY,
                            "InterPodAffinityPriority"),
}

_PREDICATE_BY_SPELLING = {s: canon for canon, spells in
                          PREDICATE_ALIASES.items() for s in spells}
_PRIORITY_BY_SPELLING = {s: canon for canon, spells in
                         PRIORITY_ALIASES.items() for s in spells}

#: Default weights: DEFAULT_PRIORITIES + the fused-loop extras.
DEFAULT_WEIGHTS: dict[str, float] = {
    **{name: w for name, _fn, w in DEFAULT_PRIORITIES},
    PRI_SELECTOR_SPREAD: 1.0,
    PRI_TPU_DEFRAG: TPU_DEFRAG_WEIGHT,
    PRI_INTERPOD_AFFINITY: 1.0,
}


@dataclass
class SchedulerPolicy:
    #: None = default set; otherwise the canonical predicate keys to run.
    enabled_predicates: Optional[frozenset] = None
    #: None = DEFAULT_WEIGHTS; otherwise canonical name -> weight, with
    #: unlisted priorities at weight 0 (the policy is the whole list).
    priority_weights: Optional[dict] = None
    extenders: list = field(default_factory=list)

    def weight(self, name: str) -> float:
        if self.priority_weights is None:
            return DEFAULT_WEIGHTS[name]
        return self.priority_weights.get(name, 0.0)

    def predicate_enabled(self, name: str) -> bool:
        return (self.enabled_predicates is None
                or name in self.enabled_predicates)


def _get(d: dict, *names, default=None):
    for n in names:
        if n in d:
            return d[n]
    return default


def parse_policy(raw: dict, source: str = "<policy>") -> SchedulerPolicy:
    if not isinstance(raw, dict):
        raise ValueError(f"{source}: policy document must be a mapping")
    if raw.get("kind", "Policy") != "Policy":
        raise ValueError(f"{source}: kind must be Policy")
    pol = SchedulerPolicy()
    preds = raw.get("predicates")
    if preds is not None:
        enabled = set()
        for i, p in enumerate(preds):
            name = p.get("name") if isinstance(p, dict) else p
            canon = _PREDICATE_BY_SPELLING.get(name or "")
            if canon is None:
                raise ValueError(
                    f"{source}: predicates[{i}]: unknown predicate "
                    f"{name!r} (known: {sorted(_PREDICATE_BY_SPELLING)})")
            enabled.add(canon)
        pol.enabled_predicates = frozenset(enabled)
    prios = raw.get("priorities")
    if prios is not None:
        weights: dict[str, float] = {}
        for i, p in enumerate(prios):
            if not isinstance(p, dict):
                p = {"name": p}
            name = p.get("name")
            canon = _PRIORITY_BY_SPELLING.get(name or "")
            if canon is None:
                raise ValueError(
                    f"{source}: priorities[{i}]: unknown priority "
                    f"{name!r} (known: {sorted(_PRIORITY_BY_SPELLING)})")
            try:
                w = float(p.get("weight", 1.0))
            except (TypeError, ValueError):
                raise ValueError(f"{source}: priorities[{i}]: weight "
                                 f"must be a number") from None
            import math
            if not math.isfinite(w) or w < 0:
                raise ValueError(
                    f"{source}: priorities[{i}]: weight must be finite "
                    f"and non-negative")
            weights[canon] = weights.get(canon, 0.0) + w
        pol.priority_weights = weights
    for i, e in enumerate(raw.get("extenders") or []):
        if not isinstance(e, dict):
            raise ValueError(f"{source}: extenders[{i}] must be a mapping")
        url = _get(e, "url_prefix", "urlPrefix")
        if not url:
            raise ValueError(f"{source}: extenders[{i}]: urlPrefix required")
        import math
        try:
            weight = float(_get(e, "weight", default=1.0))
            timeout = float(_get(e, "timeout", "httpTimeout", default=5.0))
        except (TypeError, ValueError):
            raise ValueError(f"{source}: extenders[{i}]: weight and "
                             f"timeout must be numbers") from None
        # Non-finite values pass plain comparisons ('nan' < 0 is False)
        # and would NaN-poison every score / hang the HTTP call.
        if not math.isfinite(weight) or weight < 0:
            raise ValueError(f"{source}: extenders[{i}]: weight must be "
                             f"finite and non-negative")
        if not math.isfinite(timeout) or timeout <= 0:
            raise ValueError(f"{source}: extenders[{i}]: timeout must be "
                             f"finite and positive")
        pol.extenders.append(SchedulerExtender(
            url_prefix=url,
            filter_verb=_get(e, "filter_verb", "filterVerb",
                             default="filter"),
            prioritize_verb=_get(e, "prioritize_verb", "prioritizeVerb",
                                 default="prioritize"),
            weight=weight,
            managed_resources=tuple(
                _get(e, "managed_resources", "managedResources",
                     default=()) or ()),
            timeout=timeout,
            ignorable=bool(_get(e, "ignorable", default=False)),
        ))
    return pol


def load_policy(path: str) -> SchedulerPolicy:
    """Load a Policy file. ``.json`` parses as JSON, anything else as
    YAML (reference kube-scheduler's --policy-config-file accepts both)."""
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        raw = json.loads(text)
    else:
        import yaml
        raw = yaml.safe_load(text) or {}
    return parse_policy(raw, source=path)
