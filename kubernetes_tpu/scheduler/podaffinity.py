"""Inter-pod affinity/anti-affinity — predicate + priority.

Reference: ``algorithm/predicates/predicates.go MatchInterPodAffinity``
and ``algorithm/priorities/interpod_affinity.go``. Semantics (v1.9
required terms):

- **affinity**: each term needs an existing pod matching its selector
  (in the term's namespaces; default = the incoming pod's) running in
  the candidate node's topology domain. First-pod bootstrap rule: a
  term nothing matches yet is satisfied everywhere IF the incoming pod
  itself matches it (else a replica group could never start).
- **anti-affinity**: no matching pod may run in the candidate's domain;
  plus the symmetric check — an existing pod's required anti-affinity
  term matching the incoming pod forbids that pod's domain.

Scale shape: the reference evaluates terms per (pod, node), which is
the O(nodes x pods) trap VERDICT flagged elsewhere; here an
:class:`AffinityContext` is built ONCE per incoming pod (a single scan
of cached pods, skipped entirely when neither the pod nor the cluster
uses affinity — the cache counts anti-affinity pods incrementally) and
every node check is O(terms) set lookups.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api import types as t

HOSTNAME_KEY = "kubernetes.io/hostname"


def _term_namespaces(term: t.PodAffinityTerm, pod_ns: str) -> set[str]:
    return set(term.namespaces) if term.namespaces else {pod_ns}


def _matches(term: t.PodAffinityTerm, other: t.Pod, pod_ns: str) -> bool:
    if other.metadata.namespace not in _term_namespaces(term, pod_ns):
        return False
    sel = term.label_selector
    return sel is not None and sel.matches(other.metadata.labels)


def _topo_value(node: t.Node, key: str) -> Optional[str]:
    if key == HOSTNAME_KEY:
        # Every node has an implicit hostname value even if unlabeled.
        return node.metadata.labels.get(key, node.metadata.name)
    return node.metadata.labels.get(key)


@dataclass
class _TermDomains:
    term: t.PodAffinityTerm
    #: Topology values where a matching pod runs.
    values: set = field(default_factory=set)
    #: Bootstrap rule: term matches the incoming pod itself.
    self_match: bool = False


@dataclass
class AffinityContext:
    required: list[_TermDomains]
    anti: list[_TermDomains]
    #: (topology_key, value) domains forbidden by EXISTING pods'
    #: required anti-affinity terms that match the incoming pod.
    forbidden_by_existing: set
    #: Weighted preferred terms: (weight, _TermDomains), anti negated.
    preferred: list

    def node_allows(self, node: t.Node) -> Optional[str]:
        """Reason the node is infeasible, or None."""
        for td in self.required:
            value = _topo_value(node, td.term.topology_key)
            if value is None:
                # A node without the topology key can never satisfy a
                # required term (reference semantics); admitting it via
                # the bootstrap rule would silently drop the constraint
                # for every later replica too.
                return (f"node lacks topology key "
                        f"{td.term.topology_key!r} required by pod affinity")
            if value in td.values:
                continue
            if not td.values and td.self_match:
                continue  # first pod of its own group
            return ("pod affinity: no pod matching "
                    f"{td.term.label_selector} in this "
                    f"{td.term.topology_key} domain")
        for td in self.anti:
            value = _topo_value(node, td.term.topology_key)
            if value is not None and value in td.values:
                return ("pod anti-affinity: matching pod already in "
                        f"this {td.term.topology_key} domain")
        for key, value in self.forbidden_by_existing:
            if _topo_value(node, key) == value:
                return ("existing pod's anti-affinity forbids this "
                        f"{key} domain")
        return None

    def score(self, node: t.Node) -> float:
        total = 0.0
        for weight, td in self.preferred:
            value = _topo_value(node, td.term.topology_key)
            if value is not None and value in td.values:
                total += weight
        return total


def build_context(pod: t.Pod, cache) -> Optional[AffinityContext]:
    """None when no affinity applies (the common, zero-cost case)."""
    aff = pod.spec.affinity
    has_own = bool(aff and (aff.pod_affinity or aff.pod_anti_affinity
                            or aff.pod_affinity_preferred
                            or aff.pod_anti_affinity_preferred))
    cluster_has_anti = bool(getattr(cache, "anti_affinity_pods", None))
    if not has_own and not cluster_has_anti:
        return None
    ns = pod.metadata.namespace

    required = [_TermDomains(term) for term in (aff.pod_affinity if aff else [])]
    anti = [_TermDomains(term) for term in (aff.pod_anti_affinity if aff else [])]
    preferred = [(wt.weight, _TermDomains(wt.pod_affinity_term))
                 for wt in (aff.pod_affinity_preferred if aff else [])]
    preferred += [(-wt.weight, _TermDomains(wt.pod_affinity_term))
                  for wt in (aff.pod_anti_affinity_preferred if aff else [])]
    for td in required + anti:
        td.self_match = _matches(td.term, pod, ns)

    own_terms = required + anti + [td for _w, td in preferred]
    incoming_key = pod.key()
    if own_terms:  # affinity-free pods skip the cluster scan entirely
        for info in cache.nodes.values():
            if info.node is None:
                continue
            for other in info.pods.values():
                if other.key() == incoming_key:
                    continue
                for td in own_terms:
                    if _matches(td.term, other, ns):
                        value = _topo_value(info.node, td.term.topology_key)
                        if value is not None:
                            td.values.add(value)

    forbidden = set()
    for other_key, other in getattr(cache, "anti_affinity_pods", {}).items():
        if other_key == incoming_key:
            continue
        info = cache.nodes.get(other.spec.node_name)
        if info is None or info.node is None:
            continue
        other_aff = other.spec.affinity
        for term in other_aff.pod_anti_affinity:
            if _matches(term, pod, other.metadata.namespace):
                value = _topo_value(info.node, term.topology_key)
                if value is not None:
                    forbidden.add((term.topology_key, value))
    return AffinityContext(required=required, anti=anti,
                           forbidden_by_existing=forbidden,
                           preferred=preferred)
