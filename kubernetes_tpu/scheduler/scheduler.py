"""Scheduler main loop: watch -> queue -> fit -> score -> assume -> bind.

Reference control flow (SURVEY.md section 3.2):
``plugin/pkg/scheduler/scheduler.go:430 scheduleOne`` ->
``core/generic_scheduler.go:109 Schedule`` (findNodesThatFit ->
PrioritizeNodes -> selectHost) -> assume -> async bind -> on failure
``:199 Preempt``. Differences by design:

- **Gangs**: a GangUnit pops as one item; all members are planned on
  one slice (gang.py), assumed together, bound concurrently, and
  rolled back together if any bind fails.
- **TPU assignment** happens at fit time (predicates select concrete
  chips) so assume debits exact chip IDs — mirroring the fork's
  scheduler-cache ER manager, but geometry-aware.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

from .. import tracing
from ..api import errors, types as t
from ..api.scheme import deepcopy
from ..client.informer import SharedInformer
from ..client.interface import Client
from ..client.record import EventRecorder
from ..util.loopprobe import loop_lag_probe
from ..util.tasks import spawn
from ..util.trace import Trace
from . import metrics as m
from .cache import SchedulerCache
from .gang import GangFailure, GangPlan, plan_gang
from .predicates import run_predicates, select_chips
from .priorities import prioritize
from .queue import GangUnit, SchedulingQueue

log = logging.getLogger("scheduler")


def group_suspended(group: t.PodGroup) -> bool:
    """Queue-admission suspend gate: a PodGroup bound to a LocalQueue
    stays out of the scheduling heap until the QueueController admits
    it. With the JobQueueing gate off, ``spec.queue`` is ignored and
    behavior is byte-identical to the ungated build."""
    if not group.spec.queue or group.status.admitted:
        return False
    from ..util.features import GATES
    return GATES.enabled("JobQueueing")


class _BindCoalescer:
    """Size/time-windowed batcher for ``_schedule_one``'s async binds.

    Policy (Nagle without the idle-path delay): a bind dispatches
    IMMEDIATELY while an RPC slot is free — an isolated pod below
    saturation pays zero added latency. Once all ``max_inflight`` batch
    RPCs are busy, arrivals accumulate and flush as ONE
    ``client.bind_many`` (size-capped at ``max_batch``) when a slot
    frees; a short timer backstops the flush. At saturation each wire
    round trip therefore carries a full batch — the per-request HTTP
    framing/auth/audit cost that made the REST arm ~2.7x slower than
    local is paid once per ~``max_batch`` pods.

    ``max_inflight * max_batch`` should be >= the scheduler's bind
    semaphore so coalescing never reduces peak concurrency.
    ``max_inflight`` is deliberately small: with many slots every
    arrival finds a free one and dispatches alone (measured — 4 slots
    produced almost-all-singleton batches at density scale, because
    placement emits pods slower than a single bind RPC turns around);
    two slots keep the pipe full while completions sweep the queue
    into real batches.
    """

    def __init__(self, client: Client, max_batch: int = 32,
                 max_inflight: int = 2, window: float = 0.005):
        self.client = client
        self.max_batch = max_batch
        self.max_inflight = max_inflight
        self.window = window
        self._pending: list[tuple] = []  # (ns, name, binding, future)
        self._inflight = 0
        self._timer = None
        self._tasks: set[asyncio.Task] = set()

    async def bind(self, namespace: str, name: str, binding) -> None:
        """Returns when this pod's bind landed; raises its per-item
        error (or the whole batch's transport error) on failure."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending.append((namespace, name, binding, fut))
        self._maybe_flush(loop)
        await fut

    def _maybe_flush(self, loop) -> None:
        # One dispatch per loop turn, namespace-grouped BEFORE slicing:
        # the slot check guards exactly one task, so ``max_inflight``
        # holds even when pending binds span namespaces (a batch
        # request carries one namespace).
        while self._pending and self._inflight < self.max_inflight:
            ns = self._pending[0][0]
            items, rest = [], []
            for item in self._pending:
                if item[0] == ns and len(items) < self.max_batch:
                    items.append(item)
                else:
                    rest.append(item)
            self._pending = rest
            self._inflight += 1
            task = loop.create_task(self._run(ns, items, loop))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
        if self._pending and self._timer is None:
            self._timer = loop.call_later(self.window, self._on_timer, loop)

    def _on_timer(self, loop) -> None:
        self._timer = None
        self._maybe_flush(loop)

    async def _run(self, ns: str, items: list, loop) -> None:
        try:
            # BindingLatency clocks the ACTUAL batch RPC (reference
            # BindingLatency = the POST) — never the coalescer queue
            # wait, which belongs to E2E_SCHEDULING_LATENCY. One
            # observation per wire call, so bind_call percentiles
            # describe requests, not a mislabeled queue readout.
            rpc_start = time.perf_counter()
            results = await self.client.bind_many(
                ns, [(name, binding) for _ns, name, binding, _f in items])
            m.BINDING_LATENCY.observe(time.perf_counter() - rpc_start)
        except asyncio.CancelledError:
            for *_rest, fut in items:
                if not fut.done():
                    fut.cancel()
            raise
        except Exception as e:  # noqa: BLE001 — delivered per future
            results = [e] * len(items)
        finally:
            self._inflight -= 1
            self._maybe_flush(loop)
        for (_ns, _name, _b, fut), err in zip(items, results):
            if fut.done():
                continue  # caller gone (scheduler stopping)
            if err is None:
                fut.set_result(None)
            else:
                fut.set_exception(err)

    def close(self) -> set:
        """Cancel timers/tasks and fail pending binds; returns the
        still-live tasks for the caller to await."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        for _ns, _name, _b, fut in self._pending:
            if not fut.done():
                fut.cancel()
        self._pending.clear()
        for task in list(self._tasks):
            task.cancel()
        return self._tasks


class Scheduler:
    def __init__(self, client: Client, name: str = "default-scheduler",
                 backoff_seconds: float = 1.0, policy=None,
                 informer_factory=None, metrics_port: Optional[int] = None):
        self.client = client
        #: Optional shared InformerFactory (reference: the scheduler
        #: rides the controller-manager's SharedInformerFactory). When
        #: given, pods/nodes/podgroups informers come from it — one
        #: decode per watch event instead of one per component — and
        #: their lifecycle belongs to the factory owner, not stop().
        self._factory = informer_factory
        self._owns_informers = informer_factory is None
        self.name = name
        #: Policy file selection of predicates/priorities/extenders
        #: (policy.py; reference factory.go CreateFromConfig). Fixed for
        #: the scheduler's lifetime — the equivalence cache's verdicts
        #: assume the predicate set never changes mid-run.
        self.policy = policy
        self._enabled_predicates = (policy.enabled_predicates
                                    if policy is not None else None)
        self._priority_weights = (policy.priority_weights
                                  if policy is not None else None)
        self.cache = SchedulerCache()
        self.queue = SchedulingQueue()
        self.recorder = EventRecorder(client, component=name)
        self.backoff_seconds = backoff_seconds
        self._informers: list[SharedInformer] = []
        self._pod_informer: Optional[SharedInformer] = None
        self._group_informer: Optional[SharedInformer] = None
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        #: Out-of-process filter/prioritize webhooks (extender.py;
        #: reference core/extender.go). Consulted after built-in
        #: predicates/priorities for pods they manage.
        self.extenders: list = list(policy.extenders) if policy else []
        self._bind_sem = asyncio.Semaphore(64)
        #: Wire-path bind batcher (zero added latency below saturation;
        #: see _BindCoalescer). max_inflight*max_batch == the semaphore.
        self._bind_coalescer = _BindCoalescer(client)
        #: gang key -> perf_counter at preemption decision; observed
        #: into PREEMPTION_LATENCY when the gang's plan finally binds.
        self._preempt_started: dict[str, float] = {}
        self._bind_tasks: set[asyncio.Task] = set()
        #: Max in-flight+queued async binds before placement pauses.
        self.max_bind_backlog = 256
        #: Placements slower than this log an op trace (utiltrace
        #: LogIfLong threshold; the reference uses 100ms).
        self.trace_threshold = 0.1
        self._ring_offset = 0
        #: Open "queue" spans per pod key (ktrace): started when a
        #: sampled pod enters the scheduling queue, ended at pop.
        #: Bounded by pending sampled pods; swept on pod delete.
        self._queue_spans: dict[str, object] = {}
        #: Loop-lag probe task (scheduler_loop_lag_ms family).
        self._probe_task: Optional[asyncio.Task] = None
        #: /metrics listener port (kube-scheduler --secure-port analog;
        #: metrics/http.py). None = no listener, byte-identical to the
        #: pre-kmon scheduler; 0 = pick a free port. The composer turns
        #: this on when the ClusterMetricsPipeline gate is set so the
        #: scrape manager can reach scheduler_* series over HTTP.
        self.metrics_port = metrics_port
        self.metrics_listener = None
        #: Columnar fleet snapshot (fleetarray.FleetSnapshot) when the
        #: SchedulerFastPath gate is on at start(); None = the scalar
        #: per-node loop, byte-identical to the ungated scheduler.
        self._fleet = None
        #: Max queue items drained per loop iteration when batching
        #: (gate on). One condvar acquisition + one snapshot refresh
        #: amortize over the whole drained batch. KTPU_SCHED_BATCH
        #: overrides (bench knob; 1 = per-pod drain, batching off).
        import os
        try:
            self.batch_size = max(
                1, int(os.environ.get("KTPU_SCHED_BATCH", "") or 64))
        except ValueError:
            self.batch_size = 64

    # -- wiring (reference: factory.go:137 NewConfigFactory) --------------

    async def start(self) -> None:
        # Control-plane GC policy (util/gctune.py): automatic gen2
        # passes over millions of live API objects were the bind-p99
        # tail at density scale.
        from ..util.gctune import tune_control_plane_gc
        tune_control_plane_gc()
        # Arm the loop-occupancy sanitizer (TPU_LOOPSAN=1; inert
        # otherwise) — idempotent when the apiserver armed it first.
        from ..analysis import loopsan
        loopsan.maybe_arm()
        from ..util.features import GATES
        if GATES.enabled("SchedulerFastPath"):
            # Wired before the informers so every cache mutation from
            # sync/replay onward marks the snapshot dirty; the first
            # placement's refresh() builds the columns.
            from .fleetarray import FleetSnapshot
            self._fleet = FleetSnapshot(self.cache)
            self.cache.snapshot = self._fleet
        if self._factory is not None:
            pods = self._factory.informer("pods")
            nodes = self._factory.informer("nodes")
            groups = self._factory.informer("podgroups")
        else:
            pods = SharedInformer(self.client, "pods")
            nodes = SharedInformer(self.client, "nodes")
            groups = SharedInformer(self.client, "podgroups")
        # A shared informer that synced BEFORE our handlers were added
        # never replays its store to them — without this, a scheduler
        # riding an already-running factory starts with an empty cache
        # and nothing ever schedules.
        replay_nodes = nodes.has_synced
        replay_pods = pods.has_synced
        replay_groups = groups.has_synced
        pods.add_handlers(on_add=self._pod_added, on_update=self._pod_updated,
                          on_delete=self._pod_deleted)
        # Gang membership lookups are by_index, not full-store scans —
        # O(members) per gang at 30k-pod density.
        pods.store.add_indexer(
            "gang", lambda p: ([f"{p.metadata.namespace}/{p.spec.gang}"]
                               if p.spec.gang else []))
        self._pod_informer = pods
        nodes.add_handlers(on_add=lambda n: self.cache.set_node(n),
                           on_update=lambda o, n: self.cache.set_node(n),
                           on_delete=lambda n: self.cache.remove_node(n.metadata.name))
        groups.add_handlers(on_add=self._group_changed_add,
                            on_update=self._group_changed,
                            on_delete=self._group_deleted)
        self._group_informer = groups
        self._informers = [pods, nodes, groups]
        for inf in self._informers:
            if inf._task is None:
                inf.start()
        for inf in self._informers:
            await inf.wait_for_sync()
        if replay_nodes:
            for n in nodes.list():
                self.cache.set_node(n)
        if replay_pods:
            for p in pods.list():
                self._pod_added(p)
        if replay_groups:
            for g in groups.list():
                self._group_changed_add(g)
        self._probe_task = spawn(loop_lag_probe(m.LOOP_LAG, m.LOOP_BUSY),
                                 name="scheduler-loop-probe")
        if self.metrics_port is not None:
            from ..metrics.http import MetricsListener
            self.metrics_listener = MetricsListener(port=self.metrics_port)
            await self.metrics_listener.start()
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        await self.queue.close()
        if self._probe_task is not None:
            self._probe_task.cancel()
            self._probe_task = None
        if self.metrics_listener is not None:
            await self.metrics_listener.stop()
            self.metrics_listener = None
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        # In-flight binds hold the REST client; finish or cancel them
        # before the caller tears the client/apiserver down.
        for task in list(self._bind_tasks):
            task.cancel()
        if self._bind_tasks:
            await asyncio.gather(*self._bind_tasks, return_exceptions=True)
        coalescer_tasks = self._bind_coalescer.close()
        if coalescer_tasks:
            await asyncio.gather(*coalescer_tasks, return_exceptions=True)
        for ext in self.extenders:
            try:
                await ext.close()
            except Exception as e:  # noqa: BLE001
                log.warning("extender %s: close failed: %s",
                            getattr(ext, "name", ext), e)
        if self._owns_informers:
            for inf in self._informers:
                await inf.stop()

    # -- ktrace lifecycle spans -------------------------------------------

    def _open_queue_span(self, pod: t.Pod) -> None:
        """Start the pod's "queue" stage span (sampled pods only; one
        armed() check is the entire disarmed cost)."""
        if not tracing.armed():
            return
        key = pod.key()
        if key in self._queue_spans:
            return
        ctx = tracing.context_of(pod)
        if ctx is None:
            return
        attrs = {"pod": key}
        if pod.spec.gang:
            attrs["gang"] = f"{pod.metadata.namespace}/{pod.spec.gang}"
        self._queue_spans[key] = tracing.start_span(
            "queue", component="scheduler", parent=ctx, attrs=attrs)

    def _close_queue_span(self, key: str, **attrs) -> None:
        span = self._queue_spans.pop(key, None)
        if span is not None:
            span.end(**attrs)

    def _gang_stage_spans(self, pods: list, name: str,
                          prev: Optional[list]) -> Optional[list]:
        """Advance every sampled gang member to lifecycle stage
        ``name``: end the previous stage's spans (queue spans on the
        first call, ``prev`` afterwards) and open the next. Returns
        the open spans (None when nothing is sampled)."""
        if prev:
            for sp in prev:
                sp.end()
        if not tracing.armed():
            return None
        spans = []
        for p in pods:
            ctx = tracing.context_of(p)
            if ctx is None:
                continue
            self._close_queue_span(p.key())
            spans.append(tracing.start_span(
                name, component="scheduler", parent=ctx,
                attrs={"pod": p.key(),
                       "gang": f"{p.metadata.namespace}/{p.spec.gang}"}))
        return spans or None

    # -- informer handlers ------------------------------------------------

    def _relevant(self, pod: t.Pod) -> bool:
        return (pod.spec.scheduler_name in ("", self.name)
                and t.is_pod_active(pod))

    def _pod_added(self, pod: t.Pod) -> None:
        if not pod.spec.node_name and self._relevant(pod):
            self._open_queue_span(pod)
            if self._fleet is not None:
                # Fast-path ingest: direct heap push + one coalesced
                # wake per burst instead of a spawned task per event.
                self.queue.add_pod_sync(pod)
            else:
                spawn(self.queue.add_pod(pod), name="queue-add-pod")
        elif pod.spec.node_name:
            self.cache.add_pod(pod)
            if pod.spec.gang and t.is_pod_active(pod):
                # Active only: a relisted terminating member is a
                # ghost — it must not count toward quorum or the
                # elastic cap.
                self.queue.gang_pod_confirmed(pod)

    def _pod_updated(self, old: t.Pod, pod: t.Pod) -> None:
        if pod.spec.node_name:
            # Bound (possibly by another scheduler / a recovery path
            # that never popped it here): a still-open queue span must
            # not dangle until pod deletion.
            self._close_queue_span(pod.key())
            self.cache.update_pod(pod)
            if pod.spec.gang and t.is_pod_active(pod):
                self.queue.gang_pod_confirmed(pod)
            if not t.is_pod_active(pod):
                # Terminal pods free their chips for future placements
                # — and stop counting toward gang quorum / the elastic
                # cap (a ghost bound count would park replacements).
                self.cache.remove_pod(pod)
                if pod.spec.gang:
                    self.queue.gang_pod_lost(pod)
        elif self._relevant(pod):
            self._open_queue_span(pod)
            if self._fleet is not None:
                self.queue.add_pod_sync(pod)
            else:
                spawn(self.queue.add_pod(pod), name="queue-add-pod")

    def _pod_deleted(self, pod: t.Pod) -> None:
        self.cache.remove_pod(pod)
        self._close_queue_span(pod.key(), cancelled="pod deleted")
        spawn(self.queue.remove_pod(pod), name="queue-remove-pod")

    def _group_changed_add(self, group: t.PodGroup) -> None:
        self._group_changed(None, group)

    def _group_changed(self, old, group: t.PodGroup) -> None:
        # Admission gate first: an unadmitted queued gang must never be
        # releasable, and flipping admitted -> suspended (quota reclaim)
        # must cancel an already-released unit before set_gang_min could
        # re-release it.
        self.queue.set_gang_suspended(group.key(), group_suspended(group))
        self.queue.set_gang_min(group.key(), group.spec.min_member)

    def _group_deleted(self, group: t.PodGroup) -> None:
        self.queue.set_gang_suspended(group.key(), False)
        self.cache.release_reservation(group.key())
        # A gang deleted mid-preemption must not leave a stale clock
        # that a future same-named gang would observe as an hours-long
        # preemption latency.
        self._preempt_started.pop(group.key(), None)

    # -- main loop --------------------------------------------------------

    async def _run(self) -> None:
        batching = self._fleet is not None
        while not self._stopped:
            if batching:
                # Batch drain (SchedulerFastPath): one condvar round
                # trip and one mutation-detector sweep per batch; the
                # item sequence is identical to consecutive pop()s.
                items = await self.queue.pop_batch(self.batch_size)
            else:
                item = await self.queue.pop()
                items = None if item is None else [item]
            if items is None:
                return
            m.PENDING_PODS.set(float(len(self.queue)))
            if self.cache.mutation_detector.enabled:
                self.cache.verify_cached()
            if batching:
                m.BATCH_SIZE.observe(float(len(items)))
            for item in items:
                try:
                    if isinstance(item, GangUnit):
                        await self._schedule_gang(item)
                    else:
                        await self._schedule_one(item)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001
                    log.exception("scheduleOne panic")
                    if isinstance(item, GangUnit):
                        # A popped gang unit is the ONLY copy of the
                        # release decision — single pods re-enter via
                        # informer resyncs, but a dropped gang unit never
                        # re-releases (all members stay staged, min is
                        # already known, no further transition fires).
                        # Found by tpusan: a mid-failover GET panic here
                        # wedged the gang for good.
                        await self.queue.requeue(item, self.backoff_seconds)

    async def _schedule_one(self, pod: t.Pod) -> None:
        start = time.perf_counter()
        # The informer feeds the queue, so the queued copy is the cache's
        # view (reference: scheduleOne takes the pod from NextPod without
        # a live GET). Already-bound/terminal pods are skipped here; a
        # pod deleted-while-queued fails its bind and is dropped then.
        key = pod.key()
        if (pod.spec.node_name or not t.is_pod_active(pod)
                or self.cache.knows_pod(key)):
            self._close_queue_span(key, skipped="already bound/terminal")
            return

        # ktrace: queue stage ends at pop, schedule stage runs through
        # placement + assume (NOOP spans unless this pod is sampled).
        self._close_queue_span(key)
        ctx = tracing.context_of(pod) if tracing.armed() else None
        sched_span = tracing.start_span("schedule", component="scheduler",
                                        parent=ctx, attrs={"pod": key})
        # Op trace (reference: generic_scheduler.go:110-141 utiltrace) —
        # logged only when this placement ran long.
        trace = Trace("schedule-one", pod=key)
        if self.extenders and any(e.interested(pod) for e in self.extenders):
            node_name, bindings, reasons = \
                await self._find_placement_extended(pod)
        else:
            node_name, bindings, reasons = self._find_placement(pod)
        trace.step("placement computed")
        m.ALGORITHM_LATENCY.observe(time.perf_counter() - start)
        if node_name is None:
            sched_span.end(result="unschedulable")
            await self._handle_unschedulable(pod, reasons)
            trace.step("handled unschedulable")
            trace.log_if_long(self.trace_threshold)
            return

        assumed = self._assume_copy(pod)
        for claim in assumed.spec.tpu_resources:
            for b in bindings:
                if b.name == claim.name:
                    claim.assigned = list(b.chip_ids)
        self.cache.assume_pod(assumed, node_name)
        trace.step("assumed in cache")
        trace.log_if_long(self.trace_threshold)
        sched_span.end(node=node_name)

        # Bind asynchronously (reference: scheduler.go:484-495 binds in a
        # goroutine) so the next pod's placement overlaps this pod's RPC;
        # the semaphore bounds in-flight binds.
        async def bind_task():
            bind_span = tracing.start_span(
                "bind", component="scheduler", parent=ctx,
                attrs={"pod": key, "node": node_name})
            try:
                async with self._bind_sem:
                    # The coalescer folds concurrent binds into one
                    # bindings:batch request at saturation without
                    # delaying an isolated bind. BINDING_LATENCY is
                    # observed inside the coalescer around the actual
                    # RPC — the await here additionally covers batch
                    # queue wait, which belongs to the e2e metric only.
                    await self._bind_coalescer.bind(
                        pod.metadata.namespace, pod.metadata.name,
                        t.Binding(target=t.BindingTarget(
                            node_name=node_name, tpu_bindings=bindings)))
            except Exception as e:  # noqa: BLE001
                bind_span.end(error=str(e))
                self.cache.forget_pod(assumed)
                if isinstance(e, errors.NotFoundError):
                    return  # pod deleted while queued
                log.warning("bind %s -> %s failed: %s", pod.key(), node_name, e)
                self.recorder.event(pod, "Warning", "FailedBinding", str(e))
                await self.queue.requeue(pod, self.backoff_seconds)
                m.PODS_SCHEDULED.inc(result="bind_error")
                return
            bind_span.end()
            m.E2E_SCHEDULING_LATENCY.observe(time.perf_counter() - start)
            m.PODS_SCHEDULED.inc(result="ok")
            self.recorder.event(pod, "Normal", "Scheduled",
                                f"assigned to {node_name}")

        # Backpressure: placement may run ahead of binds (pipelining),
        # but not unboundedly — at density scale an uncapped backlog
        # grows O(pods) tasks and turns the e2e latency metric into a
        # pure backlog readout.
        if len(self._bind_tasks) >= self.max_bind_backlog:
            await asyncio.wait(self._bind_tasks,
                               return_when=asyncio.FIRST_COMPLETED)
        task = asyncio.get_running_loop().create_task(bind_task())
        self._bind_tasks.add(task)
        task.add_done_callback(self._bind_tasks.discard)

    def _assume_copy(self, pod: t.Pod) -> t.Pod:
        """The copy assume_pod debits. Fast path: a structural copy
        that clones exactly what assume mutates (the Pod shell, the
        spec, each TPU claim + its ``assigned`` list) and shares the
        rest — the full codec deepcopy was ~30µs/pod of pure
        allocation churn at 30k scale, for fields nobody writes (the
        cache discipline is verified by the armed mutation detector).
        Gate off: the codec deepcopy, byte-identical behavior."""
        if self._fleet is None:
            # Gate-off fallback only: SchedulerFastPath arms the
            # structural copy below; this branch keeps the legacy
            # arm byte-identical.
            return deepcopy(pod)  # tpuvet: ignore[hot-path-cost]
        from dataclasses import replace
        spec = replace(pod.spec, tpu_resources=[
            replace(c, assigned=list(c.assigned))
            for c in pod.spec.tpu_resources])
        return replace(pod, spec=spec)

    def _find_placement(self, pod: t.Pod, return_candidates: bool = False,
                        use_fleet: bool = True):
        """findNodesThatFit + PrioritizeNodes + selectHost.

        ``return_candidates=True`` stops before selectHost and returns
        (scores, bindings_by_node, reasons) — the extender phase picks
        the host after its filter/prioritize round trips.

        SchedulerFastPath (``use_fleet``, gate on): vector-eligible
        pods place entirely through the columnar snapshot
        (:meth:`_place_vector` — identical node choice by
        construction); TPU pods get the columnar predicate mask and
        pay only chip geometry per masked-in node. Anything the
        columns cannot represent exactly — and every unschedulable
        outcome, which needs the full per-node reason strings — takes
        this scalar body unchanged.

        Chip geometry is computed ONCE per node here (select_chips) and
        reused for the fit decision, the defrag score, and the final
        binding — the reference recomputes nothing because its matcher
        is flat; ours is a box search, so reuse matters.
        """
        requests = t.pod_resource_requests(pod)  # once per pod
        fleet = self._fleet
        mask = None
        if (use_fleet and fleet is not None and not return_candidates
                and self.policy is None
                and not self.cache.has_reservations()):
            fleet.refresh()
            mask = fleet.feasibility_mask(pod, requests)
        wants_tpu = bool(pod.spec.tpu_resources)
        if mask is not None and not wants_tpu:
            placed = self._place_vector(pod, fleet, mask, requests)
            if placed is not None:
                m.BATCH_FASTPATH.inc(path="vector")
                return placed
            # No feasible node under the (exact) mask: the scalar body
            # below collects the per-node reason strings the event/
            # condition surface reports.
            mask = None
        if fleet is not None and use_fleet and not return_candidates:
            m.BATCH_FASTPATH.inc(path="masked" if mask is not None
                                 else "scalar")
        feasible = []
        reasons: list[str] = []
        chip_choices: dict[str, list] = {}
        bindings_by_node: dict[str, list] = {}
        # Node sampling (reference: percentageOfNodesToScore +
        # equivalence of findNodesThatFit's numFeasibleNodesToFind): at
        # fleet scale, stop once enough feasible nodes are collected
        # instead of scanning everything per pod. TPU pods always scan
        # fully — chip geometry makes every node's answer distinct.
        # A rotating start offset spreads load across the fleet.
        # With a fleet mask the snapshot's names ARE this insertion-
        # order list (rebuilt from the same dict at refresh).
        names = fleet.names if mask is not None else list(self.cache.nodes)
        n = len(names)                  # ring offset does the spreading
        enough = n if (wants_tpu or n <= 100) else max(100, n // 20)
        start_at = self._ring_offset % n if n else 0
        self._ring_offset += 1
        # Equivalence cache (equivalence_cache.go analog): identical
        # pods reuse per-node predicate verdicts until that node's
        # accounting changes.
        from .equivalence import equivalence_hash
        eq = equivalence_hash(pod)
        # Inter-pod affinity context (podaffinity.py): built once per
        # pod; None in affinity-free clusters. NOT part of the
        # equivalence-cached predicates — its verdict depends on other
        # pods, not node accounting.
        from .podaffinity import build_context
        # Policy can disable the required check (predicate) and the
        # soft score (priority) independently; the context is built if
        # either is active.
        from .predicates import PRED_INTERPOD_AFFINITY
        from .priorities import PRI_INTERPOD_AFFINITY
        aff_pred_on = (self.policy is None or
                       self.policy.predicate_enabled(PRED_INTERPOD_AFFINITY))
        aff_weight = (1.0 if self.policy is None
                      else self.policy.weight(PRI_INTERPOD_AFFINITY))
        affinity_ctx = (build_context(pod, self.cache)
                        if aff_pred_on or aff_weight > 0 else None)
        my_prio = t.pod_priority(pod)
        my_key = pod.key()
        any_reservations = self.cache.has_reservations()
        for idx in range(n):
            row = (start_at + idx) % n
            name = names[row]
            if mask is not None and not mask[row]:
                # Columnar verdict: infeasible (exact for the non-TPU
                # predicates; for TPU pods also the chip-count
                # prefilter select_chips would refuse anyway). Reasons
                # are not collected here — an unschedulable outcome
                # reruns the full scalar pass below.
                continue
            info = self.cache.nodes.get(name)
            if info is None or info.node is None:
                continue
            if mask is None:
                reserved = False
                if any_reservations:
                    res_req, res_chips = self.cache.node_reserved(
                        name, exclude_owner=my_key, below_priority=my_prio)
                    if res_req or res_chips:
                        # Nominated capacity held for a preemptor this
                        # pod must not steal: evaluate against a
                        # debited view, and bypass the equivalence
                        # cache (its verdicts ignore priority).
                        from .cache import ReservedNodeView
                        info = ReservedNodeView(info, res_req, res_chips)
                        reserved = True
                cached = (self.cache.equiv.lookup(name, eq)
                          if eq is not None and not reserved else None)
                if cached is not None:
                    fits, cached_reasons = cached
                else:
                    res = run_predicates(pod, info, skip_tpu=True,
                                         requests=requests,
                                         enabled=self._enabled_predicates)
                    fits, cached_reasons = res.fits, res.reasons
                    if eq is not None and not reserved:
                        self.cache.equiv.store(name, eq, fits,
                                               cached_reasons)
                if not fits:
                    reasons.append(f"{name}: {'; '.join(cached_reasons)}")
                    continue
            if affinity_ctx is not None and aff_pred_on:
                why = affinity_ctx.node_allows(info.node)
                if why is not None:
                    reasons.append(f"{name}: {why}")
                    continue
            if wants_tpu:
                bindings = select_chips(pod, info)
                if bindings is None:
                    from .predicates import pod_fits_tpus
                    why = pod_fits_tpus(pod, info) or "no feasible chip set"
                    reasons.append(f"{name}: {why}")
                    continue
                bindings_by_node[name] = bindings
                chip_choices[name] = [cid for b in bindings for cid in b.chip_ids]
            feasible.append(info)
            if len(feasible) >= enough:
                break
        if not feasible:
            if mask is not None:
                # The masked pass skipped reason collection; the
                # unschedulable surface (events, conditions, preemption
                # decisions) needs the exact per-node strings — rerun
                # the full scalar pass. Placement outcome is unchanged
                # (the mask is exact); only the cold path pays.
                return self._find_placement(pod, return_candidates,
                                            use_fleet=False)
            return None, None, reasons
        sibling_counts = self._sibling_counts(pod)
        scores = prioritize(pod, feasible, sibling_counts, chip_choices,
                            weights=self._priority_weights)
        if chip_choices and self._serving_topology_active(pod):
            self._add_serving_topology_scores(feasible, chip_choices,
                                              scores)
        if (affinity_ctx is not None and affinity_ctx.preferred
                and aff_weight > 0):
            # Normalize to the same 0..MAX_SCORE band as the other
            # priorities (interpod_affinity.go normalizes before
            # weighting) — a weight-100 soft preference must not swamp
            # LeastRequested/defrag.
            raw = {info.node.metadata.name: affinity_ctx.score(info.node)
                   for info in feasible}
            peak = max((abs(v) for v in raw.values()), default=0.0)
            if peak > 0:
                from .priorities import MAX_SCORE
                for name, v in raw.items():
                    scores[name] += aff_weight * MAX_SCORE * v / peak
        if return_candidates:
            return scores, bindings_by_node, reasons
        best = max(scores, key=lambda n: (scores[n], n))
        return best, bindings_by_node.get(best, []), []

    def _place_vector(self, pod: t.Pod, fleet, mask, requests):
        """Fully columnar placement for a vector-eligible non-TPU pod:
        ring-sampled candidates and fused priority scores as array ops
        (fleetarray.score_rows mirrors prioritize() term-for-term, so
        the chosen node is identical to the scalar path's). Returns
        None when no node is feasible — the caller reruns the scalar
        pass for the reason strings WITHOUT having consumed a ring
        offset here, so the fallback samples exactly as an unmasked
        call would have."""
        if not mask.any():
            return None
        n = len(fleet)
        enough = n if n <= 100 else max(100, n // 20)
        start_at = self._ring_offset % n
        self._ring_offset += 1
        rows = fleet.ring_candidates(mask, start_at, enough)
        limits: dict[str, float] = {}
        for c in pod.spec.containers:
            for res, amount in c.resources.limits.items():
                limits[res] = limits.get(res, 0.0) + t.parse_quantity(amount)
        from .priorities import MAX_SCORE, TPU_DEFRAG_WEIGHT
        scores = fleet.score_rows(rows, requests, limits,
                                  self._sibling_counts(pod),
                                  TPU_DEFRAG_WEIGHT * (MAX_SCORE / 2))
        best = fleet.select_best(rows, scores)
        if best is None:
            return None
        return best, [], []

    async def _find_placement_extended(self, pod: t.Pod):
        """_find_placement + the extender phase (core/extender.go):
        built-in predicates/priorities first, then each interested
        extender filters the survivors and adds weighted priorities."""
        scores, bindings_by_node, reasons = self._find_placement(
            pod, return_candidates=True)
        if not scores:
            return None, None, reasons
        names = list(scores)
        for ext in self.extenders:
            if not ext.interested(pod):
                continue
            try:
                names, failed = await ext.filter(pod, names)
                reasons.extend(f"{n}: {why} (extender)"
                               for n, why in failed.items())
            except Exception as e:  # noqa: BLE001
                if ext.ignorable:
                    log.warning("ignorable extender %s filter failed: %s",
                                ext.url_prefix, e)
                    continue
                # Non-ignorable extender down: the placement attempt
                # fails and the pod retries with backoff (reference
                # semantics; the extender owns resources we cannot
                # account for locally).
                return None, None, [f"extender {ext.url_prefix} failed: {e}"]
            if not names:
                return None, None, reasons or ["extender filtered all nodes"]
            try:
                extra = await ext.prioritize(pod, names)
            except Exception as e:  # noqa: BLE001 — scores best-effort
                log.warning("extender %s prioritize failed: %s",
                            ext.url_prefix, e)
                extra = {}
            for n, s in extra.items():
                if n not in scores:
                    continue
                # Clamp to the reference's 0..10 HostPriority band
                # (core/extender.go) so a misbehaving extender's
                # unbounded score cannot silently dominate the
                # built-in priorities' normalized range. Non-numeric
                # scores are dropped like any other prioritize error.
                try:
                    scores[n] += ext.weight * max(0.0, min(10.0, float(s)))
                except (TypeError, ValueError):
                    log.warning("extender %s returned non-numeric score "
                                "%r for %s", ext.url_prefix, s, n)
        best = max(names, key=lambda n: (scores[n], n))
        return best, bindings_by_node.get(best, []), []

    def _sibling_counts(self, pod: t.Pod) -> dict[str, int]:
        """Same-controller pods per node (SelectorSpreadPriority input).
        Reads the cache's incrementally-maintained owner index — O(nodes)
        per placement, where the naive scan was O(nodes * pods) (the
        round-1 density bottleneck)."""
        ref = next((r for r in pod.metadata.owner_references if r.controller), None)
        if ref is None:
            return {}
        return {info.node.metadata.name: info.owner_counts.get(ref.uid, 0)
                for info in self.cache.nodes.values() if info.node is not None}

    @staticmethod
    def _serving_topology_active(pod: t.Pod) -> bool:
        """Gated serving anti-fragmentation scoring applies only to
        pods carrying the serving label — one dict lookup before the
        gate check, so non-serving scheduling pays nothing either way
        (gate off = legacy placement byte-identical)."""
        from ..api.serving import SERVICE_LABEL
        if not pod.metadata.labels.get(SERVICE_LABEL):
            return False
        from ..util.features import GATES
        return GATES.enabled("ServingTopologyAware")

    def _add_serving_topology_scores(self, feasible, chip_choices,
                                     scores) -> None:
        """Add the slice-level anti-fragmentation term for a serving
        pod: prefer the node whose chip claim least shrinks its slice's
        largest free contiguous box (priorities.serving_topology_score).
        The before-volume is memoized per slice for this pass."""
        from .priorities import (SERVING_TOPOLOGY_WEIGHT,
                                 serving_topology_score)
        from .submesh import largest_free_box_volume
        before_by_slice: dict[str, int] = {}
        free_by_slice: dict[str, dict] = {}
        for info in feasible:
            node = info.node
            name = node.metadata.name
            chosen = chip_choices.get(name)
            topo = node.status.tpu
            if not chosen or topo is None or not topo.slice_id:
                continue
            sl = self.cache.slices.get(topo.slice_id)
            if sl is None or not sl.mesh_shape:
                continue
            sid = topo.slice_id
            if sid not in free_by_slice:
                if self._fleet is not None:
                    # Snapshot memo: survives across placement passes
                    # until any member node's accounting changes (the
                    # scalar memo below lives one pass only).
                    self._fleet.refresh()
                    free_by_slice[sid], before_by_slice[sid] = \
                        self._fleet.slice_free_stats(sl)
                else:
                    free_by_slice[sid] = sl.free(self.cache)
                    before_by_slice[sid] = largest_free_box_volume(
                        set(free_by_slice[sid]), sl.mesh_shape)
            slice_free = free_by_slice[sid]
            by_id = {cid: coord for coord, (n, cid) in slice_free.items()
                     if n == name}
            cells = [by_id[cid] for cid in chosen if cid in by_id]
            scores[name] += SERVING_TOPOLOGY_WEIGHT * \
                serving_topology_score(set(slice_free), sl.mesh_shape,
                                       cells, before_by_slice[sid])

    async def _handle_unschedulable(self, pod: t.Pod, reasons: list[str]) -> None:
        brief = "; ".join(reasons[:3]) or "no nodes available"
        log.info("pod %s unschedulable: %s", pod.key(), brief)
        self.recorder.event(pod, "Warning", "FailedScheduling", brief)
        cond = t.PodCondition(type=t.COND_POD_SCHEDULED, status="False",
                              reason="Unschedulable", message=brief)
        try:
            current = await self.client.get("pods", pod.metadata.namespace,
                                            pod.metadata.name)
            if t.update_pod_condition(current.status, cond):
                await self.client.update_status(current)
        except errors.StatusError:
            pass
        from ..util.features import GATES
        if t.pod_priority(pod) > 0 and GATES.enabled("PodPriority"):
            victims = await self._preempt(pod)
            if victims:
                await self.queue.requeue(pod, 0.1)
                return
        await self.queue.requeue(pod, self.backoff_seconds)
        m.PODS_SCHEDULED.inc(result="unschedulable")

    # -- preemption (reference: generic_scheduler.go:199 Preempt) ---------

    async def _preempt(self, pod: t.Pod) -> list[t.Pod]:
        """Evict lower-priority pods from the node where doing so costs
        least and makes ``pod`` feasible."""
        best_node, best_victims = None, None
        for name, info in self.cache.nodes.items():
            if info.node is None:
                continue
            victims = self._victims_on_node(pod, info)
            if victims is None:
                continue
            if best_victims is None or self._cheaper(victims, best_victims):
                best_node, best_victims = name, victims
        if best_node is None or not best_victims:
            return []
        # HOLD what the victims free for this preemptor (nominated
        # capacity): without the reservation, any pod scheduled in the
        # next iterations steals it and the preemptor livelocks
        # through repeated requeues (reference: nominated pods stay
        # visible to lower-priority scheduling).
        from .cache import Reservation
        victim_chips = {cid for v in best_victims
                        if v.spec.node_name == best_node
                        for cid in t.pod_tpu_assigned(v)}
        self.cache.reserve(Reservation(
            owner=pod.key(), priority=t.pod_priority(pod),
            node_name=best_node,
            requests=t.pod_resource_requests(pod),
            chip_ids=victim_chips))
        for v in best_victims:
            try:
                # Preemption is priority policy: it OVERRIDES the
                # budget check but still accounts the disruption in
                # the PDB (reference semantics: eviction API with the
                # scheduler's authority; disruption.go arithmetic must
                # see preempted pods as disrupted).
                await self.client.evict(
                    v.metadata.namespace, v.metadata.name,
                    t.Eviction(override_budget=True))
                m.PREEMPTION_VICTIMS.inc()
                self.recorder.event(v, "Normal", "Preempted",
                                    f"by {pod.key()} (priority {t.pod_priority(pod)})")
            except errors.StatusError:
                pass
        try:
            current = await self.client.get("pods", pod.metadata.namespace,
                                            pod.metadata.name)
            current.status.nominated_node_name = best_node
            await self.client.update_status(current)
        except errors.StatusError:
            pass
        return best_victims

    def _victims_on_node(self, pod: t.Pod, info) -> Optional[list[t.Pod]]:
        my_prio = t.pod_priority(pod)
        lower = sorted((p for p in info.pods.values()
                        if t.pod_priority(p) < my_prio and t.is_pod_active(p)),
                       key=t.pod_priority)
        if not lower:
            return None
        # Simulate removals cheapest-first until the pod fits.
        import copy
        sim = copy.copy(info)
        sim.pods = dict(info.pods)
        sim.requested = dict(info.requested)
        sim.free_chips = dict(info.free_chips)
        sim.chip_owner = dict(info.chip_owner)
        victims = []
        for v in lower:
            sim.remove_pod(v)
            victims.append(v)
            if run_predicates(pod, sim,
                              enabled=self._enabled_predicates).fits:
                return victims
        return None

    @staticmethod
    def _cheaper(a: list[t.Pod], b: list[t.Pod]) -> bool:
        ka = (max(t.pod_priority(p) for p in a), len(a))
        kb = (max(t.pod_priority(p) for p in b), len(b))
        return ka < kb

    # -- gangs ------------------------------------------------------------

    def _bound_gang_cells(self, bound_pods: list[t.Pod]) -> Optional[dict]:
        """Mesh coords held by bound gang members: coords -> (node,
        chip_id). None when any assignment cannot be resolved against
        the cache's slice geometry (node/slice gone)."""
        held: dict = {}
        by_node_chip = {}
        for sl in self.cache.slices.values():
            for coord, (node_name, chip_id) in sl.chips.items():
                by_node_chip[(node_name, chip_id)] = coord
        for pod in bound_pods:
            for claim in pod.spec.tpu_resources:
                for chip_id in claim.assigned:
                    coord = by_node_chip.get((pod.spec.node_name, chip_id))
                    if coord is None:
                        return None
                    held[coord] = (pod.spec.node_name, chip_id)
        return held

    # -- gang preemption (SURVEY hard-part 1: sub-mesh gang allocation
    # WITH preemption; reference seed generic_scheduler.go:199, lifted
    # to gang granularity) -------------------------------------------------

    def _box_candidates(self, sl, shape):
        """Every distinct axis-aligned box of ``shape`` (all
        orientations, torus wraparound — submesh.box_coords, the SAME
        geometry find_box searches) over the slice's healthy cells, as
        {coord: (node, chip_id)} dicts. Rank-generic via
        normalize_shape, deduped (a dim spanning the whole mesh yields
        identical wrapped boxes from every origin)."""
        from itertools import permutations, product
        from .submesh import box_coords, normalize_shape
        mesh = tuple(int(m) for m in sl.mesh_shape)
        rank = len(mesh)
        shape_n = normalize_shape(shape, rank)
        if len(shape_n) != rank:
            return
        seen: set = set()
        for dims in sorted(set(permutations(shape_n))):
            if any(d > m for d, m in zip(dims, mesh)):
                continue
            for origin in product(*(range(m) for m in mesh)):
                coords = box_coords(origin, dims, mesh, torus=True)
                if coords is None:
                    continue
                key = frozenset(coords)
                if key in seen:
                    continue
                seen.add(key)
                cells = {}
                for c in coords:
                    v = sl.chips.get(c)
                    if v is None:
                        cells = None
                        break
                    cells[c] = v
                if cells:
                    yield cells

    def _gang_members_of(self, ns: str, gang: str) -> list[t.Pod]:
        members = self._pod_informer.store.by_index("gang", f"{ns}/{gang}")
        return [p for p in members if t.is_pod_active(p)]

    def _box_victims(self, sl, cells: dict,
                     gang_prio: int) -> Optional[dict[str, t.Pod]]:
        """Victim set that frees this box, at GANG granularity: evicting
        one gang member triggers survivor recovery of the whole gang,
        so the whole gang IS the victim — its full cost counts
        (cheapest-victim accounting is wrong otherwise). None when any
        occupant outranks the preemptor or holds a reservation."""
        victims: dict[str, t.Pod] = {}
        for coord, (node_name, chip_id) in cells.items():
            info = self.cache.nodes.get(node_name)
            if info is None:
                return None
            owner_key = info.chip_owner.get(chip_id)
            if owner_key is None:
                continue  # free cell
            owner = info.pods.get(owner_key)
            if owner is None or t.pod_priority(owner) >= gang_prio:
                return None
            if owner_key in victims:
                continue
            if owner.spec.gang:
                for member in self._gang_members_of(
                        owner.metadata.namespace, owner.spec.gang):
                    if t.pod_priority(member) >= gang_prio:
                        return None  # a member outranks us: untouchable
                    victims[member.key()] = member
            else:
                victims[owner_key] = owner
        return victims

    def _reservation_stolen(self, res, gang_prio: int) -> bool:
        """True when any cell of this gang's own carved box is now
        held by an ACTIVE pod of priority >= the gang's — i.e. an
        occupant the gang may not preempt, so the reservation is
        permanently unsatisfiable."""
        for _coord, (node_name, chip_id) in res.cells.items():
            info = self.cache.nodes.get(node_name)
            if info is None:
                continue
            owner_key = info.chip_owner.get(chip_id)
            if owner_key is None:
                continue
            owner = info.pods.get(owner_key)
            if owner is not None and t.pod_priority(owner) >= gang_prio:
                return True
        return False

    async def _preempt_gang(self, group: t.PodGroup, pods: list[t.Pod],
                            gang_prio: int) -> bool:
        """Carve ONE contiguous box for a higher-priority gang by
        evicting whole lower-priority gangs (+ loose pods), then
        reserve the box for this group until it plans and binds."""
        shape = group.spec.slice_shape
        if not shape:
            return False
        best = None  # (cost, slice, cells, victims)
        for sl in self.cache.slices.values():
            held = self.cache.reserved_cells(
                sl.slice_id, exclude_owner=group.key(),
                below_priority=gang_prio)
            for cells in self._box_candidates(sl, shape):
                if held and any(c in held for c in cells):
                    continue
                victims = self._box_victims(sl, cells, gang_prio)
                if victims is None or not victims:
                    continue  # free boxes were the planner's job
                cost = (max(t.pod_priority(v) for v in victims.values()),
                        len(victims))
                if best is None or cost < best[0]:
                    best = (cost, sl, cells, victims)
        if best is None:
            return False
        _cost, sl, cells, victims = best
        from .cache import Reservation
        # Hold CPU/mem on the box hosts too, pro-rated by their chip
        # share — chips alone would let a CPU-only squatter bind there
        # and fail the gang's resource predicates forever.
        total_req: dict = {}
        for p in pods:
            for res, amt in t.pod_resource_requests(p).items():
                total_req[res] = total_req.get(res, 0.0) + amt
        chips_per_node: dict[str, int] = {}
        for _c, (node_name, _cid) in cells.items():
            chips_per_node[node_name] = chips_per_node.get(node_name, 0) + 1
        node_requests = {
            node_name: {res: amt * count / len(cells)
                        for res, amt in total_req.items()
                        if res != t.RESOURCE_TPU}
            for node_name, count in chips_per_node.items()}
        self.cache.reserve(Reservation(
            owner=group.key(), priority=gang_prio,
            slice_id=sl.slice_id, cells=dict(cells),
            node_requests=node_requests))
        evicted_gangs = {v.spec.gang for v in victims.values() if v.spec.gang}
        self.recorder.event(
            group, "Normal", "GangPreemption",
            f"evicting {len(victims)} pods ({len(evicted_gangs)} gangs) "
            f"to free a {'x'.join(map(str, shape))} box on {sl.slice_id}")
        # Graceful preemption (preemption.py, gated): checkpoint-opted
        # victim gangs are SIGNALED — they keep their chips for their
        # grace budget while checkpointing, then the engine's finisher
        # evicts them. The preemptor's reservation holds the box
        # meanwhile; its requeue loop binds once the chips free. Only
        # the remainder (loose pods, non-opted gangs, gate off) takes
        # the legacy hard evict below — byte-identical when gated off.
        from .. import preemption as gp
        to_evict = list(victims.values())
        if gp.enabled():
            to_evict = await gp.preempt_victims(
                self.client, victims.values(), reason="gang-preemption",
                recorder=self.recorder)
        for v in to_evict:
            try:
                await self.client.evict(
                    v.metadata.namespace, v.metadata.name,
                    t.Eviction(override_budget=True))
                m.PREEMPTION_VICTIMS.inc()
                self.recorder.event(
                    v, "Normal", "Preempted",
                    f"by gang {group.key()} (priority {gang_prio})")
            except errors.StatusError:
                pass
        return True

    async def _evict_gang_survivors(self, group, bound_pods: list[t.Pod],
                                    why: str) -> None:
        """Delete bound members of a partially-bound gang so their
        controller recreates them and the gang re-plans whole."""
        # Checkpoint-opted gangs get the graceful round first — the
        # survivors save state before the recovery kill, so the
        # recreated gang resumes instead of restarting (gate off =
        # the legacy loop below, byte-identical).
        from .. import preemption as gp
        if gp.enabled() and await gp.signal_gang(
                self.client, group, bound_pods,
                reason="gang-recovery", recorder=self.recorder):
            return
        for pod in bound_pods:
            self.recorder.event(
                group, "Warning", "GangRecoveryEvict",
                f"evicting bound member {pod.key()}: {why}")
            try:
                # The gang is already broken (this IS the recovery), so
                # its own PDB would always refuse — override, but keep
                # the disruption accounted.
                await self.client.evict(
                    pod.metadata.namespace, pod.metadata.name,
                    t.Eviction(override_budget=True))
            except errors.StatusError:
                pass

    async def _schedule_gang(self, unit: GangUnit) -> None:
        # ktrace wrapper: members advance queue -> schedule here, and
        # schedule -> bind inside (at the batched bind). The finally
        # ends whatever stage is open on EVERY exit path (requeue,
        # suspension, unschedulable, success) — a dropped span would
        # leak and never reach the collector. _run awaits one item at
        # a time, so the holder never sees two gangs.
        holder = [self._gang_stage_spans(unit.pods, "schedule", None)]
        try:
            await self._schedule_gang_inner(unit, holder)
        finally:
            for sp in (holder[0] or ()):
                sp.end()

    async def _schedule_gang_inner(self, unit: GangUnit,
                                   _stage: list) -> None:
        start = time.perf_counter()
        ns, name = unit.group_key.split("/", 1)
        try:
            group = await self.client.get("podgroups", ns, name)
        except errors.NotFoundError:
            if self._group_informer is not None \
                    and self._group_informer.store.get(
                        unit.group_key) is not None:
                # The live GET answered 404 but OUR informer still
                # holds the group: a bounded-staleness follower read
                # legitimately misses a JUST-CREATED object — that is
                # not a deletion, and dropping the popped unit on it
                # wedges the gang forever (nothing re-releases: every
                # member is staged and min is known). Requeue; the
                # follower catches up within the staleness bound.
                # Found by tpusan exploring the read-affinity path.
                await self.queue.requeue(unit, self.backoff_seconds)
                return
            self._preempt_started.pop(unit.group_key, None)
            return
        except errors.StatusError:
            # Transport failure (control-plane failover window, retries
            # exhausted): the unit is already POPPED — dropping it here
            # would wedge the gang forever, because release fires only
            # on informer transitions and every member is already
            # staged. Requeue and retry after backoff.
            await self.queue.requeue(unit, self.backoff_seconds)
            return
        if group_suspended(group):
            # Raced a quota reclaim (suspension landed after this unit
            # was popped): park the members; the admission-release wake
            # path re-releases the gang when it is admitted again.
            self.queue.set_gang_suspended(unit.group_key, True)
            return
        # The gang planner does not consult extenders; silently
        # bypassing a NON-ignorable one would double-book whatever
        # external resource it guards. Refuse loudly instead (the gang
        # retries if the config changes); ignorable extenders are
        # advisory and skippable by contract.
        blocking = [e for e in self.extenders if not e.ignorable
                    and any(e.interested(p) for p in unit.pods)]
        if blocking:
            for pod in unit.pods:
                await self._handle_unschedulable(pod, [
                    f"gang scheduling does not support non-ignorable "
                    f"extender {blocking[0].url_prefix}"])
            return
        # Refresh FULL membership from the INFORMER (by_index — the
        # live LIST this replaces decoded every pod in the namespace
        # per gang, the dominant cost at fleet scale). The informer can
        # lag the API, so the scheduler CACHE — updated synchronously
        # at assume/bind — is consulted first: a member the cache knows
        # is bound (with the cache's chip assignment) even if its
        # MODIFIED event hasn't arrived; re-planning it would
        # double-book chips.
        pods = []
        bound_pods = []
        members = self._pod_informer.store.by_index("gang", unit.group_key)
        for cur in members:
            if cur.spec.gang != name or not t.is_pod_active(cur):
                # Terminated members keep node_name + assigned chips in
                # their corpse; they must not anchor recovery geometry.
                continue
            cached = self.cache.bound_copy(cur.key())
            if cached is not None:
                bound_pods.append(cached)
            elif cur.spec.node_name:
                bound_pods.append(cur)
            else:
                pods.append(cur)
        bound = max(len(bound_pods), self.queue.gang_bound_count(unit.group_key))
        if not pods or len(pods) + bound < group.spec.min_member:
            return  # below quorum; queue re-releases when members return

        # Elastic cap (GracefulPreemption): a shrunken gang must not
        # bind past status.replicas — its quota charge follows that
        # target, and binding beyond it would physically over-commit
        # the cohort. Surplus members park in the queue (the existing
        # straggler path) and bind when the regrow pass raises the
        # target. Gate off / non-elastic gangs: target 0, no cap.
        from .. import preemption as gp
        target = gp.elastic_target(group)
        if target:
            take = max(target - bound, 0)
            if take < len(pods):
                pods.sort(key=lambda p: p.metadata.name)
                parked = len(pods) - take
                pods = pods[:take]
                self.recorder.event(
                    group, "Normal", "ElasticParked",
                    f"{parked} members beyond elastic target {target} "
                    f"wait for regrow")
                if not pods:
                    await self.queue.requeue(
                        GangUnit(unit.group_key, []), self.backoff_seconds)
                    m.PODS_SCHEDULED.inc(result="gang_elastic_parked",
                                         amount=parked)
                    return

        # Plan. A partially-bound gang (recovering from a partial bind
        # failure) must STILL land as one contiguous box: the remainder
        # is planned inside a full-shape box anchored on the chips the
        # bound members hold. If no such box exists, the bound members
        # are evicted so the whole gang re-plans from scratch — the
        # contiguity guarantee is never silently dropped.
        must_include = None
        if bound_pods and group.spec.slice_shape:
            must_include = self._bound_gang_cells(bound_pods)
            if must_include is None:
                await self._evict_gang_survivors(group, bound_pods,
                                                "bound chips unresolvable")
                await self.queue.requeue(GangUnit(unit.group_key, pods),
                                        self.backoff_seconds)
                return
        # Migration steering (GangLiveMigration): a fully-evicted gang
        # whose migration round reserved a target box re-plans INTO
        # that box — an unrestricted plan would happily land back on
        # the cells it just vacated (still free, and best-fit-first),
        # turning the move into a no-op. If the reserved box has gone
        # bad (node lost, chips taken) the restricted plan fails and
        # we fall back to the normal search so a dead target can never
        # wedge the gang; the migration controller observes the
        # off-target landing and aborts the round.
        restrict_to = None
        if must_include is None:
            from ..util.features import GATES
            if GATES.enabled("GangLiveMigration"):
                res = self.cache.reservations.get(unit.group_key)
                if res is not None and res.cells:
                    restrict_to = dict(res.cells)
        plan = plan_gang(group, pods, self.cache, must_include=must_include,
                         restrict_to=restrict_to,
                         enabled=self._enabled_predicates)
        if restrict_to is not None and isinstance(plan, GangFailure):
            plan = plan_gang(group, pods, self.cache,
                             enabled=self._enabled_predicates)
        m.ALGORITHM_LATENCY.observe(time.perf_counter() - start)
        if isinstance(plan, GangFailure):
            brief = "; ".join(plan.reasons[:3])
            if must_include is not None and bound >= group.spec.min_member:
                # The gang is AT QUORUM: the unplaceable remainder is a
                # straggler (controller over-create race, elastic
                # grow-beyond-min), not a broken gang. Evicting healthy
                # bound members for it would sacrifice a working gang,
                # and demoting the group's phase would report a SERVING
                # gang as Pending — requeue the remainder quietly and
                # let capacity (or the controller's duplicate cleanup)
                # catch up.
                self.recorder.event(group, "Normal", "GangStraggler",
                                    f"{len(pods)} members beyond quorum "
                                    f"unplaceable: {brief}")
                await self.queue.requeue(GangUnit(unit.group_key, pods),
                                         self.backoff_seconds)
                m.PODS_SCHEDULED.inc(result="gang_straggler",
                                     amount=len(pods))
                return
            self.recorder.event(group, "Warning", "GangUnschedulable", brief)
            await self._set_group_phase(group, t.PODGROUP_PENDING, brief)
            if must_include is not None:
                # Recovery could not keep the below-quorum gang
                # contiguous around the survivors: evict them so the
                # full shape re-plans.
                await self._evict_gang_survivors(group, bound_pods, brief)
            else:
                # Atomic gang-over-gang preemption: a high-priority
                # gang arriving into a full fleet carves a contiguous
                # box out of lower-priority gangs and holds it
                # (reservation) until its own plan lands.
                from ..util.features import GATES
                gang_prio = max((t.pod_priority(p) for p in pods),
                                default=0)
                res = self.cache.reservations.get(group.key())
                if res is not None and self._reservation_stolen(res,
                                                                gang_prio):
                    # A strictly-higher-priority preemptor legally
                    # took cells of the box this gang carved (its plan
                    # ignores lower-priority reservations). The hold
                    # can never be satisfied now, and while it lives
                    # the gate below blocks re-carving — the r6
                    # phase-3 livelock: at small fleets every carve
                    # collides and the losers sat stale until the
                    # 120s reservation TTL. Release and re-carve now.
                    self.cache.release_reservation(group.key())
                    self.recorder.event(
                        group, "Normal", "PreemptionRestarted",
                        "carved box was taken by a higher-priority "
                        "gang; re-carving")
                if (gang_prio > 0 and GATES.enabled("PodPriority")
                        and group.key() not in self.cache.reservations
                        and await self._preempt_gang(group, pods,
                                                     gang_prio)):
                    # Clock the whole carve: decision -> victims gone
                    # -> re-plan -> all members bound (observed when
                    # the plan lands below).
                    self._preempt_started.setdefault(
                        group.key(), time.perf_counter())
                    # Victims are terminating; retry soon, not at full
                    # backoff.
                    await self.queue.requeue(GangUnit(unit.group_key, pods),
                                             0.1)
                    m.PODS_SCHEDULED.inc(result="gang_preempting",
                                         amount=len(pods))
                    return
            # Members stay staged in the queue; the requeue re-releases the
            # gang with current membership after backoff.
            await self.queue.requeue(GangUnit(unit.group_key, pods),
                                     self.backoff_seconds)
            m.PODS_SCHEDULED.inc(result="gang_unschedulable", amount=len(pods))
            return

        # The plan landed: any preemption box held for this gang has
        # served its purpose (assume debits the real chips now).
        self.cache.release_reservation(unit.group_key)

        # assume all — via the structural fast copy (_assume_copy
        # clones exactly the shell/spec/claims the loop below mutates;
        # the full deepcopy was per-member allocation churn at gang
        # scale, the same cost _schedule_one already shed)
        assumed_pods = []
        for pod, node_name, bindings in plan.placements:
            assumed = self._assume_copy(pod)
            for claim in assumed.spec.tpu_resources:
                for b in bindings:
                    if b.name == claim.name:
                        claim.assigned = list(b.chip_ids)
            self.cache.assume_pod(assumed, node_name)
            assumed_pods.append(assumed)

        # bind all as ONE batched round trip (bindings:batch on the
        # wire path; per-item outcomes keep the all-or-nothing
        # accounting below). The old per-pod fan-out cost a 16-pod gang
        # 16 HTTP requests — the dominant wire-path gang cost.
        _stage[0] = self._gang_stage_spans(
            [p for p, _n, _b in plan.placements], "bind", _stage[0])
        bind_start = time.perf_counter()
        try:
            results = await self.client.bind_many(
                ns, [(p.metadata.name,
                      t.Binding(target=t.BindingTarget(
                          node_name=n, tpu_bindings=b)))
                     for p, n, b in plan.placements])
        except Exception as e:  # noqa: BLE001 — transport: all failed
            results = [e] * len(plan.placements)
        failures = [r for r in results if isinstance(r, Exception)]
        if failures:
            # Forget ONLY the members whose bind failed — successful binds
            # are durable state; their assumed entries are confirmed by the
            # watch. The gang requeues for the failed remainder (quorum
            # counts the bound members).
            for assumed, result in zip(assumed_pods, results):
                if isinstance(result, Exception):
                    self.cache.forget_pod(assumed)
                else:
                    self.queue.gang_pod_confirmed(assumed)
            self.recorder.event(group, "Warning", "GangBindFailed",
                                f"{len(failures)} binds failed: {failures[0]}")
            await self.queue.requeue(GangUnit(unit.group_key, pods),
                                     self.backoff_seconds)
            m.PODS_SCHEDULED.inc(result="gang_bind_error")
            return
        m.BINDING_LATENCY.observe(time.perf_counter() - bind_start)
        m.GANG_SCHEDULING_LATENCY.observe(time.perf_counter() - start)
        preempt_t0 = self._preempt_started.pop(unit.group_key, None)
        if preempt_t0 is not None:
            m.PREEMPTION_LATENCY.observe(time.perf_counter() - preempt_t0)
        m.PODS_SCHEDULED.inc(amount=len(plan.placements), result="ok")
        await self._set_group_phase(group, t.PODGROUP_SCHEDULED,
                                    f"on slice {plan.slice_id}",
                                    slice_id=plan.slice_id,
                                    scheduled=len(plan.placements))
        self.recorder.event(group, "Normal", "GangScheduled",
                            f"{len(plan.placements)} pods on slice {plan.slice_id}")

    async def _set_group_phase(self, group: t.PodGroup, phase: str, msg: str,
                               slice_id: str = "", scheduled: int = 0) -> None:
        try:
            cur = await self.client.get("podgroups", group.metadata.namespace,
                                        group.metadata.name)
            cur.status.phase = phase
            cur.status.slice_id = slice_id or cur.status.slice_id
            cur.status.scheduled = scheduled or cur.status.scheduled
            await self.client.update_status(cur)
        except errors.StatusError:
            pass


class ElectedScheduler:
    """Active-standby scheduler behind the ``SchedulerLeaderElection``
    gate (alpha, default off): N instances CAS one Lease
    (client/leaderelection.py); only the holder runs a Scheduler, so
    two scheduler processes can never double-bind a chip. Standbys keep
    a warm InformerFactory — takeover builds its Scheduler on an
    already-synced cache (Scheduler.start replays synced stores into
    its handlers) instead of relisting the world.

    Handoffs: a graceful :meth:`stop` releases the Lease
    (LeaderElector.release) so the standby takes over within its retry
    period; a crash leaves the Lease to expire and the standby pays
    ``lease_duration`` — the same fast-vs-crash split the control-plane
    replication layer has.

    With the gate off, :meth:`start` runs the scheduler directly, no
    Lease traffic at all — byte-identical to the ungated build.
    """

    LEASE_NAME = "kube-scheduler"

    def __init__(self, client: Client, identity: str,
                 name: str = "default-scheduler",
                 backoff_seconds: float = 1.0, policy=None,
                 lease_duration: float = 4.0, renew_deadline: float = 3.0,
                 retry_period: float = 1.0,
                 lease_namespace: str = "kube-system"):
        self.client = client
        self.identity = identity
        self._sched_kw = {"name": name, "backoff_seconds": backoff_seconds,
                          "policy": policy}
        from ..client.informer import InformerFactory
        self._factory = InformerFactory(client)
        from ..client.leaderelection import LeaderElector
        self.elector = LeaderElector(
            client, self.LEASE_NAME, identity, namespace=lease_namespace,
            lease_duration=lease_duration, renew_deadline=renew_deadline,
            retry_period=retry_period)
        #: The live Scheduler while this instance leads; None as standby.
        self.scheduler: Optional[Scheduler] = None
        self._task: Optional[asyncio.Task] = None
        self._gated = False

    @property
    def is_leader(self) -> bool:
        return self.elector.is_leader if self._gated else \
            self.scheduler is not None

    async def start(self) -> None:
        from ..util.features import GATES
        self._gated = GATES.enabled("SchedulerLeaderElection")
        if not self._gated:
            self.scheduler = Scheduler(self.client, **self._sched_kw)
            await self.scheduler.start()
            return
        # Warm the shared informers NOW: a standby that takes over
        # starts scheduling from an already-synced cache.
        for plural in ("pods", "nodes", "podgroups"):
            self._factory.informer(plural)
        self._factory.start_all()
        self._task = spawn(self.elector.run(self._lead),
                           name=f"elected-scheduler-{self.identity}")

    async def _lead(self) -> None:
        sched = Scheduler(self.client, informer_factory=self._factory,
                          **self._sched_kw)
        await sched.start()
        self.scheduler = sched
        try:
            await asyncio.Event().wait()  # lead until cancelled
        finally:
            self.scheduler = None
            # Shield: this runs on leadership loss/cancel, and stop()
            # must complete or in-flight binds leak into the successor.
            await asyncio.shield(sched.stop())

    async def stop(self) -> None:
        if not self._gated:
            if self.scheduler is not None:
                await self.scheduler.stop()
                self.scheduler = None
            return
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self._factory.stop_all()
