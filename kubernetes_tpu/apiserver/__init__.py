from .registry import Registry, ResourceSpec  # noqa: F401
