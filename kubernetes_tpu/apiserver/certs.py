"""Cluster PKI — CA, server certs, client-identity certs.

Reference: kubeadm's certs phase (``cmd/kubeadm/app/phases/certs/``)
mints a self-signed CA, an apiserver serving cert, and per-component
client certs; the apiserver authenticates client certs by chain
verification and maps Subject CN -> user, Subject O -> groups
(``staging/src/k8s.io/apiserver/pkg/authentication/request/x509/
x509.go:83 New``, the CommonNameUserConversion at ``:107``).

TPU-native shape: one small module over ``cryptography`` producing PEM
files on disk; the apiserver and node server load them into stdlib
``ssl`` contexts (no custom TLS code). Identity convention preserved
exactly — CN is the username, each O is a group — so RBAC rules work
identically for cert- and token-authenticated callers.
"""
from __future__ import annotations

import datetime
import ipaddress
import os
from dataclasses import dataclass

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

_ONE_DAY = datetime.timedelta(days=1)


def _san_entries(sans) -> list:
    """IP-vs-DNS classification for SubjectAlternativeName entries —
    the one place both issuance paths (_issue, sign_csr_pem) share."""
    alt = []
    for san in sans:
        try:
            alt.append(x509.IPAddress(ipaddress.ip_address(san)))
        except ValueError:
            alt.append(x509.DNSName(san))
    return alt


def _new_key():
    # ECDSA P-256: small, fast handshakes; kubeadm moved the same way.
    return ec.generate_private_key(ec.SECP256R1())


def _write(path: str, data: bytes, private: bool = False) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                 0o600 if private else 0o644)
    with os.fdopen(fd, "wb") as f:
        f.write(data)


def _key_pem(key) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())


@dataclass
class CertPair:
    cert_path: str
    key_path: str


class CertAuthority:
    """A CA on disk: ``<dir>/ca.crt`` + ``<dir>/ca.key``.

    ``ensure`` is idempotent (loads an existing CA), so every component
    of a restarted cluster keeps verifying the same chain.
    """

    def __init__(self, directory: str):
        self.dir = directory
        self.ca_cert_path = os.path.join(directory, "ca.crt")
        self.ca_key_path = os.path.join(directory, "ca.key")
        self._key = None
        self._cert = None

    # -- CA lifecycle -----------------------------------------------------

    def ensure(self, common_name: str = "kubernetes-tpu-ca") -> "CertAuthority":
        if os.path.exists(self.ca_cert_path) and os.path.exists(self.ca_key_path):
            self._key = serialization.load_pem_private_key(
                open(self.ca_key_path, "rb").read(), password=None)
            self._cert = x509.load_pem_x509_certificate(
                open(self.ca_cert_path, "rb").read())
            return self
        key = _new_key()
        name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (x509.CertificateBuilder()
                .subject_name(name).issuer_name(name)
                .public_key(key.public_key())
                .serial_number(x509.random_serial_number())
                .not_valid_before(now - _ONE_DAY)
                .not_valid_after(now + datetime.timedelta(days=3650))
                .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                               critical=True)
                .add_extension(x509.KeyUsage(
                    digital_signature=True, key_cert_sign=True, crl_sign=True,
                    content_commitment=False, key_encipherment=False,
                    data_encipherment=False, key_agreement=False,
                    encipher_only=False, decipher_only=False), critical=True)
                .sign(key, hashes.SHA256()))
        _write(self.ca_key_path, _key_pem(key), private=True)
        _write(self.ca_cert_path, cert.public_bytes(serialization.Encoding.PEM))
        self._key, self._cert = key, cert
        return self

    @property
    def cert_pem(self) -> bytes:
        return open(self.ca_cert_path, "rb").read()

    def fingerprint(self) -> str:
        """sha256 of the CA cert (DER) — the kubeadm
        ``discovery-token-ca-cert-hash`` pin a joiner verifies."""
        import hashlib
        der = self._cert.public_bytes(serialization.Encoding.DER)
        return "sha256:" + hashlib.sha256(der).hexdigest()

    # -- issuance ---------------------------------------------------------

    def _issue(self, subject: x509.Name, *, sans=None, client: bool,
               days: int = 365):
        key = _new_key()
        now = datetime.datetime.now(datetime.timezone.utc)
        eku = (ExtendedKeyUsageOID.CLIENT_AUTH if client
               else ExtendedKeyUsageOID.SERVER_AUTH)
        b = (x509.CertificateBuilder()
             .subject_name(subject).issuer_name(self._cert.subject)
             .public_key(key.public_key())
             .serial_number(x509.random_serial_number())
             .not_valid_before(now - _ONE_DAY)
             .not_valid_after(now + datetime.timedelta(days=days))
             .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                            critical=True)
             .add_extension(x509.ExtendedKeyUsage([eku]), critical=False))
        if sans:
            b = b.add_extension(
                x509.SubjectAlternativeName(_san_entries(sans)),
                critical=False)
        return key, b.sign(self._key, hashes.SHA256())

    def issue_server_cert(self, name: str, sans: list[str],
                          out_dir: str = "") -> CertPair:
        subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, name)])
        key, cert = self._issue(subject, sans=sans, client=False)
        out = out_dir or self.dir
        base = name.replace(":", "-").replace("/", "-")
        pair = CertPair(os.path.join(out, f"{base}.crt"),
                        os.path.join(out, f"{base}.key"))
        _write(pair.key_path, _key_pem(key), private=True)
        _write(pair.cert_path, cert.public_bytes(serialization.Encoding.PEM))
        return pair

    def issue_client_cert(self, user: str, groups: list[str] = (),
                          out_dir: str = "", filename: str = "") -> CertPair:
        """CN = user, O = groups — the reference identity convention."""
        attrs = [x509.NameAttribute(NameOID.COMMON_NAME, user)]
        for g in groups:
            attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, g))
        key, cert = self._issue(x509.Name(attrs), client=True)
        out = out_dir or self.dir
        base = filename or user.replace(":", "-").replace("/", "-")
        pair = CertPair(os.path.join(out, f"{base}.crt"),
                        os.path.join(out, f"{base}.key"))
        _write(pair.key_path, _key_pem(key), private=True)
        _write(pair.cert_path, cert.public_bytes(serialization.Encoding.PEM))
        return pair

    def sign_csr_pem(self, csr_pem: bytes, user: str,
                     groups: list[str] = (), days: int = 365,
                     server_auth: bool = False,
                     sans: list[str] = ()) -> bytes:
        """Sign a CSR's PUBLIC KEY for the server-decided identity
        (CN/O come from ``user``/``groups``, never from the CSR —
        a joiner must not pick its own identity). Returns cert PEM.
        The TLS-bootstrap end state: the private key never leaves the
        node (reference: ``pkg/kubelet/certificate/kubelet.go:96``).

        ``server_auth=True`` mints a SERVING cert instead (the kubelet
        serving-cert CSR flow): EKU serverAuth, SANs from ``sans`` —
        the caller (apiserver endpoint) decides which claimed addresses
        to admit, like the reference's CSR approver does."""
        csr = x509.load_pem_x509_csr(csr_pem)
        if not csr.is_signature_valid:
            raise ValueError("CSR signature invalid")
        attrs = [x509.NameAttribute(NameOID.COMMON_NAME, user)]
        for g in groups:
            attrs.append(x509.NameAttribute(NameOID.ORGANIZATION_NAME, g))
        now = datetime.datetime.now(datetime.timezone.utc)
        eku = (ExtendedKeyUsageOID.SERVER_AUTH if server_auth
               else ExtendedKeyUsageOID.CLIENT_AUTH)
        b = (x509.CertificateBuilder()
             .subject_name(x509.Name(attrs))
             .issuer_name(self._cert.subject)
             .public_key(csr.public_key())
             .serial_number(x509.random_serial_number())
             .not_valid_before(now - _ONE_DAY)
             .not_valid_after(now + datetime.timedelta(days=days))
             .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                            critical=True)
             .add_extension(x509.ExtendedKeyUsage([eku]), critical=False))
        if sans:
            b = b.add_extension(
                x509.SubjectAlternativeName(_san_entries(sans)),
                critical=False)
        cert = b.sign(self._key, hashes.SHA256())
        return cert.public_bytes(serialization.Encoding.PEM)


def make_csr_pem(key_path: str, common_name: str) -> bytes:
    """Generate a key at ``key_path`` (0600) and return a CSR PEM for
    it — the joiner half of the CSR flow."""
    key = _new_key()
    _write(key_path, _key_pem(key), private=True)
    csr = (x509.CertificateSigningRequestBuilder()
           .subject_name(x509.Name(
               [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
           .sign(key, hashes.SHA256()))
    return csr.public_bytes(serialization.Encoding.PEM)


def identity_from_der(der: bytes) -> tuple[str, list[str]]:
    """(user, groups) from a peer cert (DER) — CN and O values."""
    cert = x509.load_der_x509_certificate(der)
    cn = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
    orgs = cert.subject.get_attributes_for_oid(NameOID.ORGANIZATION_NAME)
    return (cn[0].value if cn else "", [o.value for o in orgs])


def server_ssl_context(pair: CertPair, ca_path: str = "",
                       require_client_cert: bool = False):
    """TLS-server context; with ``ca_path``, client certs are verified
    against the CA. Default CERT_OPTIONAL — tokens over TLS remain a
    valid way in, like the reference's authenticator union; a presented
    cert failing chain verification still aborts the handshake.
    ``require_client_cert=True`` (the node server: kubelet requires
    delegated authn on :10250) refuses connections without a valid
    cluster client cert at the handshake."""
    import ssl
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(pair.cert_path, pair.key_path)
    if ca_path:
        ctx.load_verify_locations(ca_path)
        ctx.verify_mode = (ssl.CERT_REQUIRED if require_client_cert
                           else ssl.CERT_OPTIONAL)
    return ctx


def client_ssl_context(ca_path: str, cert_path: str = "",
                       key_path: str = "", check_hostname: bool = True):
    """THE client-side TLS context (RESTClient and ktl join both use
    it — one place for policy like hostname checking): trust the
    cluster CA; with ``cert_path``, authenticate with an identity cert.
    Hostname verification is ON — serving certs carry their reachable
    addresses in SANs (issue_server_cert / the serving-CSR flow), so a
    cert minted for one endpoint cannot be replayed as another at a
    different address. ``check_hostname=False`` only for callers that
    pin the peer another way (e.g. the join flow's CA fingerprint,
    checked before any credential is sent)."""
    import ssl
    ctx = ssl.create_default_context(cafile=ca_path)
    ctx.check_hostname = check_hostname
    if cert_path:
        ctx.load_cert_chain(cert_path, key_path or None)
    return ctx


def local_host_sans(extra: list[str] = ()) -> list[str]:
    """The addresses this host answers on, for serving-cert SANs:
    loopback names + the machine hostname + its resolved IP (when
    resolvable). One derivation shared by the apiserver cert, node
    serving certs, and the join flow's claimed set — divergence here
    means one endpoint verifies where another fails."""
    import socket
    sans = {"localhost", "127.0.0.1", socket.gethostname()}
    try:
        sans.add(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    sans.update(extra)
    return sorted(s for s in sans if s)


def fingerprint_pem(cert_pem: bytes) -> str:
    """sha256:<hex> of a PEM cert's DER — computed LOCALLY by joiners
    over the bytes they actually received, so a server cannot assert a
    fingerprint for a CA it didn't send."""
    import hashlib
    cert = x509.load_pem_x509_certificate(cert_pem)
    der = cert.public_bytes(serialization.Encoding.DER)
    return "sha256:" + hashlib.sha256(der).hexdigest()
