"""Watch fan-out flush batching (gate ``WatchFanoutBatch``, alpha).

PR 9 made each watcher coalesce its pending events into one
``resp.write``; the measured residual at density scale is the flush
DISCIPLINE around those writes: every watch handler awaits its own
socket send inline, so the drain loop parks on a backpressured
consumer, and N handlers interleave N small write awaits per event
burst on the shared router loop. This module centralizes the sends:

- each watcher owns a :class:`WatchSink` — a bounded byte buffer the
  handler appends encoded event frames to (never awaiting);
- a small pool of flusher workers (watchers sharded across them
  round-robin) performs ONE buffered writev-style send per sink per
  flush round — everything a sink accumulated since its last flush
  goes out in a single ``resp.write``;
- a slow consumer can stall only its own shard's round, never the
  whole fan-out; one whose buffer overflows is CLOSED (the client
  relists — the same contract as the registry watch queue overflow).

Byte-stream equivalence: frames enter a sink in handler order and
leave in order, concatenated — the same lines/frames, same per-watcher
order, as the inline write loop; only the coalescing boundary moves.
Gate off, the module is never imported on the watch path.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..metrics.registry import Counter, Gauge, Histogram
from ..util.tasks import spawn

log = logging.getLogger("apiserver.fanout")

FANOUT_FLUSHES = Counter(
    "apiserver_fanout_flushes_total",
    "Buffered watch fan-out socket flushes, by flusher shard",
    labels=("shard",))

FANOUT_FLUSH_EVENTS = Histogram(
    "apiserver_fanout_flush_events",
    "Watch events coalesced into one fan-out flush",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))

FANOUT_FLUSH_BYTES = Histogram(
    "apiserver_fanout_flush_bytes",
    "Bytes per buffered fan-out flush",
    buckets=(256, 1024, 4096, 16384, 65536, 262144, 1048576))

FANOUT_OVERFLOWS = Counter(
    "apiserver_fanout_overflows_total",
    "Watch sinks closed because a slow consumer overflowed its buffer")

FANOUT_SINKS = Gauge(
    "apiserver_fanout_sinks",
    "Watch sinks currently registered with the fan-out flusher")


class WatchSink:
    """Per-watcher buffered writer. The owning watch handler is the
    only pusher; the shard's flusher worker (same event loop) is the
    only sender while registered. ``closed`` flips on overflow or a
    dead peer — the handler sees it and ends the stream."""

    __slots__ = ("resp", "closed", "in_flight", "_buf", "_events",
                 "_shard", "_limit")

    def __init__(self, resp, shard, limit: int):
        self.resp = resp
        self.closed = False
        #: True while the flusher worker awaits a send of taken bytes —
        #: the final handler-side drain must wait it out to keep the
        #: byte stream ordered.
        self.in_flight = False
        self._buf = bytearray()
        self._events = 0
        self._shard = shard
        self._limit = limit

    def push(self, line: bytes) -> None:
        """Queue one encoded event frame; wakes the shard's flusher.
        Overflow closes the sink instead of growing without bound — a
        consumer that cannot keep up with the fan-out must relist, not
        balloon apiserver memory."""
        if self.closed:
            return
        if len(self._buf) + len(line) > self._limit:
            self.closed = True
            FANOUT_OVERFLOWS.inc()
            return
        self._buf += line
        self._events += 1
        self._shard.wake.set()

    def take(self) -> tuple[bytes, int]:
        """Swap out everything pending: (bytes, event count)."""
        if not self._buf:
            return b"", 0
        out, n = bytes(self._buf), self._events
        self._buf = bytearray()
        self._events = 0
        return out, n


class _Shard:
    __slots__ = ("idx", "wake", "sinks", "task", "stopping")

    def __init__(self, idx: int):
        self.idx = idx
        self.wake = asyncio.Event()
        self.sinks: set = set()
        self.task = None
        #: Cooperative shutdown flag: cancellation alone is NOT a
        #: reliable exit on py3.10 — wait_for swallows an outer cancel
        #: that races the inner write's completion (bpo-37658 family),
        #: which would leave the worker parked forever and stop()'s
        #: gather waiting on it.
        self.stopping = False


class FanoutFlusher:
    """The flush engine: ``shards`` worker tasks, each draining its
    own subset of sinks per round. Construction is inert (no tasks
    until the first register); built by the apiserver on the router
    loop — the loop every watch response writes from."""

    def __init__(self, shards: int = 4, overflow_limit: int = 4 << 20,
                 write_timeout: float = 5.0):
        self._shards = [_Shard(i) for i in range(max(1, shards))]
        self._rr = 0
        self.overflow_limit = overflow_limit
        #: Bound on one sink's socket send: a stalled-but-connected
        #: consumer (TCP zero window) must cost its shard at most this
        #: long, not park the worker forever — past it the sink is
        #: closed like an overflow (the client relists).
        self.write_timeout = write_timeout

    def register(self, resp) -> WatchSink:
        shard = self._shards[self._rr % len(self._shards)]
        self._rr += 1
        if shard.task is None or shard.task.done():
            # done() covers a worker killed by an unexpected exception
            # (spawn() logs it): the shard must revive, or a quarter
            # of all watchers would silently stop receiving events.
            shard.stopping = False
            shard.task = spawn(self._run(shard),
                               name=f"watch-fanout-{shard.idx}")
        sink = WatchSink(resp, shard, self.overflow_limit)
        shard.sinks.add(sink)
        FANOUT_SINKS.set(float(sum(len(s.sinks) for s in self._shards)))
        return sink

    def discard(self, sink: WatchSink) -> None:
        """Synchronous removal — safe mid-cancellation, never leaks a
        sink into future flush rounds."""
        sink._shard.sinks.discard(sink)
        FANOUT_SINKS.set(float(sum(len(s.sinks) for s in self._shards)))

    async def drain(self, sink: WatchSink, timeout: float = 1.0) -> None:
        """Final handler-side flush after :meth:`discard`: wait out an
        in-flight worker send (ordering), then write the remainder
        directly — the handler owns the response again. Bounded: a
        worker parked on a dead/backpressured peer must not pin this
        handler past ``timeout`` (the stream is ending either way)."""
        deadline = asyncio.get_running_loop().time() + timeout
        while sink.in_flight:
            if asyncio.get_running_loop().time() >= deadline:
                return
            await asyncio.sleep(0.005)
        buf, _n = sink.take()
        if buf and not sink.closed:
            await sink.resp.write(buf)

    async def _run(self, shard: _Shard) -> None:
        label = str(shard.idx)
        while not shard.stopping:
            await shard.wake.wait()
            if shard.stopping:
                return
            # Micro-batch: the first push of a burst wakes this worker,
            # but the pushing handlers are still draining their watch
            # queues on this same loop — yield once so the whole burst
            # lands in the sink buffers, then flush it as ONE send per
            # sink. Without this the worker takes 1-event buffers and
            # the coalescing the engine exists for never happens.
            await asyncio.sleep(0)
            shard.wake.clear()
            try:
                for sink in list(shard.sinks):
                    buf, n = sink.take()
                    if not buf or sink.closed:
                        continue
                    sink.in_flight = True
                    try:
                        await asyncio.wait_for(sink.resp.write(buf),
                                               self.write_timeout)
                    except asyncio.TimeoutError:
                        # Stalled consumer: the contract is "a slow
                        # watcher stalls its shard for one bounded
                        # round", never indefinitely.
                        sink.closed = True
                        FANOUT_OVERFLOWS.inc()
                        continue
                    except (OSError, RuntimeError):
                        # Peer gone (any ConnectionError/BrokenPipe
                        # flavor) or response already finished: close
                        # THIS sink only — one dead watcher must never
                        # kill the shard's worker and silence its
                        # siblings. Failed sends don't count as
                        # flushes.
                        sink.closed = True
                        continue
                    finally:
                        sink.in_flight = False
                    FANOUT_FLUSHES.inc(shard=label)
                    FANOUT_FLUSH_EVENTS.observe(float(n))
                    FANOUT_FLUSH_BYTES.observe(float(len(buf)))
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                # A surprise in the round body (a metrics edit, a
                # future refactor) must not kill this worker forever —
                # that would silently stall every sink on the shard
                # until the next register() revived it.
                log.warning("fanout shard %s flush round failed: %s",
                            label, e)

    async def stop(self) -> None:
        tasks = []
        for shard in self._shards:
            # Flag + wake FIRST: a worker that loses its cancel to the
            # py3.10 wait_for race still exits at the next loop check.
            shard.stopping = True
            shard.wake.set()
            if shard.task is not None:
                shard.task.cancel()
                tasks.append(shard.task)
                shard.task = None
            shard.sinks.clear()
        if tasks:
            # Await the teardown: a worker parked in a write must
            # unwind before the server tears the loop down, or
            # shutdown leaves destroyed-pending task warnings behind.
            await asyncio.gather(*tasks, return_exceptions=True)
        FANOUT_SINKS.set(0.0)
