"""Audit logging — policy-driven structured request records.

Reference: ``staging/src/k8s.io/apiserver/pkg/audit/`` — a Policy maps
each request to a level (None/Metadata/Request) via first-matching-rule
(``pkg/audit/policy/checker.go LevelAndStages``), and events flow to
backends: a JSON-lines log backend, and/or a BATCHING webhook backend
(``plugin/pkg/audit/webhook/webhook.go``: bounded buffer, max-size/
max-wait batches, retry with backoff; drop-oldest on overflow rather
than blocking API serving). One event per request at ResponseComplete,
request body attached at Request level.
"""
from __future__ import annotations

import asyncio
import datetime
import json
import logging
from dataclasses import dataclass, field
from typing import IO, Optional

log = logging.getLogger("audit")

LEVEL_NONE = "None"
LEVEL_METADATA = "Metadata"
LEVEL_REQUEST = "Request"
_LEVELS = (LEVEL_NONE, LEVEL_METADATA, LEVEL_REQUEST)

_READ_VERBS = {"get", "list", "watch"}


@dataclass
class AuditRule:
    """One policy rule (reference: ``pkg/apis/audit Policy.Rules``).
    Empty selector lists match everything; all non-empty selectors must
    match (AND), rules evaluate in order, first match wins."""
    level: str = LEVEL_METADATA
    users: list[str] = field(default_factory=list)
    verbs: list[str] = field(default_factory=list)
    resources: list[str] = field(default_factory=list)
    namespaces: list[str] = field(default_factory=list)

    def matches(self, user: str, verb: str, resource: str,
                namespace: str) -> bool:
        return ((not self.users or user in self.users)
                and (not self.verbs or verb in self.verbs)
                and (not self.resources or resource in self.resources)
                and (not self.namespaces or namespace in self.namespaces))


class AuditPolicy:
    """Ordered rules + default level (the rule-less tail every real
    policy file ends with)."""

    def __init__(self, rules: Optional[list[AuditRule]] = None,
                 default_level: str = LEVEL_METADATA):
        self.rules = rules or []
        self.default_level = default_level
        for r in self.rules:
            if r.level not in _LEVELS:
                raise ValueError(f"unknown audit level {r.level!r} "
                                 f"(known: {_LEVELS})")
        if default_level not in _LEVELS:
            raise ValueError(f"unknown audit level {default_level!r}")

    def level_for(self, user: str, verb: str, resource: str,
                  namespace: str) -> str:
        for rule in self.rules:
            if rule.matches(user, verb, resource, namespace):
                return rule.level
        return self.default_level

    @classmethod
    def from_file(cls, path: str) -> "AuditPolicy":
        """Load a policy file (YAML or JSON):

        .. code-block:: yaml

            default_level: Metadata
            rules:
            - level: None
              resources: [events, leases]
            - level: Metadata
              resources: [secrets]      # never log secret bodies
            - level: Request
              verbs: [create, update, patch, delete]
        """
        import yaml
        with open(path) as f:
            # YAML is a JSON superset: one parser, one error surface
            # (same approach as cluster/config.py load_cluster_config).
            data = yaml.safe_load(f.read())
        if not isinstance(data, dict):
            raise ValueError(f"audit policy {path}: expected a mapping")
        rules = [AuditRule(
            level=r.get("level", LEVEL_METADATA),
            users=list(r.get("users", [])),
            verbs=list(r.get("verbs", [])),
            resources=list(r.get("resources", [])),
            namespaces=list(r.get("namespaces", [])),
        ) for r in data.get("rules", [])]
        return cls(rules, data.get("default_level", LEVEL_METADATA))


class AuditWebhookBackend:
    """Batching webhook delivery (reference: webhook.go ModeBatch).

    Events buffer in a bounded deque (drop-oldest + counter on
    overflow — audit must never block or fail API serving); a flush
    task posts ``{"kind": "EventList", "items": [...]}`` batches of up
    to ``max_batch_size`` every ``max_batch_wait`` seconds (sooner when
    a batch fills), retrying each batch with exponential backoff."""

    def __init__(self, url: str, buffer_size: int = 10000,
                 max_batch_size: int = 400, max_batch_wait: float = 5.0,
                 retries: int = 4, initial_backoff: float = 0.5,
                 ssl=None):
        from collections import deque
        self.url = url
        self.max_batch_size = max_batch_size
        self.max_batch_wait = max_batch_wait
        self.retries = retries
        self.initial_backoff = initial_backoff
        self.ssl = ssl
        self._buf = deque(maxlen=buffer_size)
        self.dropped = 0
        self.delivered = 0
        self._task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._session = None
        self._stopped = False

    def enqueue(self, event: dict) -> None:
        if len(self._buf) == self._buf.maxlen:
            self.dropped += 1  # deque drops the oldest itself
        self._buf.append(event)
        if self._wake is not None and \
                len(self._buf) >= self.max_batch_size:
            self._wake.set()

    def start(self) -> None:
        import aiohttp
        self._wake = asyncio.Event()
        self._session = aiohttp.ClientSession()  # one conn, reused
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        if self._task:
            if self._wake is not None:
                self._wake.set()
            try:
                await asyncio.wait_for(self._task, 10.0)
            except asyncio.TimeoutError:
                # wait_for cancelled + awaited the drain task; whatever
                # it was carrying plus the buffer is LOST — the loss
                # counter must say so, not read zero.
                lost = len(self._buf)
                self._buf.clear()
                self.dropped += lost
                log.warning("audit webhook: shutdown drain timed out; "
                            "%d buffered events lost (in-flight batch "
                            "may also be lost)", lost)
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def _run(self) -> None:
        while True:
            try:
                await asyncio.wait_for(self._wake.wait(),
                                       self.max_batch_wait)
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            while self._buf:
                batch = []
                while self._buf and len(batch) < self.max_batch_size:
                    batch.append(self._buf.popleft())
                await self._post(batch)
            if self._stopped:
                return

    async def _post(self, batch: list[dict]) -> None:
        import aiohttp
        payload = {"kind": "EventList", "items": batch}
        backoff = self.initial_backoff
        err = ""
        delivered_ok = False
        try:
            for attempt in range(self.retries):
                try:
                    async with self._session.post(
                            self.url, json=payload, ssl=self.ssl,
                            timeout=aiohttp.ClientTimeout(total=10)) as r:
                        if r.status < 400:
                            self.delivered += len(batch)
                            delivered_ok = True
                            return
                        err = f"HTTP {r.status}"
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    err = str(e)
                if attempt < self.retries - 1:
                    await asyncio.sleep(backoff)
                    backoff *= 2
        except asyncio.CancelledError:
            # Shutdown-drain timeout cancelled us mid-batch: the honest
            # loss counter includes the batch in hand, not just what
            # stop() finds left in the buffer — UNLESS the 2xx already
            # landed and the cancel merely hit the context exit
            # (counting it dropped too would over-report loss).
            if not delivered_ok:
                self.dropped += len(batch)
            raise
        self.dropped += len(batch)
        log.warning("audit webhook: dropped a batch of %d after %d "
                    "attempts (%s)", len(batch), self.retries, err)


class AuditLogger:
    """Audit pipeline front-end. ``policy`` (per-rule levels) governs
    what is recorded; without one, the flat ``level`` + ``omit_reads``
    knobs apply globally (the pre-policy behavior, kept). Events go to
    the JSON-lines stream (``path``/``stream``) and, when configured,
    the batching ``webhook`` backend."""

    def __init__(self, path: str = "", stream: Optional[IO] = None,
                 level: str = LEVEL_METADATA, omit_reads: bool = False,
                 policy: Optional[AuditPolicy] = None,
                 webhook: Optional[AuditWebhookBackend] = None):
        self.level = level
        self.omit_reads = omit_reads
        self.policy = policy
        self.webhook = webhook
        self._stream = stream
        self._path = path
        if path and stream is None:
            self._stream = open(path, "a", buffering=1)

    def start(self) -> None:
        """Start async backends (call on a running loop)."""
        if self.webhook is not None:
            self.webhook.start()

    async def aclose(self) -> None:
        if self.webhook is not None:
            await self.webhook.stop()
        self.close()

    def close(self) -> None:
        if self._path and self._stream:
            self._stream.close()
            self._stream = None

    def _level_for(self, user: str, verb: str, resource: str,
                   namespace: str) -> str:
        if self.policy is not None:
            return self.policy.level_for(user, verb, resource, namespace)
        if self.omit_reads and verb in _READ_VERBS:
            return LEVEL_NONE
        return self.level

    def wants_body(self, user: str, verb: str, resource: str,
                   namespace: str) -> bool:
        """The server reads the request body back only when the
        EFFECTIVE level for this request wants it."""
        return self._level_for(user, verb, resource,
                               namespace) == LEVEL_REQUEST

    def record(self, *, user: str, verb: str, resource: str,
               namespace: str, name: str, code: int,
               latency_seconds: float, body: Optional[dict] = None,
               impersonated_by: str = "") -> None:
        level = self._level_for(user, verb, resource, namespace)
        if level == LEVEL_NONE:
            return
        event = {
            "stage": "ResponseComplete",
            "level": level,
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc).isoformat(),
            "user": user,
            "verb": verb,
            "resource": resource,
            "namespace": namespace,
            "name": name,
            "code": code,
            "latency_seconds": round(latency_seconds, 6),
        }
        if impersonated_by:
            # Both identities on the record (reference: audit events
            # carry impersonatedUser alongside user).
            event["impersonated_by"] = impersonated_by
        if level == LEVEL_REQUEST and body is not None:
            event["request_object"] = body
        if self._stream is not None:
            try:
                self._stream.write(json.dumps(event) + "\n")
            except (OSError, ValueError):
                log.exception("audit write failed")
        if self.webhook is not None:
            self.webhook.enqueue(event)
