"""Audit logging — structured request records.

Reference: ``staging/src/k8s.io/apiserver/pkg/audit/`` — policy-driven
event levels (None/Metadata/Request/RequestResponse) written by a log
backend as JSON lines. Here: one event per API request, emitted after
the response (ResponseComplete stage), with the request body attached
at Request level and above. Read-only verbs can be excluded by policy
(the common production config).
"""
from __future__ import annotations

import datetime
import json
import logging
from typing import IO, Optional

log = logging.getLogger("audit")

LEVEL_NONE = "None"
LEVEL_METADATA = "Metadata"
LEVEL_REQUEST = "Request"

_READ_VERBS = {"get", "list", "watch"}


class AuditLogger:
    """JSON-lines audit backend. ``path`` or ``stream``; level selects
    how much is recorded; ``omit_reads`` drops get/list/watch events."""

    def __init__(self, path: str = "", stream: Optional[IO] = None,
                 level: str = LEVEL_METADATA, omit_reads: bool = False):
        self.level = level
        self.omit_reads = omit_reads
        self._stream = stream
        self._path = path
        if path and stream is None:
            self._stream = open(path, "a", buffering=1)

    def close(self) -> None:
        if self._path and self._stream:
            self._stream.close()
            self._stream = None

    def record(self, *, user: str, verb: str, resource: str,
               namespace: str, name: str, code: int,
               latency_seconds: float, body: Optional[dict] = None,
               impersonated_by: str = "") -> None:
        if self.level == LEVEL_NONE or self._stream is None:
            return
        if self.omit_reads and verb in _READ_VERBS:
            return
        event = {
            "stage": "ResponseComplete",
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc).isoformat(),
            "user": user,
            "verb": verb,
            "resource": resource,
            "namespace": namespace,
            "name": name,
            "code": code,
            "latency_seconds": round(latency_seconds, 6),
        }
        if impersonated_by:
            # Both identities on the record (reference: audit events
            # carry impersonatedUser alongside user).
            event["impersonated_by"] = impersonated_by
        if self.level == LEVEL_REQUEST and body is not None:
            event["request_object"] = body
        try:
            self._stream.write(json.dumps(event) + "\n")
        except (OSError, ValueError):
            log.exception("audit write failed")
