"""Bootstrap tokens + node credential minting — the kubeadm analog.

Reference flow (``cmd/kubeadm``): ``kubeadm token create`` writes a
``bootstrap.kubernetes.io/token`` Secret in kube-system; the
apiserver's bootstrap-token authenticator maps a ``<id>.<secret>``
bearer to user ``system:bootstrap:<id>`` in group
``system:bootstrappers``; RBAC lets that group request a node
credential (there: a CSR the controller signs into a
``system:node:<name>`` client cert); ``kubeadm join`` then runs the
kubelet with it.

This environment has no TLS stack, so the CSR-signing step is replaced
by its end state: ``mint_node_credential`` creates a per-node
ServiceAccount (kube-system/``node-<name>``) + token Secret and a
ClusterRoleBinding to the ``system:node`` ClusterRole, and returns the
bearer token. Same trust shape — a short-lived, revocable, auditable
bootstrap secret is exchanged for a durable, least-privilege node
identity — over the SA-token machinery the server already verifies
(``server.py _sa_user``: UID-bound, revocable).
"""
from __future__ import annotations

import base64
import datetime
import re
import secrets as pysecrets
from typing import Optional

from ..api import errors, rbac, types as t
from ..api.meta import ObjectMeta, now
from .registry import Registry

SECRET_TYPE_BOOTSTRAP = "bootstrap.kubernetes.io/token"
GROUP_BOOTSTRAPPERS = "system:bootstrappers"
BOOTSTRAP_USER_PREFIX = "system:bootstrap:"
NODE_ROLE = "system:node"
NODES_NAMESPACE = "kube-system"

_TOKEN_RE = re.compile(r"^([a-z0-9]{6})\.([a-z0-9]{16})$")
_ALPHABET = "abcdefghijklmnopqrstuvwxyz0123456789"


def generate_token() -> str:
    """``<6 char id>.<16 char secret>`` (kubeadm token format)."""
    gen = lambda n: "".join(pysecrets.choice(_ALPHABET) for _ in range(n))  # noqa: E731
    return f"{gen(6)}.{gen(16)}"


def make_bootstrap_secret(token: str, ttl_seconds: float = 24 * 3600,
                          description: str = "") -> t.Secret:
    m = _TOKEN_RE.match(token)
    if not m:
        raise ValueError("bootstrap token must look like abcdef.0123456789abcdef")
    token_id, token_secret = m.groups()
    expiry = (datetime.datetime.now(datetime.timezone.utc)
              + datetime.timedelta(seconds=ttl_seconds))
    b64 = lambda s: base64.b64encode(s.encode()).decode()  # noqa: E731
    return t.Secret(
        metadata=ObjectMeta(name=f"bootstrap-token-{token_id}",
                            namespace=NODES_NAMESPACE),
        type=SECRET_TYPE_BOOTSTRAP,
        data={
            "token-id": b64(token_id),
            "token-secret": b64(token_secret),
            "expiration": b64(expiry.isoformat()),
            "usage-bootstrap-authentication": b64("true"),
            **({"description": b64(description)} if description else {}),
        })


def _field(secret: t.Secret, key: str) -> str:
    try:
        return base64.b64decode(secret.data.get(key, ""), validate=True).decode()
    except Exception:  # noqa: BLE001 — malformed field == absent
        return ""


def resolve_bootstrap_token(registry: Registry, token: str) -> Optional[str]:
    """Bearer -> ``system:bootstrap:<id>`` or None. Constant-shape
    lookups: secret fetched by name, comparison via compare_digest."""
    m = _TOKEN_RE.match(token or "")
    if not m:
        return None
    token_id, token_secret = m.groups()
    try:
        secret = registry.get("secrets", NODES_NAMESPACE,
                              f"bootstrap-token-{token_id}")
    except errors.StatusError:
        return None
    if secret.type != SECRET_TYPE_BOOTSTRAP:
        return None
    if not pysecrets.compare_digest(_field(secret, "token-secret"),
                                    token_secret):
        return None
    if _field(secret, "usage-bootstrap-authentication") != "true":
        return None
    exp = _field(secret, "expiration")
    if exp:
        try:
            when = datetime.datetime.fromisoformat(exp)
        except ValueError:
            return None  # unparseable expiry: fail closed
        if when.tzinfo is None:
            # Hand-written naive timestamps: treat as UTC rather than
            # raising on the aware/naive comparison (fail closed, not
            # fail crashed — authn runs before the error-mapping try).
            when = when.replace(tzinfo=datetime.timezone.utc)
        if when <= datetime.datetime.now(datetime.timezone.utc):
            return None
    return BOOTSTRAP_USER_PREFIX + token_id


#: What a node agent needs (reference: the system:node ClusterRole +
#: NodeRestriction; we grant the union the agent actually exercises).
NODE_RULES = [
    rbac.PolicyRule(verbs=["*"], resources=["nodes", "nodes/status"]),
    rbac.PolicyRule(verbs=["get", "list", "watch", "update", "patch",
                           "create", "delete"],
                    resources=["pods", "pods/status"]),
    rbac.PolicyRule(verbs=["create", "update", "patch"],
                    resources=["events"]),
    rbac.PolicyRule(verbs=["*"], resources=["leases"]),
    rbac.PolicyRule(verbs=["get", "list", "watch"],
                    resources=["configmaps", "secrets", "services",
                               "endpoints", "persistentvolumeclaims",
                               "persistentvolumes"]),
]


def mint_node_credential(registry: Registry, node_name: str) -> dict:
    """The CSR-signing analog: durable node identity for ``node_name``.
    Idempotent; returns {"token", "user", "server_note"}."""
    if not re.match(r"^[a-z0-9]([a-z0-9.-]{0,61}[a-z0-9])?$", node_name or ""):
        raise errors.InvalidError("node_name must be a DNS-1123 name")
    sa_name = f"node-{node_name}"

    try:
        registry.get("clusterroles", "", NODE_ROLE)
    except errors.NotFoundError:
        registry.create(rbac.ClusterRole(
            metadata=ObjectMeta(name=NODE_ROLE), rules=list(NODE_RULES)))

    try:
        sa = registry.get("serviceaccounts", NODES_NAMESPACE, sa_name)
    except errors.NotFoundError:
        sa = registry.create(t.ServiceAccount(
            metadata=ObjectMeta(name=sa_name, namespace=NODES_NAMESPACE)))

    user = t.service_account_user(NODES_NAMESPACE, sa_name)
    binding_name = f"{NODE_ROLE}:{node_name}"
    try:
        registry.get("clusterrolebindings", "", binding_name)
    except errors.NotFoundError:
        registry.create(rbac.ClusterRoleBinding(
            metadata=ObjectMeta(name=binding_name),
            role_ref=rbac.RoleRef(kind="ClusterRole", name=NODE_ROLE),
            subjects=[rbac.Subject(kind="User", name=user)]))

    # Token secret: reuse a live one bound to this SA's UID, else mint
    # (same UID-binding rule as the ServiceAccount token controller).
    secret_name = f"{sa_name}-token"
    token = ""
    try:
        existing = registry.get("secrets", NODES_NAMESPACE, secret_name)
        if existing.metadata.annotations.get(
                t.SA_UID_ANNOTATION) == sa.metadata.uid:
            token = _field(existing, "token")
        if not token:
            # Stale UID, or a matching secret whose token field is
            # missing/undecodable — either way re-mint from scratch
            # (create below would otherwise 409 forever).
            registry.delete("secrets", NODES_NAMESPACE, secret_name)
    except errors.NotFoundError:
        pass
    if not token:
        token = pysecrets.token_urlsafe(32)
        registry.create(t.Secret(
            metadata=ObjectMeta(
                name=secret_name, namespace=NODES_NAMESPACE,
                annotations={t.SA_NAME_ANNOTATION: sa_name,
                             t.SA_UID_ANNOTATION: sa.metadata.uid}),
            type=t.SECRET_TYPE_SA_TOKEN,
            data={"token": base64.b64encode(token.encode()).decode(),
                  "namespace": base64.b64encode(
                      NODES_NAMESPACE.encode()).decode()}))
        # The SA must reference its token secret or _sa_user rejects it
        # (anti-spoof check #1).
        sa = registry.get("serviceaccounts", NODES_NAMESPACE, sa_name)
        if secret_name not in sa.secrets:
            sa.secrets.append(secret_name)
            registry.update(sa)
    return {"token": token, "user": user, "node_name": node_name}
