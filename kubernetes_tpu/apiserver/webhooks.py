"""External admission webhooks — out-of-tree policy on API writes.

Reference: ``staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/
mutating/admission.go:199 Admit`` and ``.../validating/``. The server
POSTs ``AdmissionReview{request:{uid, operation, resource, namespace,
name, object, old_object}}`` to every matching webhook; a mutating
hook may answer with a base64 RFC 6902 JSONPatch (``patch_type:
"JSONPatch"``), a validating hook answers allowed/denied with a
status message. ``failure_policy`` decides what an unreachable hook
means (Fail -> the API request is rejected; Ignore -> admitted).

Placement: the dispatcher runs in the apiserver's async handlers —
mutating hooks before the registry's in-tree chain, validating hooks
on the final (mutated) request object before storage. Writes made
through the in-process ``LocalClient`` backdoor do not traverse HTTP
and therefore skip webhooks, exactly like they skip authn — the wire
path is the policy surface.

Configs are plain API objects (Mutating/ValidatingWebhookConfiguration,
``api/extensions.py``), listed from the registry with a short TTL so
registering a webhook takes effect within a second without a watch.
"""
from __future__ import annotations

import asyncio
import base64
import json
import logging
import time
import uuid
from typing import Any, Optional

from ..api import errors
from ..api import extensions as ext

log = logging.getLogger("admission.webhooks")


def apply_json_patch(doc: Any, patch: list[dict]) -> Any:
    """Minimal RFC 6902: add / remove / replace over dicts and lists
    ("-" appends). Unknown ops or bad paths raise ValueError — a
    webhook's malformed patch must reject the request, not corrupt the
    object."""
    import copy
    doc = copy.deepcopy(doc)
    for op in patch:
        try:
            _apply_one(doc, op)
        except (IndexError, KeyError, TypeError) as e:
            # The documented contract is ValueError on ANY bad patch —
            # a stale list index must reject the request, not 500.
            raise ValueError(f"bad patch op {op!r}: {e}") from None
    return doc


def _apply_one(doc: Any, op: dict) -> None:
    action = op.get("op")
    path = op.get("path", "")
    if not path.startswith("/"):
        raise ValueError(f"bad path {path!r}")
    keys = [p.replace("~1", "/").replace("~0", "~")
            for p in path[1:].split("/")]
    cur: Any = doc
    for k in keys[:-1]:
        cur = _step(cur, k)
    last = keys[-1]
    if action == "add":
        if isinstance(cur, list):
            idx = len(cur) if last == "-" else int(last)
            cur.insert(idx, op["value"])
        elif isinstance(cur, dict):
            cur[last] = op["value"]
        else:
            raise ValueError(f"cannot add into {type(cur).__name__}")
    elif action == "replace":
        if isinstance(cur, list):
            cur[int(last)] = op["value"]
        elif isinstance(cur, dict):
            if last not in cur:
                raise ValueError(f"replace of missing key {path!r}")
            cur[last] = op["value"]
        else:
            raise ValueError(f"cannot replace in {type(cur).__name__}")
    elif action == "remove":
        if isinstance(cur, list):
            del cur[int(last)]
        elif isinstance(cur, dict):
            if last not in cur:
                raise ValueError(f"remove of missing key {path!r}")
            del cur[last]
        else:
            raise ValueError(f"cannot remove from {type(cur).__name__}")
    elif action == "test":
        have = _step(cur, last) if last else cur
        if have != op.get("value"):
            raise ValueError(
                f"test failed at {path!r}: {have!r} != {op.get('value')!r}")
    elif action in ("move", "copy"):
        frm = op.get("from", "")
        if not frm.startswith("/"):
            raise ValueError(f"bad from path {frm!r}")
        fkeys = [p.replace("~1", "/").replace("~0", "~")
                 for p in frm[1:].split("/")]
        src = doc
        for k in fkeys[:-1]:
            src = _step(src, k)
        import copy as _copy
        value = _copy.deepcopy(_step(src, fkeys[-1]))  # no aliasing
        if action == "move":
            _apply_one(doc, {"op": "remove", "path": frm})
        _apply_one(doc, {"op": "add", "path": path, "value": value})
    else:
        raise ValueError(f"unsupported op {action!r}")


def _step(cur: Any, key: str) -> Any:
    if isinstance(cur, list):
        return cur[int(key)]
    if isinstance(cur, dict):
        if key not in cur:
            raise ValueError(f"missing path segment {key!r}")
        return cur[key]
    raise ValueError(f"cannot traverse {type(cur).__name__}")


class WebhookDispatcher:
    """Lists webhook configs from the registry (TTL-cached) and calls
    matching hooks for an (operation, resource) write."""

    def __init__(self, registry, ttl: float = 1.0):
        self.registry = registry
        self.ttl = ttl
        self._cache: tuple[float, list, list] = (float("-inf"), [], [])
        self._session = None
        #: ca_bundle PEM -> SSLContext (see _hook_ssl).
        self._ssl_cache: dict[str, Any] = {}

    def invalidate(self) -> None:
        """Drop the TTL snapshot — the server calls this when a webhook
        configuration itself is written, so `create config; create pod`
        inside one TTL window still intercepts the pod. SSL contexts
        go too: rotated/deleted ca_bundles must not pin stale trust
        (and the dict stays bounded by the live config set)."""
        self._cache = (float("-inf"), [], [])
        self._ssl_cache.clear()

    def _configs(self) -> tuple[list, list]:
        now = time.monotonic()
        at, mut, val = self._cache
        if now - at < self.ttl:
            return mut, val
        try:
            mut, _ = self.registry.list("mutatingwebhookconfigurations")
            val, _ = self.registry.list("validatingwebhookconfigurations")
        except errors.StatusError:
            mut, val = [], []
        self._cache = (now, mut, val)
        return mut, val

    @staticmethod
    def _matches(hook: ext.Webhook, operation: str, plural: str) -> bool:
        for rule in hook.rules:
            ops = rule.operations or ["*"]
            if "*" not in ops and operation not in ops:
                continue
            if "*" in rule.resources or plural in rule.resources:
                return True
        return False

    def has_hooks(self, operation: str, plural: str) -> bool:
        mut, val = self._configs()
        return any(self._matches(h, operation, plural)
                   for cfg in mut + val for h in cfg.webhooks)

    def has_validating(self, operation: str, plural: str) -> bool:
        """Gate for the dry-run admission preview: the extra in-tree
        pass is only worth paying when a validating hook will actually
        see its output."""
        _, val = self._configs()
        return any(self._matches(h, operation, plural)
                   for cfg in val for h in cfg.webhooks)

    def _hook_ssl(self, hook: ext.Webhook):
        """Per-hook TLS trust: ``ca_bundle`` (PEM) verifies the hook's
        serving cert (reference clientConfig.caBundle); without one,
        the system trust store applies. Contexts are cached by bundle
        content — building an SSLContext per call is milliseconds."""
        if not hook.url.startswith("https://") or not hook.ca_bundle:
            return None
        ctx = self._ssl_cache.get(hook.ca_bundle)
        if ctx is None:
            import ssl
            ctx = ssl.create_default_context(cadata=hook.ca_bundle)
            self._ssl_cache[hook.ca_bundle] = ctx
        return ctx

    async def _call(self, hook: ext.Webhook, review: dict) -> Optional[dict]:
        """One hook round trip; None means unreachable/invalid (the
        failure_policy decides what that means)."""
        import aiohttp
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        ssl_ctx = self._hook_ssl(hook)
        try:
            async with self._session.post(
                    hook.url, json=review,
                    **({"ssl": ssl_ctx} if ssl_ctx is not None else {}),
                    timeout=aiohttp.ClientTimeout(
                        total=hook.timeout_seconds)) as resp:
                if resp.status != 200:
                    return None
                body = await resp.json()
            return body.get("response") or None
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as e:
            log.warning("webhook %s (%s) failed: %s", hook.name, hook.url, e)
            return None

    def _review(self, operation: str, plural: str, namespace: str,
                name: str, obj: Optional[dict],
                old: Optional[dict]) -> dict:
        return {"kind": "AdmissionReview",
                "api_version": ext.ADMISSION_V1,
                "request": {"uid": str(uuid.uuid4()),
                            "operation": operation,
                            "resource": plural,
                            "namespace": namespace,
                            "name": name,
                            "object": obj,
                            "old_object": old}}

    @staticmethod
    def _enforce(hook: ext.Webhook, resp: Optional[dict]) -> bool:
        """Shared unreachable/denied policy: returns False when an
        Ignore-policy hook should simply be skipped; raises on denial
        or on an unreachable Fail-policy hook."""
        if resp is None:
            if hook.failure_policy == ext.FAILURE_POLICY_IGNORE:
                return False
            raise errors.ForbiddenError(
                f"admission webhook {hook.name!r} unreachable "
                f"(failurePolicy=Fail)")
        if not resp.get("allowed", False):
            msg = (resp.get("status") or {}).get("message", "denied")
            raise errors.ForbiddenError(
                f"admission webhook {hook.name!r} denied the "
                f"request: {msg}")
        return True

    async def run_mutating(self, operation: str, plural: str,
                           namespace: str, name: str, obj: dict,
                           old: Optional[dict] = None) -> dict:
        """Run matching mutating hooks in config order; returns the
        (possibly patched) object dict. Raises ForbiddenError on denial
        or on unreachable Fail-policy hooks."""
        mut, _ = self._configs()
        for cfg in mut:
            for hook in cfg.webhooks:
                if not self._matches(hook, operation, plural):
                    continue
                resp = await self._call(hook, self._review(
                    operation, plural, namespace, name, obj, old))
                if not self._enforce(hook, resp):
                    continue
                patch_b64 = resp.get("patch")
                if patch_b64:
                    try:
                        patch = json.loads(base64.b64decode(patch_b64))
                        obj = apply_json_patch(obj, patch)
                    except (ValueError, json.JSONDecodeError) as e:
                        raise errors.ForbiddenError(
                            f"admission webhook {hook.name!r} returned a "
                            f"bad patch: {e}") from None
        return obj

    async def run_validating(self, operation: str, plural: str,
                             namespace: str, name: str,
                             obj: Optional[dict],
                             old: Optional[dict] = None) -> None:
        """Run matching validating hooks CONCURRENTLY (they cannot
        mutate, so order is irrelevant — reference does the same)."""
        _, val = self._configs()
        hooks = [h for cfg in val for h in cfg.webhooks
                 if self._matches(h, operation, plural)]
        if not hooks:
            return
        review = self._review(operation, plural, namespace, name, obj, old)
        results = await asyncio.gather(
            *(self._call(h, review) for h in hooks))
        for hook, resp in zip(hooks, results):
            self._enforce(hook, resp)

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
