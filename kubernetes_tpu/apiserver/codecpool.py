"""Process-pool JSON codec offload for the apiserver event loop.

Reference motivation: the apiserver negotiates protobuf on the hot path
because serialization dominates control-plane CPU at density scale
(``apimachinery/pkg/runtime/serializer/protobuf``). This repo stays on
JSON (``perf/decode_share.py`` is the go/no-go instrument for a binary
codec), but the event loop must not burn milliseconds serializing a
30k-pod LIST or decoding a 512-item batchCreate body while binds queue
behind it. Behind the ``ApiServerCodecOffload`` gate, encode-cache
*misses* on LIST assembly and decode of large request bodies dispatch
to a ``concurrent.futures.ProcessPoolExecutor``; everything below the
size thresholds stays inline — for small objects the pickle round trip
costs more than the ``json.dumps`` it would save.

Host sizing, stated: the pool runs ``cpu_count - 1`` workers. On a
single-core host (the bench VM) that is zero spare cores, so the pool
stays INLINE even with the gate on — offloading to a process competing
for the same core is pure IPC overhead. The ``codec_pool_*`` metrics
make the fallback visible: ``codec_pool_inline_total`` counts work the
thresholds or host kept on the loop, ``codec_pool_submits_total``
counts real offloads. ``KTPU_CODEC_POOL_WORKERS`` overrides the sizing
(tests force 1 to exercise the true pool path on any host).

Correctness: pool results re-enter the serialize-once cache through
:meth:`EncodeCache.finish_async_encode` with a generation token taken
at dispatch — a write landing while an encode is in flight invalidates
the key and bumps its generation, so the completed future can never
resurrect a stale entry (see tests/unit/test_codecpool.py).
"""
from __future__ import annotations

import json
import os
from typing import Optional

from ..metrics.registry import Counter, Gauge

CODEC_POOL_SUBMITS = Counter(
    "codec_pool_submits_total",
    "Codec jobs dispatched to the process pool, by operation",
    labels=("op",))

CODEC_POOL_INLINE = Counter(
    "codec_pool_inline_total",
    "Codec jobs kept on the event loop (below threshold / no spare "
    "cores / pool down), by operation and reason",
    labels=("op", "reason"))

CODEC_POOL_ITEMS = Counter(
    "codec_pool_items_total",
    "Objects encoded/decoded through the pool, by operation",
    labels=("op",))

CODEC_POOL_WORKERS = Gauge(
    "codec_pool_workers", "Worker processes the codec pool runs (0 = inline)")

CODEC_POOL_STALE_DROPS = Counter(
    "codec_pool_stale_drops_total",
    "Pool encode results dropped because a write invalidated the key "
    "while the encode was in flight")


def _encode_many(values: list[dict]) -> list[bytes]:
    """Worker half of the encode offload: wire bytes per value. Module
    level so it pickles by reference, not by closure."""
    dumps = json.dumps
    return [dumps(v, separators=(",", ":")).encode() for v in values]


def _encode_many_compact(values: list[dict]) -> list[bytes]:
    """Compact-codec worker twin (CompactWireCodec LIST misses)."""
    from ..util.compactcodec import encode_many
    return encode_many(values)


def _decode_bytes(raw: bytes):
    return json.loads(raw)


def _decode_bytes_compact(raw: bytes):
    """Compact-codec decode twin (CompactWireCodec write bodies)."""
    from ..util.compactcodec import decode_body
    return decode_body(raw)


def pool_workers() -> int:
    """Worker count for this host: every core but one (the event loop
    keeps its own), overridable via KTPU_CODEC_POOL_WORKERS. 0 = the
    pool stays inline."""
    env = os.environ.get("KTPU_CODEC_POOL_WORKERS", "")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return max(0, (os.cpu_count() or 1) - 1)


class CodecPool:
    """Lazy process pool + thresholds; safe to construct eagerly (no
    processes exist until the first over-threshold job).

    Thresholds: ``min_encode_items`` objects per LIST-assembly batch,
    ``min_decode_bytes`` per request body. Both err toward inline —
    the offload pays one pickle each way, so it must buy back at least
    a few hundred microseconds of loop time to be worth dispatching.
    """

    def __init__(self, workers: Optional[int] = None,
                 min_encode_items: int = 64,
                 min_decode_bytes: int = 32 * 1024,
                 encode_chunk: int = 512):
        self.workers = pool_workers() if workers is None else workers
        self.min_encode_items = min_encode_items
        self.min_decode_bytes = min_decode_bytes
        #: Objects per pool task — several tasks per big LIST so M
        #: workers overlap, without per-object dispatch overhead.
        self.encode_chunk = encode_chunk
        self._executor = None
        self._broken = False
        CODEC_POOL_WORKERS.set(float(self.workers))

    @property
    def active(self) -> bool:
        """True when jobs can actually leave the event loop."""
        return self.workers > 0 and not self._broken

    def _get_executor(self):
        if self._executor is None:
            from concurrent.futures import ProcessPoolExecutor
            self._executor = ProcessPoolExecutor(max_workers=self.workers)
        return self._executor

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None

    async def encode_values(self, values: list[dict],
                            codec: str = "json") -> list[bytes]:
        """Wire-encode ``values`` — through the pool when the batch is
        big enough and a worker exists, inline otherwise. Order is
        preserved; output is byte-identical to the inline path
        (``json.dumps(v, separators=(",", ":"))``, or the compact
        codec's ``encode_obj`` when ``codec="compact"``)."""
        encode = _encode_many if codec == "json" else _encode_many_compact
        if not values:
            return []
        if not self.active:
            CODEC_POOL_INLINE.inc(op="encode", reason="no-workers")
            return encode(values)
        if len(values) < self.min_encode_items:
            CODEC_POOL_INLINE.inc(op="encode", reason="below-threshold")
            return encode(values)
        import asyncio
        loop = asyncio.get_running_loop()
        chunks = [values[i:i + self.encode_chunk]
                  for i in range(0, len(values), self.encode_chunk)]
        try:
            futs = [loop.run_in_executor(self._get_executor(),
                                         encode, c) for c in chunks]
            CODEC_POOL_SUBMITS.inc(len(futs), op="encode")
            CODEC_POOL_ITEMS.inc(len(values), op="encode")
            outs = await asyncio.gather(*futs)
        except Exception:  # noqa: BLE001 — a dead pool degrades to inline
            self._broken = True
            CODEC_POOL_INLINE.inc(op="encode", reason="pool-error")
            return encode(values)
        return [b for chunk in outs for b in chunk]

    async def decode_body(self, raw: bytes, codec: str = "json",
                          op: str = "other"):
        """Request-body decode — pooled when the body is large enough,
        inline otherwise. Raises the same decode errors the inline
        path would (``json.JSONDecodeError``, or the compact codec's
        ``ValueError`` family when ``codec="compact"``). ``op`` names
        the verb: inline decodes route through the per-op decode_share
        seams so by_op attribution survives the offload gate being
        stacked (pool decodes run in worker processes, outside any
        profile — nothing to attribute there)."""
        from ..util.compactcodec import decode_request
        if not self.active or len(raw) < self.min_decode_bytes:
            reason = ("no-workers" if not self.active
                      else "below-threshold")
            CODEC_POOL_INLINE.inc(op="decode", reason=reason)
            return decode_request(raw, codec, op)
        decode = _decode_bytes if codec == "json" else _decode_bytes_compact
        import asyncio
        loop = asyncio.get_running_loop()
        try:
            CODEC_POOL_SUBMITS.inc(op="decode")
            CODEC_POOL_ITEMS.inc(op="decode")
            return await loop.run_in_executor(self._get_executor(),
                                              decode, raw)
        except ValueError:
            # json.JSONDecodeError and the msgpack/framing errors are
            # all ValueErrors — the caller's 400 mapping, not a pool
            # failure.
            raise
        except Exception:  # noqa: BLE001 — a dead pool degrades to inline
            self._broken = True
            CODEC_POOL_INLINE.inc(op="decode", reason="pool-error")
            return decode_request(raw, codec, op)
