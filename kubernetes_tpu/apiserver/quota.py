"""Quota accounting shared by admission and the quota controller.

Reference: ``pkg/quota`` evaluators — one definition of a pod's
footprint, consumed by both ``plugin/pkg/admission/resourcequota``
(synchronous enforcement) and ``pkg/controller/resourcequota``
(usage recalculation / drift healing).
"""
from __future__ import annotations

from ..api import types as t


def pod_usage(pod: t.Pod) -> dict[str, float]:
    """Resource footprint of one pod (terminal pods are free)."""
    if pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED):
        return {}
    use = {t.RESOURCE_PODS: 1.0}
    for c in pod.spec.containers:
        for res, qty in c.resources.requests.items():
            use[res] = use.get(res, 0.0) + t.parse_quantity(qty)
    chips = t.pod_tpu_chip_count(pod)
    if chips:
        use[t.RESOURCE_TPU] = use.get(t.RESOURCE_TPU, 0.0) + chips
    return use
