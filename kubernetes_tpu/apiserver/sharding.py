"""Resource-group sharded apiserver workers.

Reference motivation: Kant-style horizontal control-plane scaling —
the single apiserver event loop is the measured wall at density scale
(BENCH r01→r05: ~336-345 pods/s on the 30k REST arm while the
scheduler does ~950 in-process). Behind the ``ApiServerSharding``
gate, non-watch resource requests are partitioned by RESOURCE GROUP
and dispatched to per-group worker event loops over the shared
MVCC/WAL store:

- ``pods``      — pods (binds, batch binds, evictions ride along)
- ``nodes``     — nodes + leases (heartbeat traffic)
- ``queueing``  — podgroups, clusterqueues, localqueues
- ``events``    — events (the classic noisy neighbor)
- everything else stays inline on the router loop.

The router (the aiohttp server loop) keeps the ENTIRE external
surface: authn/authz, audit, the max-in-flight limiter, redirects,
metrics, and every watch stream run exactly where they always did —
only the verb handler body moves to the group's worker. Request bodies
are pre-read on the router loop before dispatch (aiohttp caches the
bytes), so handlers never touch the connection from a foreign thread.

Ordering: all mutations of one resource group run through ONE worker,
so per-key orderings observable today are preserved; cross-group
ordering was never promised beyond MVCC revision arbitration, which
the store's process-wide lock provides unchanged. The WAL, the encode
cache, watch delivery (``call_soon_threadsafe``), and the metrics
registry are already foreign-thread-safe — sharding leans on exactly
those seams.

Two execution modes:

- ``thread`` (default): one daemon thread + event loop per shard —
  real loop decoupling (a 30k LIST on the pods worker no longer
  delays node heartbeats or election traffic on the router).
- ``inline``: per-request tasks on the router loop, tagged per shard.
  Used automatically while TPU_SAN is armed — the interleaving
  explorer owns exactly one loop, and foreign threads would break
  schedule replay — so ``hack/race.sh`` explores the sharded
  dispatch path deterministically.

Single-core honesty: on a 1-CPU host thread mode buys no parallelism
(the GIL serializes the workers); what it buys is isolation of
head-of-line blocking between groups. The measured throughput wins on
such hosts come from the watch fan-out batching and codec paths, not
from sharding — see README "Control-plane scale-out".
"""
from __future__ import annotations

import asyncio
import threading
from typing import Optional

from ..metrics.registry import Counter, Gauge

SHARD_REQUESTS = Counter(
    "apiserver_shard_requests_total",
    "Requests dispatched to apiserver shard workers, by shard",
    labels=("shard",))

SHARD_INLINE = Counter(
    "apiserver_shard_inline_total",
    "Resource requests served on the router loop (unsharded group, "
    "watch streams, or sharding off)")

SHARD_DEPTH = Gauge(
    "apiserver_shard_inflight",
    "Requests currently in flight per shard worker",
    labels=("shard",))

#: plural -> shard name. Unlisted plurals stay on the router loop.
RESOURCE_GROUPS = {
    "pods": "pods",
    "nodes": "nodes",
    "leases": "nodes",
    "podgroups": "queueing",
    "clusterqueues": "queueing",
    "localqueues": "queueing",
    "events": "events",
}

SHARD_NAMES = ("pods", "nodes", "queueing", "events")


def shard_for(plural: str) -> Optional[str]:
    """Shard name for a plural (batch action suffixes already
    stripped by the caller), or None for router-inline resources."""
    return RESOURCE_GROUPS.get(plural)


class _ShardWorker:
    """One shard: a daemon thread running its own event loop."""

    def __init__(self, name: str):
        self.name = name
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name=f"apiserver-shard-{name}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        try:
            self.loop.run_forever()
        finally:
            # The worker closes its OWN loop: a stop() whose join
            # timed out must not leak the loop for the process
            # lifetime (and no other thread can safely close it).
            self.loop.close()

    async def dispatch(self, coro):
        """Run ``coro`` on this shard's loop; awaits (and propagates
        exceptions/cancellation) from the caller's loop."""
        cfut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return await asyncio.wrap_future(cfut)
        except asyncio.CancelledError:
            cfut.cancel()
            raise

    def stop(self, join_timeout: float = 2.0) -> None:
        def _shutdown():
            # Cancel, DRAIN, then stop: stopping the loop in the same
            # callback as the cancellations would return run_forever
            # before any cancelled handler ran its except/finally
            # blocks (leaking e.g. the codec path's encode-token
            # cleanup) and strand the router's dispatch await.
            from ..util.tasks import spawn

            async def _drain():
                tasks = [t for t in asyncio.all_tasks(self.loop)
                         if t is not asyncio.current_task()]
                for t in tasks:
                    t.cancel()
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*tasks, return_exceptions=True),
                        1.0)
                except asyncio.TimeoutError:
                    pass  # wedged handler: stop anyway, join bounds us
                self.loop.stop()
            spawn(_drain(), name=f"shard-{self.name}-drain")
        try:
            self.loop.call_soon_threadsafe(_shutdown)
            self._thread.join(timeout=join_timeout)
        except RuntimeError:
            pass  # loop already closed by its own thread


class ShardPool:
    """The apiserver's shard workers; built lazily on first dispatch
    so a gated-off server never spawns a thread.

    ``mode``: ``"thread"`` | ``"inline"`` | ``"auto"`` (thread unless
    TPU_SAN is armed — deterministic exploration owns the one loop).
    """

    def __init__(self, mode: str = "auto"):
        if mode == "auto":
            from ..analysis import invariants
            import os
            # Inline when (a) tpusan owns the one loop — foreign
            # threads would break deterministic schedule replay — or
            # (b) the host has no spare core: thread workers on a
            # single CPU pay GIL handoffs + cross-loop hops for zero
            # parallelism (measured: 200n/2k REST arm DROPPED ~25%
            # with thread workers on the 1-core bench VM).
            single_core = (os.cpu_count() or 1) < 2
            mode = ("inline" if (invariants.SANITIZER is not None
                                 or os.environ.get("TPU_SAN")
                                 or single_core)
                    else "thread")
        self.mode = mode
        self._workers: dict[str, _ShardWorker] = {}
        self._lock = threading.Lock()
        #: Optional ``fn(name, loop)`` called once per spawned worker
        #: (the apiserver hangs its loop-lag probe here).
        self.on_worker = None

    def _worker(self, shard: str) -> _ShardWorker:
        w = self._workers.get(shard)
        if w is None:
            with self._lock:
                w = self._workers.get(shard)
                if w is None:
                    w = _ShardWorker(shard)
                    self._workers[shard] = w
                    if self.on_worker is not None:
                        self.on_worker(shard, w.loop)
        return w

    async def dispatch(self, shard: str, coro):
        """Run ``coro`` under shard accounting. Thread mode hops to the
        shard's loop; inline mode runs it as a task on the caller's
        loop (a real task boundary, so tpusan explores the reordering
        the thread mode would produce)."""
        SHARD_REQUESTS.inc(shard=shard)
        SHARD_DEPTH.inc(shard=shard)
        try:
            if self.mode == "thread":
                return await self._worker(shard).dispatch(coro)
            task = asyncio.get_running_loop().create_task(coro)
            try:
                return await task
            except asyncio.CancelledError:
                task.cancel()
                raise
        finally:
            SHARD_DEPTH.dec(shard=shard)

    def loops(self) -> dict[str, asyncio.AbstractEventLoop]:
        """Live shard loops (thread mode), for the loop-lag probes."""
        if self.mode != "thread":
            return {}
        return {name: w.loop for name, w in self._workers.items()}

    def stop(self) -> None:
        with self._lock:
            workers, self._workers = dict(self._workers), {}
        for w in workers.values():
            w.stop()
