"""Admission chain — mutate-then-validate hooks on every write.

Reference: ``staging/src/k8s.io/apiserver/pkg/admission`` invoked from
``endpoints/handlers/create.go:37`` plus the in-tree plugins in
``plugin/pkg/admission/`` — notably the fork's ``resourcev2`` plugin
(``admission.go:32-118``) which rewrites legacy count-style GPU limits
into the per-device resource model. :class:`TpuResourceDefaulter` is
the TPU analog of that compat shim.
"""
from __future__ import annotations

import uuid
from typing import TYPE_CHECKING, Optional

from ..analysis import loopsan
from ..api import errors, types as t
from ..api.meta import TypedObject

if TYPE_CHECKING:
    from .registry import Registry, ResourceSpec

#: Plurals the chain's plugins only ever READ while admitting a write
#: (policy/config objects: namespaces, priority classes, quota-free
#: lookups...). The registry memoizes GET/LIST results for exactly
#: these — and nothing else — for the duration of one batch chunk's
#: admission pass (``Registry.batch_admission_context``), so a
#: 64-item chunk pays each lookup once instead of 64 times. The quota
#: charge path (``resourcequotas``) is deliberately absent: its
#: read-CAS-retry loop must see fresh state on every attempt. A write
#: to any of these plurals (NamespaceLifecycle auto-creating a
#: namespace mid-chunk) invalidates that plural's memo entries.
BATCH_MEMO_PLURALS = frozenset({
    "namespaces", "priorityclasses", "serviceaccounts", "limitranges",
    "podsecuritypolicies", "storageclasses", "localqueues",
    "clusterqueues",
})


class AdmissionPlugin:
    name = "plugin"

    def admit(self, op: str, spec: "ResourceSpec", obj: TypedObject,
              old: Optional[TypedObject]) -> TypedObject:
        """Mutate phase: return the (possibly modified) object."""
        return obj

    def validate(self, op: str, spec: "ResourceSpec", obj: TypedObject,
                 old: Optional[TypedObject]) -> None:
        """Validate phase: raise to reject."""


class AdmissionChain:
    def __init__(self, plugins: Optional[list[AdmissionPlugin]] = None):
        self.plugins = plugins or []

    def admit(self, op: str, spec: "ResourceSpec", obj: TypedObject,
              old: Optional[TypedObject],
              dry_run: bool = False) -> TypedObject:
        """``dry_run=True`` skips plugins whose validate phase has
        durable side effects (``charges_state`` — the quota charge):
        a dry-run pass must never double-charge against the real one."""
        with loopsan.seam("admission.pass"):
            for p in self.plugins:
                obj = p.admit(op, spec, obj, old)
            for p in self.plugins:
                if dry_run and getattr(p, "charges_state", False):
                    continue
                p.validate(op, spec, obj, old)
            return obj


class TpuResourceDefaulter(AdmissionPlugin):
    """Rewrite count-style ``google.com/tpu`` container limits into a
    named :class:`~kubernetes_tpu.api.types.PodTpuRequest` + container
    reference, deleting the raw limit.

    Reference: ``plugin/pkg/admission/resourcev2/admission.go:51-118``
    (``Admit`` + ``newExtendedResource``) — same old->new compat shim,
    UUID-suffixed claim name and all.
    """

    name = "TpuResourceDefaulter"

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        pod: t.Pod = obj
        for c in pod.spec.containers:
            n = c.resources.limits.pop(t.RESOURCE_TPU, None) or \
                c.resources.requests.pop(t.RESOURCE_TPU, None)
            if not n:
                continue
            claim_name = f"tpu-{uuid.uuid4().hex[:8]}"
            pod.spec.tpu_resources.append(
                t.PodTpuRequest(name=claim_name, chips=int(n)))
            c.tpu_requests.append(claim_name)
            c.resources.limits.pop(t.RESOURCE_TPU, None)
            c.resources.requests.pop(t.RESOURCE_TPU, None)
        return pod


class NamespaceLifecycle(AdmissionPlugin):
    """Reject creates in missing or terminating namespaces; auto-create
    the default namespace. Reference: ``plugin/pkg/admission/namespace``."""

    name = "NamespaceLifecycle"
    _EXEMPT = {"Namespace", "Node", "PriorityClass", "Lease", "Event"}

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def validate(self, op, spec, obj, old):
        if op != "CREATE" or spec.kind in self._EXEMPT or not spec.namespaced:
            return
        ns_name = obj.metadata.namespace
        try:
            ns = self.registry.get("namespaces", "", ns_name)
        except errors.NotFoundError:
            if ns_name == "default":
                self.registry.create(t.Namespace(
                    metadata=t.ObjectMeta(name="default")))  # type: ignore[attr-defined]
                return
            raise errors.ForbiddenError(f"namespace {ns_name!r} not found") from None
        if ns.status.phase == t.NS_TERMINATING or ns.metadata.deletion_timestamp:
            raise errors.ForbiddenError(
                f"namespace {ns_name!r} is terminating; cannot create {spec.kind}")


class PriorityResolver(AdmissionPlugin):
    """Resolve priority_class_name -> numeric priority at admission.

    Reference: priority admission in the scheduler ecosystem; pods carry
    resolved ``spec.priority`` so the scheduler never does lookups.
    """

    name = "PriorityResolver"

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        pod: t.Pod = obj
        if pod.spec.priority_class_name and pod.spec.priority is None:
            try:
                pc = self.registry.get("priorityclasses", "", pod.spec.priority_class_name)
                pod.spec.priority = pc.value
            except errors.NotFoundError:
                raise errors.BadRequestError(
                    f"priority class {pod.spec.priority_class_name!r} not found") from None
        if pod.spec.priority is None:
            pod.spec.priority = 0
        return pod


class ResourceQuotaPlugin(AdmissionPlugin):
    """Enforce per-namespace hard quotas on pod create.

    Reference: ``plugin/pkg/admission/resourcequota`` + ``pkg/quota``.
    Redesigned away from the round-1 O(pods-in-namespace) recount per
    create: admission *charges* ``quota.status.used`` with a CAS update
    (exactly the reference's synchronous status charge), and the quota
    controller recalculates usage level-triggered to heal drift
    (terminated pods, failed creates after the charge, force deletes).
    Cost per pod create is O(quotas in namespace), not O(pods).
    """

    name = "ResourceQuota"
    CAS_RETRIES = 10
    #: validate() CHARGES quota status — skipped under dry-run
    #: admission so a preview pass cannot double-charge.
    charges_state = True

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def validate(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return
        pod: t.Pod = obj
        ns = pod.metadata.namespace
        quotas, _ = self.registry.list("resourcequotas", ns)
        if not quotas:
            return
        from .quota import pod_usage
        want = pod_usage(pod)
        charged: list[str] = []
        for q in quotas:
            try:
                self._charge(ns, q.metadata.name, want)
                charged.append(q.metadata.name)
            except errors.StatusError:
                # Roll back quotas charged earlier in the loop so a
                # rejected pod doesn't leave used inflated until the
                # quota controller's next full recount.
                negative = {res: -amt for res, amt in want.items()}
                for name in charged:
                    try:
                        self._charge(ns, name, negative)
                    except errors.StatusError:
                        pass  # controller resync heals residual drift
                raise

    def _charge(self, ns: str, quota_name: str, want: dict) -> None:
        for _ in range(self.CAS_RETRIES):
            try:
                cur = self.registry.get("resourcequotas", ns, quota_name)
            except errors.NotFoundError:
                return
            tracked = {res: amt for res, amt in want.items()
                       if res in cur.spec.hard}
            if not tracked:
                return
            used = dict(cur.status.used)
            for res, amt in tracked.items():
                hard = t.parse_quantity(cur.spec.hard[res])
                if used.get(res, 0.0) + amt > hard:
                    raise errors.ForbiddenError(
                        f"exceeded quota {quota_name!r}: requested "
                        f"{res}={amt:g}, used {used.get(res, 0.0):g}, "
                        f"hard limit {hard:g}")
                # Clamp: a rollback racing the controller's recount must
                # not drive usage negative.
                used[res] = max(0.0, used.get(res, 0.0) + amt)
            cur.status.used = used
            cur.status.hard = dict(cur.spec.hard)
            try:
                self.registry.update(cur, subresource="status")
                return
            except errors.ConflictError:
                continue  # concurrent charge: re-read and retry
        raise errors.ConflictError(
            f"quota {quota_name!r}: too much contention charging usage")


class ServiceAccountPlugin(AdmissionPlugin):
    """Default pods to the "default" ServiceAccount and mount its token
    secret (reference: ``plugin/pkg/admission/serviceaccount`` — it also
    rejects pods whose SA does not exist; here a missing SA just skips
    the mount, because the default SA is created asynchronously by the
    controller and workload pods must not race it)."""

    name = "ServiceAccount"
    MOUNT_PATH = "/var/run/secrets/kubernetes-tpu/serviceaccount"
    VOLUME = "ktpu-sa-token"

    def __init__(self, registry):
        self.registry = registry

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        pod: t.Pod = obj
        if not pod.spec.service_account_name:
            pod.spec.service_account_name = "default"
        try:
            sa = self.registry.get("serviceaccounts",
                                   pod.metadata.namespace,
                                   pod.spec.service_account_name)
        except errors.NotFoundError:
            return obj
        if not sa.automount_token or not sa.secrets:
            return obj
        if any(v.name == self.VOLUME for v in pod.spec.volumes):
            return obj
        pod.spec.volumes.append(t.Volume(
            name=self.VOLUME,
            secret=t.SecretVolume(secret_name=sa.secrets[0])))
        for c in pod.spec.containers + pod.spec.init_containers:
            if not any(m.name == self.VOLUME for m in c.volume_mounts):
                c.volume_mounts.append(t.VolumeMount(
                    name=self.VOLUME, mount_path=self.MOUNT_PATH,
                    read_only=True))
        return obj


class LimitRanger(AdmissionPlugin):
    """Default and bound container resources from the namespace's
    LimitRange objects (reference: ``plugin/pkg/admission/limitranger``).

    Mutate: a container missing a request/limit for a resource named in
    ``default_request``/``default`` gets it filled in. Validate: every
    container request/limit must sit within [min, max]. Runs BEFORE
    ResourceQuota in the chain so quota charges see defaulted values
    (same ordering as the reference's plugin list)."""

    name = "LimitRanger"

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def _items(self, ns: str) -> list[t.LimitRangeItem]:
        try:
            ranges, _ = self.registry.list("limitranges", ns)
        except errors.StatusError:
            return []
        return [item for lr in ranges for item in lr.spec.limits
                if item.type == "Container"]

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        items = self._items(obj.metadata.namespace)
        if not items:
            return obj
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            for item in items:
                for res, val in item.default_request.items():
                    c.resources.requests.setdefault(res, val)
                for res, val in item.default.items():
                    c.resources.limits.setdefault(res, val)
                    # Reference: a defaulted limit also backs a missing
                    # request so the pod stays Burstable, not invalid.
                    c.resources.requests.setdefault(res, val)
        return obj

    def validate(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return
        items = self._items(obj.metadata.namespace)
        if not items:
            return
        for c in list(obj.spec.containers) + list(obj.spec.init_containers):
            for item in items:
                # A bound on an ABSENT value must reject, or the policy
                # is a no-op for containers that just omit the field
                # (reference minConstraint "No request is specified" /
                # maxConstraint "No limit is specified"). admit() ran
                # first, so LimitRange defaults have already filled in
                # what they could.
                for res, lo in item.min.items():
                    got = c.resources.requests.get(res)
                    if got is None:
                        raise errors.ForbiddenError(
                            f"container {c.name!r}: no {res} request, but "
                            f"LimitRange sets min {lo}")
                    if t.parse_quantity(got) < t.parse_quantity(lo):
                        raise errors.ForbiddenError(
                            f"container {c.name!r}: {res} request {got} "
                            f"is below LimitRange min {lo}")
                    lim = c.resources.limits.get(res)
                    if lim is not None and t.parse_quantity(lim) < \
                            t.parse_quantity(lo):
                        raise errors.ForbiddenError(
                            f"container {c.name!r}: {res} limit {lim} "
                            f"is below LimitRange min {lo}")
                for res, hi in item.max.items():
                    got = c.resources.limits.get(res)
                    if got is None:
                        raise errors.ForbiddenError(
                            f"container {c.name!r}: no {res} limit, but "
                            f"LimitRange sets max {hi}")
                    if t.parse_quantity(got) > t.parse_quantity(hi):
                        raise errors.ForbiddenError(
                            f"container {c.name!r}: {res} limit {got} "
                            f"exceeds LimitRange max {hi}")
                    req = c.resources.requests.get(res)
                    if req is not None and t.parse_quantity(req) > \
                            t.parse_quantity(hi):
                        raise errors.ForbiddenError(
                            f"container {c.name!r}: {res} request {req} "
                            f"exceeds LimitRange max {hi}")


class PodSecurity(AdmissionPlugin):
    """PSP-lite gate (reference: ``pkg/security/podsecuritypolicy/``
    admission). Zero-cost while no PodSecurityPolicy objects exist;
    once any do, every pod CREATE must satisfy at least one policy:

    - ``run_as_user_rule``: RunAsAny / MustRunAs (the pod's effective
      uid — container override else pod default — must sit inside one
      of the policy's ranges, and must be SET) / MustRunAsNonRoot
      (set and nonzero).
    - ``allow_host_paths`` / ``read_only_host_paths``: whether hostPath
      volumes are admitted, and whether every container mount of one
      must be read_only.

    Validate-only (no mutation): matching the reference's reject-at-
    admission behavior for out-of-policy pods."""

    name = "PodSecurity"

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def validate(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return
        try:
            policies, _ = self.registry.list("podsecuritypolicies", "")
        except errors.StatusError:
            return
        if not policies:
            return
        reasons = []
        for psp in sorted(policies, key=lambda p: p.metadata.name):
            why = self._violates(obj, psp)
            if why is None:
                return  # satisfied by this policy
            reasons.append(f"{psp.metadata.name}: {why}")
        raise errors.ForbiddenError(
            f"pod {obj.metadata.name!r} rejected by every "
            f"PodSecurityPolicy ({'; '.join(reasons)})")

    @staticmethod
    def _effective_uid(pod: t.Pod, container: t.Container):
        if container.security_context is not None \
                and container.security_context.run_as_user is not None:
            return container.security_context.run_as_user
        if pod.spec.security_context is not None:
            return pod.spec.security_context.run_as_user
        return None

    def _violates(self, pod: t.Pod, psp: t.PodSecurityPolicy):
        s = psp.spec
        for c in list(pod.spec.containers) + list(pod.spec.init_containers):
            uid = self._effective_uid(pod, c)
            if s.run_as_user_rule == "MustRunAsNonRoot":
                if uid is None or uid == 0:
                    return (f"container {c.name!r} must run as a "
                            f"non-root uid")
            elif s.run_as_user_rule == "MustRunAs":
                if uid is None:
                    return f"container {c.name!r} must set run_as_user"
                if not any(r.min <= uid <= r.max
                           for r in s.run_as_user_ranges):
                    return (f"container {c.name!r} uid {uid} outside "
                            f"allowed ranges")
        host_vols = {v.name for v in pod.spec.volumes
                     if v.host_path is not None}
        if host_vols and not s.allow_host_paths:
            return f"hostPath volumes not allowed ({sorted(host_vols)})"
        if host_vols and s.read_only_host_paths:
            for c in list(pod.spec.containers) + list(pod.spec.init_containers):
                for vm in c.volume_mounts:
                    if vm.name in host_vols and not vm.read_only:
                        return (f"hostPath mount {vm.name!r} in container "
                                f"{c.name!r} must be read_only")
        return None


class DefaultTolerationSeconds(AdmissionPlugin):
    """Give every pod bounded tolerations for the not-ready and
    unreachable NoExecute taints, so a dead node's pods are evicted
    after ``default_seconds`` instead of immediately (no toleration) or
    never (operator forgot one).

    Reference: ``plugin/pkg/admission/defaulttolerationseconds/
    admission.go`` — same 300s default, same already-tolerates check.
    """

    name = "DefaultTolerationSeconds"

    def __init__(self, default_seconds: int = 300):
        self.default_seconds = default_seconds

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        pod: t.Pod = obj
        if pod.spec.tolerations is None:  # explicit JSON null
            pod.spec.tolerations = []
        for key in (t.TAINT_NODE_NOT_READY, t.TAINT_NODE_UNREACHABLE):
            probe = t.Taint(key=key, effect=t.TAINT_NO_EXECUTE)
            if any(tol.tolerates(probe) for tol in pod.spec.tolerations):
                continue
            pod.spec.tolerations.append(t.Toleration(
                key=key, operator="Exists", effect=t.TAINT_NO_EXECUTE,
                toleration_seconds=self.default_seconds))
        return pod


class ExtendedResourceToleration(AdmissionPlugin):
    """Pods that claim TPU chips automatically tolerate taints keyed by
    the TPU resource name — operators taint accelerator nodes
    ``google.com/tpu=present:NoSchedule`` and only chip-requesting pods
    land there, with no per-pod toleration boilerplate.

    Reference: ``plugin/pkg/admission/extendedresourcetoleration/
    admission.go`` (one Exists-toleration per requested extended
    resource).
    """

    name = "ExtendedResourceToleration"

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        pod: t.Pod = obj
        if not pod.spec.tpu_resources:
            return pod
        if pod.spec.tolerations is None:  # explicit JSON null
            pod.spec.tolerations = []
        # Skip only when the pod already TOLERATES a tpu-keyed taint
        # (exact-duplicate semantics, reference MergeTolerations): a
        # narrow Equal toleration for some other value must not
        # suppress the Exists one or the pod stays unschedulable on
        # the very nodes this plugin opens up.
        probe = t.Taint(key=t.RESOURCE_TPU, effect=t.TAINT_NO_SCHEDULE)
        if not any(tol.tolerates(probe) and tol.operator == "Exists"
                   for tol in pod.spec.tolerations):
            # effect=NoSchedule exactly (reference parity): an
            # effect-less toleration would also tolerate NoExecute,
            # pinning pods to a TPU node an operator is draining.
            pod.spec.tolerations.append(t.Toleration(
                key=t.RESOURCE_TPU, operator="Exists",
                effect=t.TAINT_NO_SCHEDULE))
        return pod


class PodNodeSelector(AdmissionPlugin):
    """Merge the namespace's ``scheduler.tpu/node-selector`` annotation
    into every pod's node selector; a pod contradicting its namespace's
    selector is rejected (namespaces as placement boundaries — e.g. a
    team's namespace pinned to its reserved slice hosts).

    Reference: ``plugin/pkg/admission/podnodeselector/admission.go``
    (annotation ``scheduler.alpha.kubernetes.io/node-selector``).
    """

    name = "PodNodeSelector"
    ANNOTATION = "scheduler.tpu/node-selector"

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        pod: t.Pod = obj
        try:
            ns = self.registry.get("namespaces", "", pod.metadata.namespace)
        except errors.NotFoundError:
            return pod  # NamespaceLifecycle owns that rejection
        raw = (ns.metadata.annotations or {}).get(self.ANNOTATION, "")
        if not raw:
            return pod
        if pod.spec.node_selector is None:  # explicit JSON null
            pod.spec.node_selector = {}
        selector = {}
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            k, eq, v = part.partition("=")
            k, v = k.strip(), v.strip()
            if not eq or not k:
                # A malformed annotation silently dropped would strip
                # the namespace's placement boundary (or merge an
                # unmatchable "" key leaving every pod Pending with no
                # pointer at the typo) — reject it at the source.
                raise errors.ForbiddenError(
                    f"namespace {pod.metadata.namespace!r} annotation "
                    f"{self.ANNOTATION} is malformed at {part!r} "
                    f"(want comma-separated key=value)")
            selector[k] = v
        for k, v in selector.items():
            have = pod.spec.node_selector.get(k)
            if have is not None and have != v:
                raise errors.ForbiddenError(
                    f"pod node selector {k}={have!r} conflicts with "
                    f"namespace {pod.metadata.namespace!r} selector "
                    f"{k}={v!r}")
            pod.spec.node_selector[k] = v
        return pod


class DefaultStorageClass(AdmissionPlugin):
    """Stamp PVCs that name no storage class with the cluster default
    (the StorageClass annotated ``storageclass.tpu/is-default-class``).
    Two defaults is operator error — rejected loudly rather than picked
    arbitrarily.

    Reference: ``plugin/pkg/admission/storageclass/setdefault/
    admission.go``. Divergence: the reference distinguishes nil (apply
    default) from "" (explicitly classless); dataclass fields have no
    nil, so "" means unset here and an intentionally classless PVC sets
    ``storage_class_name: "-"`` (normalized back to empty).
    """

    name = "DefaultStorageClass"
    ANNOTATION = "storageclass.tpu/is-default-class"
    NO_CLASS = "-"

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def admit(self, op, spec, obj, old):
        if spec.kind != "PersistentVolumeClaim" or op != "CREATE":
            return obj
        pvc = obj
        if pvc.metadata.annotations is None:  # explicit JSON null
            pvc.metadata.annotations = {}
        if pvc.spec.storage_class_name == self.NO_CLASS:
            pvc.spec.storage_class_name = ""
            pvc.metadata.annotations["volume.tpu/no-class"] = "true"
            return pvc
        if pvc.spec.storage_class_name:
            return pvc
        if pvc.metadata.annotations.get("volume.tpu/no-class") == "true":
            return pvc
        classes, _rev = self.registry.list("storageclasses")
        defaults = [
            sc for sc in classes
            if (sc.metadata.annotations or {}).get(self.ANNOTATION) == "true"]
        if not defaults:
            return pvc
        if len(defaults) > 1:
            names = sorted(sc.metadata.name for sc in defaults)
            raise errors.ForbiddenError(
                f"{len(defaults)} default StorageClasses ({names}); "
                f"mark exactly one with {self.ANNOTATION}=true")
        pvc.spec.storage_class_name = defaults[0].metadata.name
        return pvc


class LocalQueueAdmission(AdmissionPlugin):
    """Namespace -> queue binding for gang admission (JobQueueing gate).

    Mutate: a PodGroup created with ``spec.queue == ""`` is defaulted
    to the namespace's default LocalQueue (the one annotated
    ``queueing.tpu/default-queue=true``), so tenants opt a whole
    namespace into admission without touching every Job.
    Validate: a named queue must exist and its ClusterQueue must be
    installed — a dangling reference would suspend the gang forever
    with no controller ever admitting it.

    Everything is skipped while the gate is off: objects are
    byte-identical to the ungated build.
    """

    name = "LocalQueueAdmission"

    def __init__(self, registry: "Registry"):
        self.registry = registry

    @staticmethod
    def _gated() -> bool:
        from ..util.features import GATES
        return GATES.enabled("JobQueueing")

    def admit(self, op, spec, obj, old):
        if spec.kind != "PodGroup" or op != "CREATE" or not self._gated():
            return obj
        group = obj
        if group.spec.queue:
            return group
        from ..api.queueing import DEFAULT_QUEUE_ANNOTATION
        queues, _ = self.registry.list("localqueues",
                                       group.metadata.namespace)
        defaults = [q for q in queues if q.metadata.annotations.get(
            DEFAULT_QUEUE_ANNOTATION) == "true"]
        if len(defaults) > 1:
            # Ambiguity must be LOUD: silently leaving spec.queue empty
            # would let the gang bypass admission entirely (same rule
            # as DefaultStorageClass: mark exactly one).
            raise errors.BadRequestError(
                f"{len(defaults)} LocalQueues in namespace "
                f"{group.metadata.namespace!r} carry "
                f"{DEFAULT_QUEUE_ANNOTATION}=true; mark exactly one")
        if defaults:
            group.spec.queue = defaults[0].metadata.name
        return group

    def validate(self, op, spec, obj, old):
        if spec.kind != "PodGroup" or op != "CREATE" or not self._gated():
            return
        group = obj
        if not group.spec.queue:
            return
        try:
            lq = self.registry.get("localqueues", group.metadata.namespace,
                                   group.spec.queue)
        except errors.NotFoundError:
            raise errors.BadRequestError(
                f"LocalQueue {group.spec.queue!r} not found in namespace "
                f"{group.metadata.namespace!r}") from None
        try:
            self.registry.get("clusterqueues", "", lq.spec.cluster_queue)
        except errors.NotFoundError:
            raise errors.BadRequestError(
                f"LocalQueue {group.spec.queue!r} references missing "
                f"ClusterQueue {lq.spec.cluster_queue!r}") from None


class InferenceServiceDefaulter(AdmissionPlugin):
    """Serving defaults (InferenceAutoscaling gate, in the
    LocalQueueAdmission style: skipped entirely while the gate is off
    so created objects stay byte-identical to the ungated build).

    Defaults: replica window [1, max(min, 1)], port 8100, a 2000ms SLO,
    a 256 tokens/s per-replica rating, and a 0.65 busy-fraction target
    — the numbers ``hack/serve_smoke.sh`` and the serving bench grade
    against unless the operator says otherwise.
    """

    name = "InferenceServiceDefaulter"

    @staticmethod
    def _gated() -> bool:
        from ..util.features import GATES
        return GATES.enabled("InferenceAutoscaling")

    def admit(self, op, spec, obj, old):
        if spec.kind != "InferenceService" or op != "CREATE" \
                or not self._gated():
            return obj
        from ..api.serving import effective_spec
        obj.spec = effective_spec(obj.spec)
        return obj


def default_chain(registry: "Registry") -> AdmissionChain:
    return AdmissionChain([
        NamespaceLifecycle(registry),
        TpuResourceDefaulter(),
        PriorityResolver(registry),
        LocalQueueAdmission(registry),
        InferenceServiceDefaulter(),
        ServiceAccountPlugin(registry),
        DefaultTolerationSeconds(),
        ExtendedResourceToleration(),
        PodNodeSelector(registry),
        DefaultStorageClass(registry),
        LimitRanger(registry),
        ResourceQuotaPlugin(registry),
        PodSecurity(registry),
    ])
