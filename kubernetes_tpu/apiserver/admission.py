"""Admission chain — mutate-then-validate hooks on every write.

Reference: ``staging/src/k8s.io/apiserver/pkg/admission`` invoked from
``endpoints/handlers/create.go:37`` plus the in-tree plugins in
``plugin/pkg/admission/`` — notably the fork's ``resourcev2`` plugin
(``admission.go:32-118``) which rewrites legacy count-style GPU limits
into the per-device resource model. :class:`TpuResourceDefaulter` is
the TPU analog of that compat shim.
"""
from __future__ import annotations

import uuid
from typing import TYPE_CHECKING, Optional

from ..api import errors, types as t
from ..api.meta import TypedObject

if TYPE_CHECKING:
    from .registry import Registry, ResourceSpec


class AdmissionPlugin:
    name = "plugin"

    def admit(self, op: str, spec: "ResourceSpec", obj: TypedObject,
              old: Optional[TypedObject]) -> TypedObject:
        """Mutate phase: return the (possibly modified) object."""
        return obj

    def validate(self, op: str, spec: "ResourceSpec", obj: TypedObject,
                 old: Optional[TypedObject]) -> None:
        """Validate phase: raise to reject."""


class AdmissionChain:
    def __init__(self, plugins: Optional[list[AdmissionPlugin]] = None):
        self.plugins = plugins or []

    def admit(self, op: str, spec: "ResourceSpec", obj: TypedObject,
              old: Optional[TypedObject]) -> TypedObject:
        for p in self.plugins:
            obj = p.admit(op, spec, obj, old)
        for p in self.plugins:
            p.validate(op, spec, obj, old)
        return obj


class TpuResourceDefaulter(AdmissionPlugin):
    """Rewrite count-style ``google.com/tpu`` container limits into a
    named :class:`~kubernetes_tpu.api.types.PodTpuRequest` + container
    reference, deleting the raw limit.

    Reference: ``plugin/pkg/admission/resourcev2/admission.go:51-118``
    (``Admit`` + ``newExtendedResource``) — same old->new compat shim,
    UUID-suffixed claim name and all.
    """

    name = "TpuResourceDefaulter"

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        pod: t.Pod = obj
        for c in pod.spec.containers:
            n = c.resources.limits.pop(t.RESOURCE_TPU, None) or \
                c.resources.requests.pop(t.RESOURCE_TPU, None)
            if not n:
                continue
            claim_name = f"tpu-{uuid.uuid4().hex[:8]}"
            pod.spec.tpu_resources.append(
                t.PodTpuRequest(name=claim_name, chips=int(n)))
            c.tpu_requests.append(claim_name)
            c.resources.limits.pop(t.RESOURCE_TPU, None)
            c.resources.requests.pop(t.RESOURCE_TPU, None)
        return pod


class NamespaceLifecycle(AdmissionPlugin):
    """Reject creates in missing or terminating namespaces; auto-create
    the default namespace. Reference: ``plugin/pkg/admission/namespace``."""

    name = "NamespaceLifecycle"
    _EXEMPT = {"Namespace", "Node", "PriorityClass", "Lease", "Event"}

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def validate(self, op, spec, obj, old):
        if op != "CREATE" or spec.kind in self._EXEMPT or not spec.namespaced:
            return
        ns_name = obj.metadata.namespace
        try:
            ns = self.registry.get("namespaces", "", ns_name)
        except errors.NotFoundError:
            if ns_name == "default":
                self.registry.create(t.Namespace(
                    metadata=t.ObjectMeta(name="default")))  # type: ignore[attr-defined]
                return
            raise errors.ForbiddenError(f"namespace {ns_name!r} not found") from None
        if ns.status.phase == t.NS_TERMINATING or ns.metadata.deletion_timestamp:
            raise errors.ForbiddenError(
                f"namespace {ns_name!r} is terminating; cannot create {spec.kind}")


class PriorityResolver(AdmissionPlugin):
    """Resolve priority_class_name -> numeric priority at admission.

    Reference: priority admission in the scheduler ecosystem; pods carry
    resolved ``spec.priority`` so the scheduler never does lookups.
    """

    name = "PriorityResolver"

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def admit(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return obj
        pod: t.Pod = obj
        if pod.spec.priority_class_name and pod.spec.priority is None:
            try:
                pc = self.registry.get("priorityclasses", "", pod.spec.priority_class_name)
                pod.spec.priority = pc.value
            except errors.NotFoundError:
                raise errors.BadRequestError(
                    f"priority class {pod.spec.priority_class_name!r} not found") from None
        if pod.spec.priority is None:
            pod.spec.priority = 0
        return pod


class ResourceQuotaPlugin(AdmissionPlugin):
    """Enforce per-namespace hard quotas on create.

    Reference: ``plugin/pkg/admission/resourcequota`` + ``pkg/quota``.
    Counts pods, TPU chips, cpu/memory requests against every quota in
    the namespace and rejects if any limit would be exceeded.
    """

    name = "ResourceQuota"

    def __init__(self, registry: "Registry"):
        self.registry = registry

    def validate(self, op, spec, obj, old):
        if spec.kind != "Pod" or op != "CREATE":
            return
        pod: t.Pod = obj
        ns = pod.metadata.namespace
        quotas, _ = self.registry.list("resourcequotas", ns)
        if not quotas:
            return
        want = t.pod_resource_requests(pod)
        pods, _ = self.registry.list("pods", ns)
        used: dict[str, float] = {}
        for p in pods:
            if not t.is_pod_active(p):
                continue
            for k, v in t.pod_resource_requests(p).items():
                used[k] = used.get(k, 0.0) + v
        for q in quotas:
            for res, hard in q.spec.hard.items():
                if res not in want:
                    continue
                if used.get(res, 0.0) + want[res] > t.parse_quantity(hard):
                    raise errors.ForbiddenError(
                        f"exceeded quota {q.metadata.name!r}: requested "
                        f"{res}={want[res]:g}, used {used.get(res, 0.0):g}, "
                        f"hard limit {t.parse_quantity(hard):g}")


def default_chain(registry: "Registry") -> AdmissionChain:
    return AdmissionChain([
        NamespaceLifecycle(registry),
        TpuResourceDefaulter(),
        PriorityResolver(registry),
        ResourceQuotaPlugin(registry),
    ])
