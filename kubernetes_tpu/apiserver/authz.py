"""Authorization — RBAC authorizer + mode selection.

Reference: ``plugin/pkg/auth/authorizer/rbac/rbac.go`` (RuleResolver
walking bindings -> roles -> rules) and the apiserver's
``--authorization-mode`` (AlwaysAllow / RBAC). The resolver reads the
registry directly (in-proc store reads are cheap and always current —
the reference uses informers for the same data).
"""
from __future__ import annotations

from typing import Optional

from ..api import errors, rbac
from .registry import Registry


class Attributes:
    """One authorization question (reference: ``authorizer.Attributes``)."""

    ANONYMOUS = "system:anonymous"

    def __init__(self, user: str, groups: set[str], verb: str,
                 resource: str, namespace: str = "", name: str = ""):
        self.user = user
        # Anonymous callers are NOT system:authenticated (reference:
        # anonymous gets system:unauthenticated) — otherwise an
        # any-logged-in-user grant would extend to unauthenticated ones.
        implicit = ("system:unauthenticated" if user == self.ANONYMOUS
                    else rbac.GROUP_AUTHENTICATED)
        self.groups = groups | {implicit}
        self.verb = verb
        self.resource = resource
        self.namespace = namespace
        self.name = name

    def __repr__(self) -> str:  # for Forbidden messages + audit
        scope = f" in {self.namespace!r}" if self.namespace else ""
        return (f"user {self.user!r} {self.verb} "
                f"{self.resource}/{self.name or '*'}{scope}")


class Authorizer:
    def authorize(self, attrs: Attributes) -> bool:
        raise NotImplementedError


class AlwaysAllow(Authorizer):
    """Dev mode — the reference's insecure/AlwaysAllow stance."""

    def authorize(self, attrs: Attributes) -> bool:
        return True


class RBACAuthorizer(Authorizer):
    def __init__(self, registry: Registry):
        self.registry = registry

    def authorize(self, attrs: Attributes) -> bool:
        if rbac.GROUP_MASTERS in attrs.groups:
            return True
        # Cluster-wide grants.
        for binding in self._list("clusterrolebindings", ""):
            if not self._bound(binding, attrs):
                continue
            rules = self._role_rules(binding.role_ref, "")
            if self._rules_allow(rules, attrs):
                return True
        # Namespaced grants (only meaningful for namespaced requests).
        if attrs.namespace:
            for binding in self._list("rolebindings", attrs.namespace):
                if not self._bound(binding, attrs):
                    continue
                rules = self._role_rules(binding.role_ref, attrs.namespace)
                if self._rules_allow(rules, attrs):
                    return True
        return False

    def _list(self, plural: str, namespace: str) -> list:
        try:
            items, _rev = self.registry.list(plural, namespace)
            return items
        except errors.StatusError:
            return []

    def _bound(self, binding, attrs: Attributes) -> bool:
        return any(rbac.subject_matches(s, attrs.user, attrs.groups)
                   for s in binding.subjects)

    def _role_rules(self, ref: rbac.RoleRef, namespace: str) -> list:
        try:
            if ref.kind == "ClusterRole":
                role = self.registry.get("clusterroles", "", ref.name)
            else:
                role = self.registry.get("roles", namespace, ref.name)
        except errors.StatusError:
            return []
        return role.rules

    @staticmethod
    def _rules_allow(rules: list, attrs: Attributes) -> bool:
        return any(rule.matches(attrs.verb, attrs.resource, attrs.name)
                   for rule in rules)


def verb_for_request(method: str, has_name: bool, is_watch: bool) -> str:
    """HTTP -> RBAC verb (reference: ``RequestInfoFactory``)."""
    if is_watch:
        return "watch"
    if method == "GET":
        return "get" if has_name else "list"
    return {"POST": "create", "PUT": "update", "PATCH": "patch",
            "DELETE": "delete" if has_name else "deletecollection"}.get(
                method, method.lower())


#: Usernames minted by bootstrap.mint_node_credential.
NODE_USER_PREFIX = "system:serviceaccount:kube-system:node-"


class NodeRestriction(Authorizer):
    """NodeRestriction-lite (reference: the node authorizer +
    NodeRestriction admission): node identities must not read secrets
    in kube-system — that namespace holds every OTHER node's token
    secret and all bootstrap tokens, so one compromised node must not
    be able to mint or steal cluster-wide identities. Workload-
    namespace secrets stay readable (pod volumes need them; per-pod
    graph scoping as in the reference node authorizer is future work).
    Everything else delegates to the wrapped authorizer."""

    def __init__(self, inner: Authorizer):
        self.inner = inner

    def authorize(self, attrs: Attributes) -> bool:
        if (attrs.user.startswith(NODE_USER_PREFIX)
                and attrs.resource.split("/")[0] == "secrets"
                # "" = cluster-wide list/watch, which spans every
                # namespace including kube-system — same denial, or the
                # namespaced check is a bypassable fiction.
                and attrs.namespace in ("", "kube-system")):
            return False
        return self.inner.authorize(attrs)


def make_authorizer(mode: str, registry: Registry) -> Optional[Authorizer]:
    if mode == "RBAC":
        return NodeRestriction(RBACAuthorizer(registry))
    if mode in ("", "AlwaysAllow"):
        return AlwaysAllow()
    raise ValueError(f"unknown authorization mode {mode!r}")
