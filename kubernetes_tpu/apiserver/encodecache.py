"""Serialize-once response cache — encoded JSON bytes per (key, revision).

Reference: the watch cache's serialize-once fan-out
(``staging/src/k8s.io/apiserver/pkg/storage/cacher/cacher.go`` +
``runtime.CacheableObject``): an object's wire encoding is computed once
per revision and shared by every consumer — the watch fan-out, GETs,
and LIST assembly — instead of paying ``to_dict`` + ``json.dumps`` per
request. At density scale (30k pods, every bind a write followed by N
watcher re-encodes plus scheduler/loadgen reads) re-encoding unchanged
objects was a dominant apiserver CPU cost.

Correctness model: entries are keyed by ``(key, revision, which)`` —
a store revision is immutable, so a cached encoding can never go stale.
Writes additionally *invalidate* all entries for the written key (wired
via :meth:`MVCCStore.add_write_hook`), which keeps the cache populated
only with the revisions still being served and makes the memory bound a
formality rather than the correctness mechanism. ``which`` is the raw
watch's "cur"/"prev" disambiguator: a selector-left MODIFIED surfaces
the prev-value corpse at the same revision as the new value.
"""
from __future__ import annotations

from typing import Optional

from ..metrics.registry import Counter, Gauge
from ..util.lockdep import make_lock

ENCODE_CACHE_HITS = Counter(
    "encode_cache_hits_total",
    "Serialize-once cache hits (encoded bytes reused)")
ENCODE_CACHE_MISSES = Counter(
    "encode_cache_misses_total",
    "Serialize-once cache misses (object encoded)")
ENCODE_CACHE_ENTRIES = Gauge(
    "encode_cache_entries", "Entries currently held by the encode cache")
ENCODE_CACHE_BYTES = Gauge(
    "encode_cache_bytes", "Encoded bytes currently held by the encode cache")
ENCODE_CACHE_EVICTIONS = Counter(
    "encode_cache_evictions_total",
    "Entries evicted by the encode cache's entry/byte ceilings")


class EncodeCache:
    """Bounded map ``(key, revision, which) -> encoded JSON bytes``.

    Thread-safe: reads come from the apiserver event loop, but write
    hooks fire under the store lock from whatever thread performed the
    mutation (``Registry.run`` uses a worker thread for durable
    stores). The cache lock is a leaf — it never acquires another lock.
    """

    def __init__(self, limit: int = 16384, max_bytes: int = 64 * 1024 * 1024):
        """``limit``: max entries; ``max_bytes``: max total encoded
        bytes (0 = entries-only bound). Either ceiling triggers the
        same oldest-quarter eviction — under sustained churn the cache
        holds a bounded working set, never the write history."""
        self.limit = limit
        self.max_bytes = max_bytes
        self._bytes = 0
        self._lock = make_lock("apiserver.EncodeCache")
        #: Insertion-ordered; eviction pops the oldest quarter.
        self._data: dict[tuple[str, int, str], bytes] = {}
        #: key -> cache keys held for it (write invalidation is O(entries
        #: for that key), never a full scan).
        self._by_key: dict[str, list[tuple[str, int, str]]] = {}
        #: Async-encode race guard (the codec-pool path): per-key
        #: invalidation generation, tracked ONLY while an offloaded
        #: encode of that key is in flight (bounded by in-flight work,
        #: not by keyspace). A write bumps the generation; a completing
        #: pool encode whose dispatch-time token no longer matches is
        #: dropped — it must never resurrect an entry a write
        #: invalidated while it was in the pool.
        self._gen: dict[str, int] = {}
        self._pending: dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._data)

    def get(self, key: str, revision: int,
            which: str = "cur") -> Optional[bytes]:
        line = self._data.get((key, revision, which))
        if line is None:
            ENCODE_CACHE_MISSES.inc()
        else:
            ENCODE_CACHE_HITS.inc()
        return line

    def put(self, key: str, revision: int, line: bytes,
            which: str = "cur") -> None:
        with self._lock:
            self._put_locked((key, revision, which), line)

    def _put_locked(self, ck: tuple, line: bytes) -> None:
        """The one insert path (dup-check, eviction, index, gauge) —
        shared by :meth:`put` and :meth:`finish_async_encode` so the
        two can never drift."""
        if ck in self._data:
            return
        # Either ceiling forces an eviction round; a single entry
        # larger than max_bytes still inserts once the cache is empty
        # (refusing it would re-encode that object on every request).
        while self._data and (
                len(self._data) >= self.limit
                or (self.max_bytes
                    and self._bytes + len(line) > self.max_bytes)):
            self._evict_locked()
        self._data[ck] = line
        self._bytes += len(line)
        self._by_key.setdefault(ck[0], []).append(ck)
        ENCODE_CACHE_ENTRIES.set(float(len(self._data)))
        ENCODE_CACHE_BYTES.set(float(self._bytes))

    def invalidate(self, key: str) -> None:
        """Drop every cached encoding for ``key`` (called on write)."""
        with self._lock:
            for ck in self._by_key.pop(key, ()):
                old = self._data.pop(ck, None)
                if old is not None:
                    self._bytes -= len(old)
            if key in self._pending:
                # An offloaded encode of this key is in flight: its
                # dispatch-time token is now stale and its completion
                # must be discarded (finish_async_encode checks).
                self._gen[key] = self._gen.get(key, 0) + 1
            ENCODE_CACHE_ENTRIES.set(float(len(self._data)))
            ENCODE_CACHE_BYTES.set(float(self._bytes))

    # -- async (pool-offloaded) encode guard ------------------------------

    def begin_async_encode(self, key: str) -> int:
        """Register an offloaded encode of ``key``; returns the token
        :meth:`finish_async_encode` must present. Call BEFORE reading
        the store value that will be encoded — a write after the read
        then provably bumps the generation this token snapshot holds."""
        with self._lock:
            self._pending[key] = self._pending.get(key, 0) + 1
            return self._gen.get(key, 0)

    def abort_async_encode(self, key: str) -> None:
        """Release a :meth:`begin_async_encode` registration without
        inserting anything — the cancellation path (client gone mid-
        LIST, pool failure). Without this, an aborted 30k-pod LIST
        would leave thousands of ``_pending``/``_gen`` entries behind
        forever, breaking the bounded-by-in-flight-work invariant."""
        with self._lock:
            n = self._pending.get(key, 0) - 1
            if n <= 0:
                self._pending.pop(key, None)
                self._gen.pop(key, None)
            else:
                self._pending[key] = n

    def finish_async_encode(self, key: str, revision: int, line: bytes,
                            token: int, which: str = "cur") -> bool:
        """Complete an offloaded encode: insert the entry iff no write
        invalidated ``key`` since :meth:`begin_async_encode` minted the
        token. Returns False (entry dropped) when the encode lost the
        race — the write-hook invalidation must win over a stale
        future completion, or a dead revision's bytes reappear."""
        with self._lock:
            n = self._pending.get(key, 0) - 1
            if n <= 0:
                self._pending.pop(key, None)
                current = self._gen.pop(key, 0)
            else:
                self._pending[key] = n
                current = self._gen.get(key, 0)
            if current != token:
                from .codecpool import CODEC_POOL_STALE_DROPS
                CODEC_POOL_STALE_DROPS.inc()
                return False
            self._put_locked((key, revision, which), line)
            return True

    def _evict_locked(self) -> None:
        # Oldest quarter by insertion order: one write-heavy burst must
        # not turn every subsequent put into an eviction.
        drop = max(1, self.limit // 4)
        for ck in list(self._data)[:drop]:
            self._bytes -= len(self._data[ck])
            del self._data[ck]
            ENCODE_CACHE_EVICTIONS.inc()
            held = self._by_key.get(ck[0])
            if held is not None:
                try:
                    held.remove(ck)
                except ValueError:
                    pass
                if not held:
                    del self._by_key[ck[0]]

    def stats(self) -> dict:
        """Occupancy + traffic snapshot (the /debug/v1/storage view)."""
        with self._lock:
            return {"entries": len(self._data), "bytes": self._bytes,
                    "limit": self.limit, "max_bytes": self.max_bytes,
                    "hits": ENCODE_CACHE_HITS.value(),
                    "misses": ENCODE_CACHE_MISSES.value(),
                    "evictions": ENCODE_CACHE_EVICTIONS.value()}
