"""Standalone apiserver process — ``python -m kubernetes_tpu.apiserver``.

Reference analog: ``cmd/kube-apiserver`` (the apiserver as its own
binary with its own address space). The all-in-one ``ktl up`` composes
everything in-process; this entry exists for deployments — and
benchmarks — where the apiserver must not share a GIL/event loop with
its clients: the REST-path density harness runs it as a subprocess so
the wire path measured is the one a real deployment has.

Prints ``LISTENING <port>`` on stdout once serving (parent processes
wait for that line), then runs until SIGTERM/SIGINT.
"""
from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from ..api import errors
from ..api import types as t
from ..util.gctune import tune_control_plane_gc
from ..api.meta import ObjectMeta
from .registry import Registry
from .server import APIServer


async def amain(argv=None) -> int:
    tune_control_plane_gc()
    p = argparse.ArgumentParser(prog="kubernetes-tpu-apiserver")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 picks a free port (printed on stdout)")
    p.add_argument("--data-dir", default="",
                   help="durable WAL/snapshot dir; empty = in-memory")
    p.add_argument("--namespaces", default="default,kube-system",
                   help="comma-separated namespaces to ensure at boot")
    p.add_argument("--feature-gates", default="",
                   help='"Gate=true,Other=false" applied to the process-'
                        "global gate table (e.g. ApiServerSharding=true,"
                        "ApiServerCodecOffload=true)")
    args = p.parse_args(argv)

    if args.feature_gates:
        from ..util.features import GATES
        GATES.parse(args.feature_gates)

    store = None
    if args.data_dir:
        import os

        from ..storage.mvcc import MVCCStore
        store = MVCCStore(os.path.join(args.data_dir, "state"))
    registry = Registry(store=store)
    for ns in filter(None, args.namespaces.split(",")):
        try:
            registry.create(t.Namespace(metadata=ObjectMeta(name=ns)))
        except errors.AlreadyExistsError:
            pass  # durable restart
    server = APIServer(registry)
    port = await server.start(args.host, args.port)
    print(f"LISTENING {port}", flush=True)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            signal.signal(sig, lambda *_: stop.set())
    await stop.wait()
    await server.stop()
    return 0


def main() -> int:
    """cProfile seam (KTPU_PROFILE=<stats path>): the decode-share
    measurement (perf/decode_share.py) profiles this process across a
    density run and attributes CPU to codec vs everything else."""
    import os
    profile_path = os.environ.get("KTPU_PROFILE", "")
    if not profile_path:
        return asyncio.run(amain())
    import cProfile
    prof = cProfile.Profile()
    prof.enable()
    try:
        return asyncio.run(amain())
    finally:
        prof.disable()
        prof.dump_stats(profile_path)


if __name__ == "__main__":
    sys.exit(main())
