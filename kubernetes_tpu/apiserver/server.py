"""HTTP API server — REST + watch endpoint over the registry.

Reference: the generic apiserver handler chain
(``staging/src/k8s.io/apiserver/pkg/server/config.go:530
DefaultBuildHandlerChain``: authn -> audit -> ... -> authz) and route
installation (``endpoints/installer.go:196 registerResourceHandlers``).

URL scheme (group folded into the path like the reference's /apis):

- ``/api/<group>/<version>/<plural>``                      cluster list/create
- ``/api/<group>/<version>/namespaces/<ns>/<plural>``      namespaced list/create
- ``.../<plural>/<name>``                                  get/put/patch/delete
- ``.../<plural>/<name>/status``                           status subresource
- ``.../pods/<name>/binding``                              scheduler binding
- ``?watch=1&resource_version=N``                          JSON-lines stream
- ``/healthz``, ``/readyz``, ``/version``, ``/metrics``, ``/apis`` discovery

Watch responses are chunked ``{"type": ..., "object": ...}`` lines —
the transport informers consume. A server-side bookmark keeps idle
streams alive (and lets clients resume precisely).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Optional

from aiohttp import web

from .. import tracing
from ..api import errors
from ..api.scheme import deepcopy as obj_deepcopy, to_dict
from ..metrics.registry import REGISTRY as METRICS, Counter, Gauge, Histogram
from ..util.loopprobe import loop_lag_probe
from ..util.tasks import spawn
from .admission import default_chain
from .audit import AuditLogger
from .authz import Attributes, Authorizer, verb_for_request
from .registry import Registry
from .sharding import SHARD_INLINE, shard_for

log = logging.getLogger("apiserver")

REQUEST_LATENCY = Histogram(
    "apiserver_request_latency_seconds",
    "API request latency by verb and resource",
    labels=("verb", "resource"),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
             0.5, 0.75, 1.0, 1.5, 2.5),
)

#: Unlabeled raw-sample sibling of REQUEST_LATENCY: true p50/p90/p99
#: for the bench harnesses (bucket quantiles are bucket EDGES — the
#: r05 "p50=0.5 / p90=1.0 / p99=10.0 ms" numbers were edges, not
#: measurements). Rendered as the raw-quantile gauge below at scrape.
REQUEST_LATENCY_RAW = Histogram(
    "apiserver_request_latency_raw_seconds",
    "API request latency, raw samples retained for true percentiles",
    buckets=(0.001, 0.01, 0.1, 1.0), sample_limit=120_000)

REQUEST_LATENCY_RAW_Q = Gauge(
    "apiserver_request_latency_raw_quantile_ms",
    "True request-latency percentiles (ms) from raw samples, "
    "recomputed at each /metrics scrape", labels=("q",))

#: Event-loop lag probe: how late a short sleep fires on each apiserver
#: loop (router + shard workers). The sum is wall time the loop spent
#: BEHIND schedule — the bench arms attribute wall-vs-loop time from
#: per-phase deltas of _sum (see perf/loadgen.py).
LOOP_LAG = Histogram(
    "apiserver_loop_lag_ms",
    "Event-loop scheduling lag per probe tick, by loop",
    labels=("loop",),
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
             250.0, 500.0, 1000.0),
    sample_limit=20_000)

LOOP_BUSY = Gauge(
    "apiserver_loop_busy_fraction",
    "EWMA busy fraction per apiserver event loop (loop-lag derived)",
    labels=("loop",))

#: Probe cadence; cheap by construction (one timer per loop).
LOOP_PROBE_INTERVAL = 0.05

BATCH_REQUESTS = Counter(
    "apiserver_batch_requests_total",
    "Batch API requests (:batchCreate / bindings:batch) by kind",
    labels=("kind",))

#: Write-path compact negotiation (CompactWireCodec on the CREATE /
#: batchCreate / bindings:batch bodies): how many request bodies each
#: verb decoded from the compact codec — the instrument that says the
#: write path actually negotiated, not just the LIST/watch half.
COMPACT_WRITE_REQUESTS = Counter(
    "apiserver_compact_write_requests_total",
    "Write-path request bodies decoded from the compact wire codec, "
    "by verb", labels=("verb",))

BATCH_ITEMS = Counter(
    "apiserver_batch_items_total",
    "Per-item outcomes inside batch API requests",
    labels=("kind", "result"))

#: Watch fan-out accounting, ALWAYS ON (the gated WatchFanoutBatch path
#: has its own apiserver_fanout_* families): how many streams are open
#: and what each coalesced write round carries. ``dispatch`` says how
#: the store delivers to the stream — "indexed" rides a keyed bucket
#: (per-node pod watchers at fleet width), "scan" pays the per-event
#: prefix scan. The fleet bench reads bytes/round and stream width here.
WATCH_STREAMS = Gauge(
    "apiserver_watch_streams",
    "Open watch streams by store dispatch mode",
    labels=("dispatch",))

WATCH_ROUNDS = Counter(
    "apiserver_watch_rounds_total",
    "Coalesced watch write rounds (one buffered socket send each)")

WATCH_ROUND_BYTES = Histogram(
    "apiserver_watch_round_bytes",
    "Bytes per coalesced watch write round",
    buckets=(256, 1024, 4096, 16384, 65536, 262144, 1048576),
    sample_limit=20_000)

WATCH_EVENTS_SENT = Counter(
    "apiserver_watch_events_sent_total",
    "Watch event frames written to clients (bookmarks excluded)")

#: Per-request item cap for the batch subresources — one request must
#: not monopolize the event loop (the reference bounds list chunks the
#: same way; callers split larger batches).
MAX_BATCH_ITEMS = 512


class APIServer:
    def __init__(self, registry: Optional[Registry] = None,
                 tokens: Optional[dict[str, str]] = None,
                 authorizer: Optional[Authorizer] = None,
                 user_groups: Optional[dict[str, set]] = None,
                 audit: Optional[AuditLogger] = None):
        """``tokens``: bearer token -> username; None disables authn
        (local/dev mode, like the reference's insecure port).
        ``authorizer``: None = AlwaysAllow; pass
        ``authz.RBACAuthorizer(registry)`` for RBAC mode.
        ``user_groups``: username -> extra groups (e.g. system:masters).
        ``audit``: optional AuditLogger recording every request."""
        self.registry = registry or Registry()
        if self.registry.admission is None:
            self.registry.admission = default_chain(self.registry)
        self.tokens = tokens
        self.authorizer = authorizer
        self.user_groups = user_groups or {}
        self.audit = audit
        #: Cluster DNS "ip:port" advertised to joining nodes via the
        #: node-credentials response (kubeadm's cluster-info analog);
        #: set by the cluster composer once DNS is up. Loopback-bound
        #: DNS is only reachable by same-host joiners — the composer
        #: should bind a routable host for true multi-host.
        self.dns_address = ""
        #: CertAuthority when the cluster runs TLS (certs.py); enables
        #: GET /bootstrap/v1/ca and the CSR-signing join endpoint.
        self.cert_authority = None
        #: External admission webhooks (webhooks.py): mutating hooks run
        #: before the registry's in-tree chain, validating hooks on the
        #: final request object; zero overhead while no config exists.
        from .webhooks import WebhookDispatcher
        self.webhooks = WebhookDispatcher(self.registry)
        #: External token authenticator in the union (reference: the
        #: webhook TokenReview authenticator, --authentication-token-
        #: webhook-config-file): consulted after static/SA/bootstrap
        #: tokens miss; the endpoint answers authentication/v1
        #: TokenReview. ``authn_webhook_ssl``: optional ssl context for
        #: a private CA.
        self.authn_webhook_url = ""
        self.authn_webhook_ssl = None
        self._authn_webhook_cache: dict[str, tuple] = {}
        #: Requests slower than this log a slow-op line (SLO: 1s p99).
        self.slow_request_threshold = 1.0
        #: Max concurrent non-watch requests (reference: the
        #: max-in-flight filter in DefaultBuildHandlerChain); beyond it
        #: requests get 429 and clients back off.
        self.max_inflight = 400
        self._inflight = 0
        #: token -> (namespace, sa name) reverse index over SA token
        #: Secrets, rebuilt at most every ttl seconds — O(1) lookups,
        #: bounded by the number of SA secrets (unknown tokens cost a
        #: dict miss, never a scan or a cache entry).
        self._sa_index: dict[str, tuple[str, str]] = {}
        self._sa_index_at = float("-inf")
        self.sa_index_ttl = 10.0
        self._agg_discovery: list = []
        self._agg_discovery_at = float("-inf")
        self._proxy_session = None
        #: ShardPool when ApiServerSharding is on (built at start());
        #: None = every request runs on the router loop, byte-identical
        #: to the unsharded apiserver.
        self.shards = None
        #: CodecPool when ApiServerCodecOffload is on (built at
        #: start()); None = all codec work inline, byte-identical.
        self.codec_pool = None
        #: Callable returning the kmon MetricsPipeline (or None) —
        #: wired by single-binary composers so /debug/v1/query and
        #: /debug/v1/alerts can read the co-located TSDB/rule state.
        #: Unwired (remote controller-manager) or gate-off: 404.
        self.metrics_pipeline_provider = None
        #: Bounded staleness a follower tolerates before refusing a
        #: read the client marked with X-Ktpu-Max-Staleness (the
        #: client's header value wins when tighter).
        self.follower_staleness_bound = 5.0
        self._probe_tasks: list = []
        self._probe_futs: list = []
        #: Events coalesced into one watch-stream socket write. One
        #: write per event was a measured syscall cost at density
        #: scale (the fan-out's send() dominated apiserver CPU).
        self.watch_write_batch = 128
        #: Seconds between under-traffic BOOKMARK frames on a watch
        #: stream (WatchBookmarks gate; the reference's ~1/min, scaled
        #: to this cluster's clocks). Idle streams already get a
        #: bookmark from the 10s next() timeout regardless of gate —
        #: this adds them while events flow, so a busy informer's
        #: resume point keeps advancing.
        self.watch_bookmark_interval = 10.0
        #: FanoutFlusher when WatchFanoutBatch is on (built lazily at
        #: the first gated watch); None = per-watcher inline writes,
        #: byte-identical.
        self.fanout = None
        self.app = web.Application(middlewares=[self._middleware])
        self._routes()
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    # -- handler chain ----------------------------------------------------

    @web.middleware
    async def _middleware(self, request: web.Request, handler):
        # authn -> authz -> handler -> audit -> error mapping
        # (reference: DefaultBuildHandlerChain, compressed).
        if self.tokens is not None and not request.path.startswith(
                ("/healthz", "/readyz", "/version", "/bootstrap/v1/ca",
                 "/ha/v1/status")):
            # x509 first (reference: the authenticator union tries the
            # request cert before bearer tokens, x509.go:83): a client
            # cert that survived chain verification in the handshake
            # carries CN=user / O=groups.
            user = None
            transport = request.transport
            ssl_obj = (transport.get_extra_info("ssl_object")
                       if transport is not None else None)
            if ssl_obj is not None:
                # Parse the peer cert ONCE per connection (it cannot
                # change mid-connection) — x509 parsing on every
                # request of a node agent's watch/heartbeat stream is
                # pure repeated work on the hot path.
                ident = getattr(transport, "_ktpu_cert_identity", None)
                if ident is None:
                    der = ssl_obj.getpeercert(binary_form=True)
                    if der:
                        from .certs import identity_from_der
                        ident = identity_from_der(der)
                    else:
                        ident = ("", [])
                    try:
                        transport._ktpu_cert_identity = ident
                    except AttributeError:
                        pass  # slotted transport: re-parse per request
                cn, orgs = ident
                if cn:
                    user = cn
                    request["cert_groups"] = set(orgs)
            if user is None:
                auth = request.headers.get("Authorization", "")
                token = auth[7:] if auth.startswith("Bearer ") else ""
                user = (self.tokens.get(token) or self._sa_user(token)
                        or self._bootstrap_user(token))
                if user is None and token and self.authn_webhook_url:
                    # Union tail: the external TokenReview webhook.
                    hit = await self._webhook_user(token)
                    if hit is not None:
                        user, webhook_groups = hit
                        request["cert_groups"] = set(webhook_groups)
            if user is None:
                return self._err(errors.UnauthorizedError(
                    "no valid client certificate or bearer token"))
            request["user"] = user
            # Impersonation (reference: WithImpersonation,
            # staging/.../server/config.go:530-543): a caller holding
            # the ``impersonate`` verb acts as another identity; audit
            # records BOTH. Runs after authn, before authz — all
            # downstream decisions see the impersonated identity.
            imp_user = request.headers.get("Impersonate-User", "")
            if imp_user:
                resp = self._impersonate(request, user, imp_user)
                if resp is not None:
                    return resp
            elif "Impersonate-Group" in request.headers:
                # Group-without-user is an error in the reference, not
                # a silent no-op the caller would misread as applied.
                return self._err(errors.BadRequestError(
                    "Impersonate-Group requires Impersonate-User"))
        attrs = self._attributes(request)
        # Long-running exemption from max-in-flight applies only to
        # requests that ARE watches (collection GET) — '?watch=1' on a
        # mutating verb must not bypass the limiter.
        is_watch = (request.method == "GET"
                    and not request.match_info.get("name")
                    and request.query.get("watch") in ("1", "true"))
        # ktrace server span: a sampled traceparent header joins this
        # request to the caller's trace; the span is ACTIVATED so
        # everything downstream (registry create stamps, admission,
        # recorder events) nests under it. No header / disarmed: one
        # check, the shared no-op span.
        server_span = tracing.NOOP_SPAN
        if tracing.armed() and not is_watch:
            tctx = tracing.decode(
                request.headers.get(tracing.TRACEPARENT_HEADER))
            if tctx is not None and tctx.sampled:
                server_span = tracing.start_span(
                    f"{request.method} "
                    f"{request.match_info.get('plural') or request.path}",
                    component="apiserver", parent=tctx).activate()
        start = time.perf_counter()
        code = 500
        admitted = False
        try:
            if not is_watch:
                # Max-in-flight INSIDE the try block: 429s must reach
                # the latency metric + audit log — overload is exactly
                # when telemetry matters.
                if self._inflight >= self.max_inflight:
                    raise errors.TooManyRequestsError(
                        f"too many requests in flight "
                        f"({self.max_inflight}); retry")
                self._inflight += 1
                admitted = True
            # Replicated control plane: a FOLLOWER serves reads and
            # watches from its local store but never mutates — writes
            # are redirected to the leader with a 307 + Location hint
            # (the client follows and re-pins); a no-leader window is
            # a 503 + Retry-After the client waits out.
            replica = self.registry.replica
            if replica is not None and request.method != "GET" \
                    and not replica.is_leader:
                resp = self._not_leader(request, replica)
                code = resp.status
                return resp
            if replica is not None and request.method == "GET":
                resp = self._check_staleness(request, replica)
                if resp is not None:
                    code = resp.status
                    return resp
            if attrs is not None and self.authorizer is not None \
                    and not self.authorizer.authorize(attrs):
                resp = self._err(errors.ForbiddenError(f"forbidden: {attrs}"))
                code = resp.status
                return resp
            # Aggregation: delegate group/versions claimed by an
            # APIService and NOT served locally (local resources win,
            # like the reference's delegation chain ordering).
            group = request.match_info.get("group")
            if group:
                version = request.match_info.get("version", "")
                plural = request.match_info.get("plural", "")
                if ":" in plural:  # {plural}:batchCreate action suffix
                    plural = plural.split(":", 1)[0]
                spec = self.registry._by_plural.get(plural)
                gv = f"{group}/{version}"
                local = (spec is not None and
                         (spec.api_version == gv
                          or self.registry.scheme.convertible(
                              gv, spec.kind)))
                if not local:
                    target = self._apiservice_target(group, version)
                    if target is not None:
                        resp = await self._proxy(request, target)
                        code = resp.status
                        return resp
            resp = await self._run_handler(request, handler, is_watch)
            code = resp.status
            return resp
        except errors.StatusError as e:
            code = e.code
            return self._err(e)
        except web.HTTPException as e:
            code = e.status
            raise
        except Exception as e:  # noqa: BLE001
            log.exception("handler panic on %s %s", request.method, request.path)
            return self._err(errors.StatusError(f"internal error: {e}"))
        finally:
            server_span.end(code=code)
            if admitted:
                self._inflight -= 1
            elapsed = time.perf_counter() - start
            plural = request.match_info.get("plural", "-")
            REQUEST_LATENCY.observe(elapsed, verb=request.method,
                                    resource=plural)
            if request.query.get("watch") not in ("1", "true"):
                # Watch streams' elapsed is the STREAM LIFETIME, not a
                # request latency — a handful of reconnect-closed
                # watches would dominate the raw p99 this metric
                # exists to make honest (same exclusion as the
                # slow-request log below).
                REQUEST_LATENCY_RAW.observe(elapsed)
            if elapsed > self.slow_request_threshold \
                    and request.query.get("watch") not in ("1", "true"):
                # utiltrace-style slow-op line (the reference's 1s API
                # latency SLO is the bar worth logging against).
                log.info("slow request: %s %s took %.1fms (code %d)",
                         request.method, request.path, 1e3 * elapsed, code)
            if self.audit is not None and attrs is not None:
                await self._audit(request, attrs, code, elapsed)

    def _impersonate(self, request, user: str, imp_user: str):
        """Authorize + apply Impersonate-User/-Group. Returns an error
        response to send, or None on success (request identity
        rewritten in place)."""
        groups = self._groups_for(user) | request.get("cert_groups", set())
        imp_groups = request.headers.getall("Impersonate-Group", [])

        def allowed(resource: str, name: str) -> bool:
            if self.authorizer is None:
                return True
            return self.authorizer.authorize(Attributes(
                user, groups, "impersonate", resource, "", name))

        if not allowed("users", imp_user):
            return self._err(errors.ForbiddenError(
                f"user {user!r} cannot impersonate user {imp_user!r}"))
        for g in imp_groups:
            if not allowed("groups", g):
                return self._err(errors.ForbiddenError(
                    f"user {user!r} cannot impersonate group {g!r}"))
        request["impersonated_by"] = user
        request["user"] = imp_user
        # The impersonated identity's groups are EXACTLY the requested
        # ones (reference semantics) — never the impersonator's own
        # cert groups, and NOT the target's configured user_groups
        # either: 'impersonate users/alice' must not smuggle in
        # system:masters just because alice holds it (that requires
        # 'impersonate groups/system:masters'). _attributes honors
        # this via the impersonated_by marker.
        request["cert_groups"] = set(imp_groups)
        return None

    async def _webhook_user(self, token: str):
        """(user, groups) from the external TokenReview webhook, or
        None. Verdicts cache 30s (denials 5s) — the webhook must not
        sit on every request's hot path."""
        import time as _time
        cached = self._authn_webhook_cache.get(token)
        if cached is not None and cached[2] > _time.monotonic():
            return cached[0]
        import aiohttp
        result = None
        try:
            kw = ({"ssl": self.authn_webhook_ssl}
                  if self.authn_webhook_ssl is not None else {})
            async with aiohttp.ClientSession() as s:
                async with s.post(self.authn_webhook_url,
                                  json={"spec": {"token": token}},
                                  timeout=aiohttp.ClientTimeout(total=5),
                                  **kw) as r:
                    if r.status == 200:
                        body = await r.json()
                        status = body.get("status") or {}
                        u = status.get("user") or {}
                        # authenticated:true WITHOUT a username is a
                        # broken webhook, not an identity — an empty
                        # user must never pass authn.
                        if status.get("authenticated") \
                                and u.get("username"):
                            result = (u["username"],
                                      list(u.get("groups") or ()))
        except Exception as e:  # noqa: BLE001 — authn webhook down: deny
            log.warning("authn webhook failed: %s", e)
            return None  # not cached: recover as soon as it is back
        ttl = 30.0 if result else 5.0
        self._authn_webhook_cache[token] = (result, None,
                                            _time.monotonic() + ttl)
        if len(self._authn_webhook_cache) > 4096:
            # Hard size bound: expired entries first, then OLDEST
            # (insertion order) — a flood of unique junk tokens must
            # not grow memory or turn every insert into an O(n) scan
            # that evicts nothing.
            now_m = _time.monotonic()
            for k in [k for k, v in self._authn_webhook_cache.items()
                      if v[2] <= now_m]:
                del self._authn_webhook_cache[k]
            while len(self._authn_webhook_cache) > 4096:
                self._authn_webhook_cache.pop(
                    next(iter(self._authn_webhook_cache)))
        return result

    def _sa_user(self, token: str) -> Optional[str]:
        """Resolve a bearer against service-account token Secrets
        (reference: the token authenticator; tokens here are opaque,
        not JWTs). A revoked/deleted ServiceAccount's token stops
        working: resolution requires the SA object to still exist."""
        if not token:
            return None
        now = time.monotonic()
        if now - self._sa_index_at > self.sa_index_ttl:
            self._rebuild_sa_index()
            self._sa_index_at = now
        hit = self._sa_index.get(token)
        if hit is None:
            return None
        ns, sa_name, sa_uid, secret_name = hit
        from ..api import types as t
        try:
            sa = self.registry.get("serviceaccounts", ns, sa_name)
        except errors.StatusError:
            return None  # SA deleted: token is dead even if the
            #              secret GC has not caught up yet
        # Two anti-spoof/anti-replay checks (reference: signed JWTs
        # carry the SA UID; opaque tokens verify structurally):
        # 1. the SA object must REFERENCE the token secret — a caller
        #    who can only create Secrets cannot mint an identity;
        # 2. the secret's recorded SA UID must match — a token leaked
        #    before delete/recreate dies with its original SA.
        if secret_name not in sa.secrets:
            return None
        if sa_uid and sa.metadata.uid != sa_uid:
            return None
        return t.service_account_user(ns, sa_name)

    def _bootstrap_user(self, token: str) -> Optional[str]:
        """Bootstrap-token authenticator (kubeadm flow; bootstrap.py)."""
        from .bootstrap import resolve_bootstrap_token
        return resolve_bootstrap_token(self.registry, token)

    def _rebuild_sa_index(self) -> None:
        import base64
        from ..api import types as t
        index: dict[str, tuple] = {}
        try:
            secrets, _rev = self.registry.list("secrets")
        except errors.StatusError:
            secrets = []
        for s in secrets:
            if s.type != t.SECRET_TYPE_SA_TOKEN:
                continue
            try:
                value = base64.b64decode(
                    s.data.get("token", ""), validate=True).decode()
            except (ValueError, UnicodeDecodeError) as e:
                log.warning("sa-token secret %s/%s has undecodable token: %s",
                            s.metadata.namespace, s.metadata.name, e)
                continue
            sa = s.metadata.annotations.get(t.SA_NAME_ANNOTATION, "default")
            uid = s.metadata.annotations.get(t.SA_UID_ANNOTATION, "")
            index[value] = (s.metadata.namespace, sa, uid, s.metadata.name)
        self._sa_index = index

    def _attributes(self, request: web.Request) -> Optional[Attributes]:
        """Authorization attributes for resource requests; None for
        non-resource paths (/healthz, /metrics, ... need authn only)."""
        plural = request.match_info.get("plural")
        if not plural:
            return None
        name = request.match_info.get("name", "")
        sub = request.match_info.get("subresource", "")
        if ":" in plural:
            # Batch action suffix ({plural}:batchCreate) — authorization
            # attributes are those of the underlying per-item verb on
            # the base resource: a batch must never be a policy bypass.
            plural = plural.split(":", 1)[0]
        if request.path.endswith("/bindings:batch"):
            sub = "binding"
        verb = verb_for_request(request.method, bool(name),
                                request.query.get("watch") in ("1", "true"))
        user = request.get("user", "system:anonymous")
        groups = self._request_groups(request, user)
        resource = f"{plural}/{sub}" if sub else plural
        return Attributes(user, groups, verb, resource,
                          request.match_info.get("namespace", ""), name)

    def _request_groups(self, request, user: str) -> set[str]:
        """The authorization groups a request's identity carries — the
        ONE place this is computed (both _attributes and the access
        reviews must agree, or can-i answers diverge from real
        requests). Impersonated identities carry EXACTLY the requested
        groups (set by _impersonate) — configured user_groups of the
        target must not leak in."""
        if request.get("impersonated_by"):
            return set(request.get("cert_groups", set()))
        return self._groups_for(user) | request.get("cert_groups", set())

    def _groups_for(self, user: str) -> set[str]:
        """Configured + username-implied groups (reference: the
        authenticators attach these; here usernames are canonical).
        The single source for both RBAC attributes and the bootstrap
        endpoint's gate."""
        from .bootstrap import BOOTSTRAP_USER_PREFIX, GROUP_BOOTSTRAPPERS
        groups = set(self.user_groups.get(user, ()))
        if user.startswith(BOOTSTRAP_USER_PREFIX):
            groups.add(GROUP_BOOTSTRAPPERS)
        if user.startswith("system:serviceaccount:"):
            groups.add("system:serviceaccounts")
        return groups

    async def _audit(self, request: web.Request, attrs: Attributes,
                     code: int, elapsed: float) -> None:
        body = None
        if request.method in ("POST", "PUT", "PATCH") and \
                self.audit.wants_body(attrs.user, attrs.verb,
                                      attrs.resource, attrs.namespace):
            from ..util import compactcodec
            try:
                raw = await request.read()
                if request.content_type == compactcodec.CONTENT_TYPE:
                    # Compact-negotiated write bodies audit the same
                    # decoded value the handler saw, not _unreadable.
                    body = compactcodec.decode_body(raw)
                else:
                    body = json.loads(raw)
            except Exception:  # noqa: BLE001 — audit must never alter
                body = {"_unreadable": True}  # the response (disconnects,
                # payload errors, bad JSON all land here)
        self.audit.record(
            user=attrs.user, verb=attrs.verb, resource=attrs.resource,
            namespace=attrs.namespace, name=attrs.name, code=code,
            latency_seconds=elapsed, body=body,
            impersonated_by=request.get("impersonated_by", ""))

    @staticmethod
    def _not_leader(request: web.Request, replica) -> web.Response:
        """The follower's answer to a write: 307 with the leader's URL
        in Location (reference analog: apiserver proxying is not done
        here — like etcd, the client is told where the leader is), or
        503 + Retry-After while no leader is known. The 503 carries
        X-Ktpu-No-Leader so clients know the server refused BEFORE
        acting — safe to retry for every verb, mutations included."""
        leader_url = replica.leader_hint()
        if leader_url:
            return web.json_response(
                {"kind": "Status", "status": "Failure", "code": 307,
                 "message": f"not the leader; retry at {leader_url}"},
                status=307,
                headers={"Location": leader_url + str(request.rel_url)})
        e = errors.ServiceUnavailableError(
            "no replication leader elected; retry")
        # Retry-After sized to the election, not the generic 1s: a
        # no-leader window normally closes within one election timeout,
        # and a client parked for 1s would DOMINATE the measured
        # write-unavailability window.
        retry = max(0.05, getattr(replica, "election_timeout", 0.5))
        return web.json_response(
            e.to_dict(), status=e.code,
            headers={"Retry-After": f"{retry:.2f}",
                     "X-Ktpu-No-Leader": "1"})

    async def _run_handler(self, request: web.Request, handler,
                           is_watch: bool) -> web.StreamResponse:
        """The sharding dispatch seam (ApiServerSharding): non-watch
        requests for a sharded resource group run on that group's
        worker loop; watches, unsharded resources, and non-resource
        paths stay on the router (watch streams must write from the
        connection's loop; everything user-visible — authn/authz,
        audit, limits, redirects — already ran there). The request
        body is pre-read HERE so the handler never touches the
        connection from a foreign thread (aiohttp caches the bytes)."""
        pool = self.shards
        if pool is None:
            return await handler(request)
        plural = request.match_info.get("plural", "")
        if ":" in plural:
            plural = plural.split(":", 1)[0]
        shard = shard_for(plural) if plural else None
        if is_watch or shard is None:
            SHARD_INLINE.inc()
            return await handler(request)
        if request.method in ("POST", "PUT", "PATCH") \
                and request.can_read_body:
            await request.read()
        return await pool.dispatch(shard, handler(request))

    def _check_staleness(self, request: web.Request,
                         replica) -> Optional[web.Response]:
        """Bounded-staleness guard for follower reads: a client that
        sent X-Ktpu-Max-Staleness gets its read served only when this
        replica heard from a live leader within that bound (the
        leader itself is always staleness 0). The refusal is a 503
        with X-Ktpu-Stale — the client's read-affinity mode retries
        the LEADER once instead of rotating endpoints (a stale
        follower is not a dead one). Requests without the header keep
        the PR 8 semantics byte-identical: followers serve reads and
        watches unconditionally."""
        raw = request.headers.get("X-Ktpu-Max-Staleness", "")
        if not raw:
            return None
        try:
            bound = min(float(raw), self.follower_staleness_bound)
        except ValueError:
            return None
        if bound != bound:  # NaN parses but compares False with
            return None     # everything — even the leader's 0.0 would
            #                 "exceed" it; treat like a malformed header
        if replica.read_staleness() <= bound:
            return None
        e = errors.ServiceUnavailableError(
            f"follower read refused: staleness exceeds the "
            f"{bound:.2f}s bound")
        headers = {"Retry-After": "0.2", "X-Ktpu-Stale": "1"}
        leader_url = replica.leader_hint()
        if leader_url:
            headers["X-Ktpu-Leader"] = leader_url
        return web.json_response(e.to_dict(), status=e.code,
                                 headers=headers)

    async def _loop_lag_probe(self, name: str) -> None:
        """Lightweight event-loop lag probe (util/loopprobe.py — one
        implementation shared with the scheduler's
        scheduler_loop_lag_ms family): _sum/_count deltas let the
        bench arms attribute per-phase wall-vs-loop time; the gauge is
        a local EWMA for eyeballing /metrics."""
        await loop_lag_probe(LOOP_LAG, LOOP_BUSY,
                             interval=LOOP_PROBE_INTERVAL, loop=name)

    def _start_shard_probe(self, name: str, loop) -> None:
        """Give a freshly spawned shard worker loop its own lag probe
        (called from ShardPool on worker creation, router thread)."""
        self._probe_futs.append(asyncio.run_coroutine_threadsafe(
            self._loop_lag_probe(name), loop))

    @staticmethod
    def _err(e: errors.StatusError) -> web.Response:
        # 429/503 carry Retry-After (reference: the max-in-flight filter
        # and apf send it) so clients back off by the server's clock,
        # not a guess; the REST client honors it.
        headers = {"Retry-After": "1"} if e.code in (429, 503) else None
        return web.json_response(e.to_dict(), status=e.code, headers=headers)

    def _obj_response(self, obj, status: int = 200,
                      convert: str = "") -> web.Response:
        d = to_dict(obj)
        if convert:
            d = self.registry.scheme.from_hub(convert, obj.kind, d)
        return web.json_response(d, status=status)

    def _conv_version(self, request, spec) -> str:
        """The served-but-not-stored version this request speaks, or ""
        when it speaks the storage version. Conversion happens at the
        server edge (reference: the apiserver decodes any served
        version to the hub, stores one, and answers in kind)."""
        group = request.match_info.get("group")
        version = request.match_info.get("version")
        if not group or not version:
            return ""
        rv = f"{group}/{version}"
        if rv == spec.api_version:
            return ""
        if self.registry.scheme.convertible(rv, spec.kind):
            return rv
        return ""

    def _body_to_hub(self, data: dict, rv: str, spec) -> dict:
        """Versioned request body -> hub-versioned wire dict, applying
        the VERSION'S OWN defaulting first (a beta default may differ
        from the hub's). A body claiming a DIFFERENT version than the
        URL is a 400 (reference behavior) — silently converting a
        v1-shaped body "up" from v1beta1 would corrupt it."""
        scheme = self.registry.scheme
        body_av = data.get("api_version", "")
        if body_av and body_av != rv:
            raise errors.BadRequestError(
                f"body api_version {body_av!r} does not match the "
                f"request URL's {rv!r}")
        data = dict(data)
        data.setdefault("api_version", rv)
        data.setdefault("kind", spec.kind)
        try:
            versioned = scheme.decode(data)
            data = to_dict(versioned)
            data["api_version"], data["kind"] = rv, spec.kind
        except KeyError:
            pass  # no class registered for this version: convert raw
        return scheme.to_hub(rv, spec.kind, data)

    # -- routes -----------------------------------------------------------

    def _routes(self) -> None:
        r = self.app.router
        r.add_get("/healthz", self._healthz)
        r.add_get("/readyz", self._healthz)
        # Replication introspection (like etcd's /v3/maintenance/status;
        # authn-exempt like /healthz): role/term/leader hint/commit rev
        # — the failover harness's time-to-new-leader probe.
        r.add_get("/ha/v1/status", self._ha_status)
        r.add_get("/version", self._version)
        r.add_get("/metrics", self._metrics)
        # ktrace surface (non-resource path: authn-only, like /metrics):
        # GET serves this process's bounded span collector — in a
        # LocalCluster every component shares the process, so one GET
        # sees the whole pod lifecycle; POST ingests spans pushed by
        # out-of-process components (multi-host agents).
        r.add_get("/debug/v1/traces", self._debug_traces)
        r.add_post("/debug/v1/traces", self._debug_traces_ingest)
        r.add_get("/debug/v1/query", self._debug_query)
        r.add_get("/debug/v1/alerts", self._debug_alerts)
        r.add_get("/debug/v1/storage", self._debug_storage)
        # loopsan occupancy table (armed via TPU_LOOPSAN=1; disarmed
        # returns an empty, armed=false snapshot) — the per-seam
        # attribution behind the coarse loop_busy gauges.
        r.add_get("/debug/v1/loopprof", self._debug_loopprof)
        r.add_get("/apis", self._discovery)
        # kubeadm-join analog: exchange a bootstrap token for a durable
        # node credential (bootstrap.py; the CSR-signing step's end
        # state, authz'd to system:bootstrappers explicitly below).
        # TokenReview (reference: authentication.k8s.io/v1) — the
        # delegated-authn half of the kubelet model: node servers POST
        # a caller's bearer token here and get back its identity
        # (kubelet --authentication-token-webhook).
        r.add_post("/apis/authentication/v1/tokenreviews",
                   self._token_review)
        # Access reviews (reference: authorization.k8s.io/v1,
        # ``kubectl auth can-i``): virtual create-only resources that
        # evaluate the live authorizer instead of persisting anything.
        r.add_post("/apis/authorization/v1/selfsubjectaccessreviews",
                   self._access_review)
        r.add_post("/apis/authorization/v1/subjectaccessreviews",
                   self._access_review)
        r.add_post("/bootstrap/v1/node-credentials", self._node_credentials)
        # TLS bootstrap (kubeadm discovery + kubelet TLS bootstrap):
        # the CA cert is public (joiners verify it against a sha256
        # pin); CSR signing needs a bootstrap token.
        r.add_get("/bootstrap/v1/ca", self._serve_ca)
        r.add_post("/bootstrap/v1/sign-csr", self._sign_csr)
        base = "/api/{group}/{version}"
        for prefix in (base + "/namespaces/{namespace}/{plural}", base + "/{plural}"):
            r.add_get(prefix, self._list_or_watch)
            # _create also serves POST {plural}:batchCreate — the colon
            # action suffix lands inside the {plural} segment, so the
            # collection route matches it without a second resource.
            r.add_post(prefix, self._create)
            # Batched scheduler binds: one request, N pods/binding
            # subresource writes (see _bind_batch).
            r.add_post(prefix + "/bindings:batch", self._bind_batch)
            r.add_delete(prefix, self._delete_collection)
            r.add_get(prefix + "/{name}", self._get)
            r.add_put(prefix + "/{name}", self._update)
            r.add_patch(prefix + "/{name}", self._patch)
            r.add_delete(prefix + "/{name}", self._delete)
            r.add_put(prefix + "/{name}/{subresource}", self._update)
            r.add_patch(prefix + "/{name}/{subresource}", self._patch)
            r.add_post(prefix + "/{name}/{subresource}", self._subresource_post)

    async def _healthz(self, request):
        return web.Response(text="ok")

    async def _ha_status(self, request):
        replica = self.registry.replica
        if replica is None:
            return web.json_response({"replicated": False})
        return web.json_response({"replicated": True, **replica.status()})

    async def _token_review(self, request):
        """POST {"spec": {"token": ...}} -> TokenReview with status
        {authenticated, user:{username, groups}}. Runs the same
        authenticator union the request middleware uses (static tokens,
        SA tokens, bootstrap tokens). Caller must be authenticated
        (non-resource path: authn-only, like /metrics)."""
        try:
            body = await request.json()
            token = str((body.get("spec") or {}).get("token") or "")
        except Exception:  # noqa: BLE001
            return self._err(errors.InvalidError(
                'body must be {"spec": {"token": "..."}}'))
        user = None
        if token and self.tokens is not None:
            user = (self.tokens.get(token) or self._sa_user(token)
                    or self._bootstrap_user(token))
        if user:
            status = {"authenticated": True,
                      "user": {"username": user,
                               "groups": sorted(self._groups_for(user))}}
        else:
            status = {"authenticated": False}
        return web.json_response({"kind": "TokenReview",
                                  "api_version": "authentication/v1",
                                  "status": status})

    async def _access_review(self, request):
        """POST Self/SubjectAccessReview -> status {allowed, reason}.

        Reference: ``staging/src/k8s.io/apiserver/plugin/pkg/
        authorizer`` + the authorization.k8s.io/v1 virtual resources.
        Self-review answers for the CALLER (post-impersonation, so
        ``--as`` composes); subject-review answers for a spec-named
        identity and is gated on the caller holding ``create`` on
        ``subjectaccessreviews`` — otherwise any authenticated user
        could map out everyone else's permissions."""
        self_review = request.path.endswith("selfsubjectaccessreviews")
        kind = ("SelfSubjectAccessReview" if self_review
                else "SubjectAccessReview")
        try:
            body = await request.json()
            spec = body.get("spec") or {}
            ra = spec.get("resource_attributes") or {}
            if not isinstance(spec, dict) or not isinstance(ra, dict):
                raise TypeError
            verb = str(ra.get("verb") or "")
            resource = str(ra.get("resource") or "")
            raw_groups = spec.get("groups") or []
            if not isinstance(raw_groups, (list, tuple)):
                raise TypeError
            spec_groups = {str(g) for g in raw_groups}
        except Exception:  # noqa: BLE001
            return self._err(errors.InvalidError(
                'body must be {"spec": {"resource_attributes": '
                '{"verb", "resource", ...}, "groups": [...]}}'))
        if not verb or not resource:
            return self._err(errors.InvalidError(
                "spec.resource_attributes needs verb and resource"))
        caller = request.get("user", Attributes.ANONYMOUS)
        # Same group derivation as _attributes — a review must answer
        # what a real request would get.
        caller_groups = self._request_groups(request, caller)
        if self_review:
            subject, subj_groups = caller, caller_groups
        else:
            gate = Attributes(caller, caller_groups, "create",
                              "subjectaccessreviews")
            if self.authorizer is not None \
                    and not self.authorizer.authorize(gate):
                return self._err(errors.ForbiddenError(
                    f"forbidden: {gate}"))
            subject = str(spec.get("user") or "")
            if not subject:
                return self._err(errors.InvalidError(
                    "SubjectAccessReview spec.user is required"))
            # The subject's real requests get configured+implied groups
            # from _groups_for; spec.groups adds to that (the reference
            # SAR likewise unions authenticator-attached groups).
            subj_groups = self._groups_for(subject) | spec_groups
        attrs = Attributes(subject, subj_groups, verb, resource,
                           str(ra.get("namespace") or ""),
                           str(ra.get("name") or ""))
        allowed = (self.authorizer is None
                   or self.authorizer.authorize(attrs))
        status = {"allowed": allowed}
        if not allowed:
            status["reason"] = f"no RBAC rule grants {attrs}"
        return web.json_response({"kind": kind,
                                  "api_version": "authorization/v1",
                                  "status": status}, status=201)

    async def _node_credentials(self, request):
        """POST {"node_name": ...} -> {"token", "user", "node_name"}.
        Callers: bootstrap-token users (system:bootstrappers) or
        cluster admins; this is a non-resource path, so the group gate
        lives here rather than in RBAC rules."""
        from ..api import rbac as rbacapi
        from .bootstrap import GROUP_BOOTSTRAPPERS, mint_node_credential
        user = request.get("user", "system:anonymous")
        groups = self._groups_for(user) | request.get("cert_groups", set())
        def record(code: int, name: str = "") -> None:
            # Credential minting MUST be auditable — this is a
            # non-resource path, so the middleware's attrs-gated audit
            # skips it; record explicitly (audit may be disabled).
            if self.audit is not None:
                self.audit.record(user=user, verb="mint",
                                  resource="node-credentials",
                                  namespace="kube-system", name=name,
                                  code=code, latency_seconds=0.0)

        if self.tokens is not None and GROUP_BOOTSTRAPPERS not in groups \
                and rbacapi.GROUP_MASTERS not in groups:
            record(403)
            return self._err(errors.ForbiddenError(
                f"user {user!r} is not a bootstrapper"))
        try:
            body = await request.json()
            node_name = body.get("node_name", "")
        except Exception:  # noqa: BLE001
            return self._err(errors.InvalidError("body must be JSON"))
        try:
            cred = mint_node_credential(self.registry, node_name)
        except errors.StatusError as e:
            record(e.code, node_name)
            raise
        record(200, node_name)
        # The fresh SA token must authenticate immediately — invalidate
        # the authenticator's index instead of waiting out its TTL.
        self._sa_index_at = float("-inf")
        if self.dns_address:
            cred["dns_server"] = self.dns_address
        return web.json_response(cred)

    async def _serve_ca(self, request):
        """Public CA cert + fingerprint (kubeadm cluster-info analog:
        joiners verify the cert against an out-of-band sha256 pin, so
        serving it needs no authn — see middleware exemption)."""
        if self.cert_authority is None:
            raise errors.NotFoundError("cluster does not run TLS")
        return web.json_response({
            "ca_pem": self.cert_authority.cert_pem.decode(),
            "fingerprint": self.cert_authority.fingerprint(),
        })

    async def _sign_csr(self, request):
        """POST {"node_name", "csr_pem"} -> {"cert_pem"}: sign a
        joiner's CSR as the node identity (CN/O chosen server-side —
        the CSR only contributes a public key). Gated exactly like
        node-credentials: bootstrap token or cluster admin. The private
        key never crosses the wire (kubelet.go:96 TLS bootstrap).

        ``"usage": "serving"`` (+ ``"sans": [...]``) mints the node's
        SERVING cert instead — the kubelet serving-cert CSR flow. The
        claimed SANs are admitted plus the connection's observed peer
        address (the same trust the reference's default node-serving
        approver places in node-reported addresses)."""
        from ..api import rbac as rbacapi
        from .bootstrap import (GROUP_BOOTSTRAPPERS, NODES_NAMESPACE,
                                mint_node_credential)
        if self.cert_authority is None:
            raise errors.NotFoundError("cluster does not run TLS")
        user = request.get("user", "system:anonymous")
        groups = self._groups_for(user) | request.get("cert_groups", set())

        def authorized(node_name: str) -> bool:
            """Bootstrappers and admins sign for any node; a node's
            OWN identity may renew itself (kubelet cert rotation,
            pkg/kubelet/certificate) — and only itself. The identity
            this endpoint MINTS is the node ServiceAccount user
            (mint_node_credential), so that is what a rotating node
            authenticates as; the kubelet-style system:node:<name>
            form is accepted too."""
            if self.tokens is None:
                return True
            from ..api.types import service_account_user
            own = {f"system:node:{node_name}",
                   service_account_user(NODES_NAMESPACE,
                                        f"node-{node_name}")}
            return (GROUP_BOOTSTRAPPERS in groups
                    or rbacapi.GROUP_MASTERS in groups
                    or user in own)

        def record(code: int, name: str = "") -> None:
            if self.audit is not None:
                self.audit.record(user=user, verb="sign", resource="csr",
                                  namespace=NODES_NAMESPACE, name=name,
                                  code=code, latency_seconds=0.0)
        try:
            body = await request.json()
            node_name = body.get("node_name", "")
            csr_pem = body.get("csr_pem", "").encode()
            serving = body.get("usage", "") == "serving"
            sans = [str(s) for s in body.get("sans", [])][:16]
        except Exception:  # noqa: BLE001
            record(400)
            return self._err(errors.InvalidError("body must be JSON"))
        if not authorized(node_name):
            record(403, node_name)
            return self._err(errors.ForbiddenError(
                f"user {user!r} may not sign certificates for node "
                f"{node_name!r}"))
        if serving:
            # SAN admission policy (the reference's serving-cert CSR
            # approver restricts SANs to the Node's recorded
            # addresses): a bootstrap token must NOT mint a
            # cluster-CA serverAuth cert for arbitrary names — that
            # would defeat client hostname verification cluster-wide.
            # Admitted: the observed peer address, loopback, and the
            # node name when it is a bare single label (never an
            # FQDN/IP someone else answers on). Everything else is
            # dropped.
            peer = request.remote or ""
            admitted = []
            for claim in sans:
                if not claim:
                    continue
                if claim == peer or (claim == node_name
                                     and "." not in claim):
                    admitted.append(claim)
                elif "." in claim and peer:
                    # FQDN hostnames are admitted only when OUR
                    # resolver maps them to the requester — so a
                    # bootstrap token cannot mint a cert for the
                    # apiserver's (or anyone else's) name.
                    import socket as socketlib
                    try:
                        resolved = await asyncio.to_thread(
                            socketlib.gethostbyname, claim)
                    except OSError:
                        continue
                    if resolved == peer:
                        admitted.append(claim)
            sans = admitted
            if peer and peer not in sans:
                sans.append(peer)
            # Loopback SANs only for loopback joiners (local/dev): a
            # remote node's serving cert valid for 127.0.0.1 would
            # verify as ANY node whenever a client falls back to
            # loopback — one compromised node impersonates them all.
            if not peer or peer in ("127.0.0.1", "::1", "localhost"):
                for addr in ("127.0.0.1", "localhost"):
                    if addr not in sans:
                        sans.append(addr)
        # Validate the CSR BEFORE any durable mutation: a garbage CSR
        # must not leave behind a credential Secret + ClusterRoleBinding
        # nobody received (and must not audit as a success).
        try:
            from cryptography import x509 as _x509
            _x509.load_pem_x509_csr(csr_pem)
        except Exception as e:  # noqa: BLE001
            record(400, node_name)
            return self._err(errors.InvalidError(f"bad CSR: {e}"))
        # Reuse the credential mint for the RBAC objects + name checks;
        # the cert carries the same username so bindings apply as-is.
        try:
            cred = mint_node_credential(self.registry, node_name)
            cert_pem = self.cert_authority.sign_csr_pem(
                csr_pem, user=cred["user"], server_auth=serving,
                sans=sans if serving else ())
        except errors.StatusError as e:
            record(e.code, node_name)
            raise
        except ValueError as e:
            record(400, node_name)
            return self._err(errors.InvalidError(f"bad CSR: {e}"))
        record(200, node_name)
        return web.json_response({"cert_pem": cert_pem.decode(),
                                  "user": cred["user"],
                                  "node_name": node_name})

    async def _version(self, request):
        from .. import __version__
        return web.json_response({"version": __version__, "platform": "tpu"})

    async def _metrics(self, request):
        # True request-latency percentiles recomputed at scrape time
        # from the raw-sample histogram — the bench harness reads
        # these gauges instead of inferring quantiles from bucket
        # edges (perf/density.py satellite of the r05 finding). One
        # copy + sort for all three (raw_quantiles): a scrape must not
        # stall the router loop re-sorting 120k samples per quantile.
        # Off-loop: sorting 120k retained samples inline would stall
        # watch fan-out and binds sharing the router loop per scrape.
        vals = await asyncio.to_thread(
            REQUEST_LATENCY_RAW.raw_quantiles, (0.5, 0.9, 0.99))
        if vals is not None:
            for q, v in zip((50, 90, 99), vals):
                REQUEST_LATENCY_RAW_Q.set(round(v * 1e3, 3), q=str(q))
        return web.Response(text=METRICS.render(), content_type="text/plain")

    async def _debug_traces(self, request):
        """``GET /debug/v1/traces?trace_id=&pod=&component=&limit=`` —
        matching spans from the in-process collector, oldest first
        (``ktl trace pod|gang`` reads this)."""
        q = request.query
        limit = self._int_param(q.get("limit", "0") or "0", "limit")
        # No default cap beyond the collector's own ring bound: a
        # silent half-buffer truncation would read as "incomplete
        # traces" to an investigation exporting everything.
        spans = tracing.COLLECTOR.snapshot(
            trace_id=q.get("trace_id", ""), pod=q.get("pod", ""),
            component=q.get("component", ""), limit=limit)
        return web.json_response({
            "spans": spans,
            "dropped": tracing.COLLECTOR.dropped,
            "buffered": len(tracing.COLLECTOR),
        })

    async def _debug_loopprof(self, request):
        """``GET /debug/v1/loopprof?top=`` — ranked event-loop
        occupancy by seam from the TPU_LOOPSAN sanitizer, plus any
        over-threshold callback violations with stacks."""
        from ..analysis import loopsan
        top = self._int_param(request.query.get("top", "0") or "0", "top")
        snap = loopsan.publish_metrics()
        if top and top > 0:
            snap["seams"] = snap["seams"][:top]
        return web.json_response(snap)

    def _pipeline_or_404(self):
        """The co-located kmon pipeline, or NotFound — the route does
        not exist unless the ClusterMetricsPipeline gate is on AND the
        composer wired a provider (gate off must be byte-identical, and
        a remote controller-manager has no in-process TSDB to read)."""
        from ..util.features import GATES
        pipeline = (self.metrics_pipeline_provider()
                    if self.metrics_pipeline_provider is not None
                    else None)
        if pipeline is None \
                or not GATES.enabled("ClusterMetricsPipeline"):
            raise errors.NotFoundError(
                "metrics pipeline not enabled (ClusterMetricsPipeline "
                "gate off, or no co-located controller-manager)")
        return pipeline

    @staticmethod
    def _float_param(value, name: str) -> float:
        try:
            return float(value)
        except (TypeError, ValueError):
            raise errors.BadRequestError(
                f"query parameter {name!r} must be a number, "
                f"got {value!r}") from None

    async def _debug_query(self, request):
        """``GET /debug/v1/query?query=<expr>[&time=][&start=&end=
        &step=]`` — PromQL-lite over the kmon TSDB. With start/end:
        a range query (matrix); otherwise instant (vector/scalar).
        Instant evaluation is one pass over a bounded in-memory store
        — microseconds, safe inline on the router loop. RANGE queries
        re-evaluate the expression per step (up to 11k steps × a full
        series scan each), so they run in a thread — the TSDB is
        lock-protected for exactly this reader — instead of stalling
        watches, binds, and heartbeats sharing the router loop."""
        from ..monitoring.promql import PromQLError
        pipeline = self._pipeline_or_404()
        q = request.query
        expr = q.get("query", "")
        if not expr:
            raise errors.BadRequestError("missing 'query' parameter")
        try:
            if "start" in q or "end" in q:
                import time as _time
                end = (self._float_param(q["end"], "end")
                       if "end" in q else _time.time())
                start = (self._float_param(q["start"], "start")
                         if "start" in q else end - 300.0)
                step = self._float_param(
                    q.get("step", "") or str(pipeline.interval), "step")
                data = await asyncio.to_thread(
                    pipeline.query_range, expr, start, end, step)
            else:
                at = (self._float_param(q["time"], "time")
                      if "time" in q else None)
                data = pipeline.query_instant(expr, at)
        except PromQLError as e:
            raise errors.BadRequestError(str(e)) from None
        return web.json_response({"status": "success", "data": data})

    async def _debug_alerts(self, request):
        """``GET /debug/v1/alerts`` — active (pending + firing) kmon
        alerts plus pipeline/TSDB bound accounting."""
        pipeline = self._pipeline_or_404()
        return web.json_response({
            "alerts": pipeline.alerts(),
            "stats": pipeline.stats(),
        })

    async def _debug_storage(self, request):
        """``GET /debug/v1/storage`` — the ``ktl describe store`` view:
        current vs compacted revision, WAL footprint, retained watch
        history, attached watchers, encode-cache occupancy, and the
        active compaction policy. The numbers the endurance gate reads;
        every field is a lock-protected O(1) store read, safe inline."""
        store = self.registry.store
        policy = self.registry.compaction_policy
        rev = store.revision
        compact_rev = store.compact_rev
        # Lifetime records/ops: 1.0 on the per-object write path,
        # ~1/chunk under BatchWriteTxn — the amortization number the
        # endurance gate asserts. null until the first durable write.
        ops_total = store.wal_ops_total
        return web.json_response({
            "revision": rev,
            "compact_revision": compact_rev,
            "compact_lag": rev - compact_rev,
            "durable": store.durable,
            "wal_bytes": store.wal_bytes,
            "wal_records": store.wal_records,
            "wal_records_total": store.wal_records_total,
            "wal_ops_total": ops_total,
            "wal_records_per_create": (
                None if not ops_total
                else store.wal_records_total / ops_total),
            "snapshots": store.snapshots,
            "compactions": store.compactions,
            "history_entries": store.history_len,
            "watchers": store.watcher_count,
            "encode_cache": self.registry.encode_cache.stats(),
            "compaction_policy": None if policy is None else {
                "retention_revisions": policy.retention_revisions,
                "retention_seconds": policy.retention_seconds,
                "interval_seconds": policy.interval_seconds,
            },
        })

    async def _debug_traces_ingest(self, request):
        """``POST {"spans": [...]}`` — span ingest for out-of-process
        components. Malformed items are skipped, never an error: a
        telemetry push must not drive a remote agent into backoff."""
        body = await self._body_obj(request)
        spans = body.get("spans") if isinstance(body, dict) else None
        if not isinstance(spans, list):
            raise errors.BadRequestError(
                'body must be {"spans": [span, ...]}')
        return web.json_response(
            {"ingested": tracing.COLLECTOR.ingest(spans)})

    async def _discovery(self, request):
        out = []
        for spec in self.registry._by_plural.values():
            out.append({
                "name": spec.plural, "kind": spec.kind,
                "api_version": spec.api_version, "namespaced": spec.namespaced,
            })
        out.extend(await self._aggregated_discovery())
        return web.json_response({"resources": out})

    async def _aggregated_discovery(self) -> list:
        """Merge aggregated apiservers' resources into /apis (reference:
        the aggregator's discovery merge), filtered to each APIService's
        claimed group and briefly cached."""
        if time.monotonic() - self._agg_discovery_at < 15.0:
            return self._agg_discovery
        merged: list = []
        try:
            services, _rev = self.registry.list("apiservices")
        except errors.StatusError:
            services = []
        if services:
            import aiohttp
            for svc in services:
                target = self._apiservice_target(svc.spec.group,
                                                 svc.spec.version)
                if target is None:
                    continue
                gv = f"{svc.spec.group}/{svc.spec.version}"
                try:
                    timeout = aiohttp.ClientTimeout(total=5)
                    async with aiohttp.ClientSession(timeout=timeout) as s:
                        async with s.get(f"{target}/apis") as resp:
                            data = await resp.json()
                    merged.extend(r for r in data.get("resources", [])
                                  if r.get("api_version") == gv)
                except Exception as e:  # noqa: BLE001
                    log.warning("aggregated discovery: extension %s "
                                "unreachable, skipping: %s", target, e)
                    continue
        self._agg_discovery = merged
        self._agg_discovery_at = time.monotonic()
        return merged

    # -- aggregation (kube-aggregator analog) -----------------------------

    def _apiservice_target(self, group: str, version: str) -> Optional[str]:
        """Base URL of the APIService delegated this group/version, or
        None when served locally. Resolution: direct url, else the
        referenced Service's first ready endpoint via its node address
        (same hostNetwork convention the ServiceProxy uses)."""
        try:
            services, _rev = self.registry.list("apiservices")
        except errors.StatusError:
            return None
        for svc in services:
            if (svc.spec.group, svc.spec.version) != (group, version):
                continue
            if svc.spec.url:
                return svc.spec.url.rstrip("/")
            try:
                eps = self.registry.get("endpoints",
                                        svc.spec.service_namespace,
                                        svc.spec.service_name)
            except errors.StatusError:
                return None
            for subset in eps.subsets:
                for addr in subset.addresses:
                    host = addr.ip
                    if addr.node_name:
                        try:
                            node = self.registry.get("nodes", "",
                                                     addr.node_name)
                            if node.status.addresses:
                                host = node.status.addresses[0].address
                        except errors.StatusError:
                            pass
                    return f"http://{host}:{svc.spec.service_port}"
            return None
        return None

    def _proxy_sess(self):
        """One long-lived session for the aggregation data path (the
        RESTClient._sess pattern) — per-request sessions would pay
        connector setup + a fresh TCP connection every call."""
        import aiohttp
        if self._proxy_session is None or self._proxy_session.closed:
            self._proxy_session = aiohttp.ClientSession()
        return self._proxy_session

    async def _proxy(self, request: web.Request, target: str) -> web.StreamResponse:
        """Reverse-proxy one request to an extension apiserver,
        streaming the response. Watch streams run unbounded; everything
        else gets a deadline so a hung extension cannot pin
        max-in-flight slots forever."""
        import aiohttp
        url = target + request.rel_url.path_qs
        body = await request.read()
        is_watch = request.query.get("watch") in ("1", "true")
        timeout = aiohttp.ClientTimeout(
            total=None if is_watch else 60.0)
        # Forward the negotiation headers UNTOUCHED (raw header values,
        # parameters included): the extension decodes the body by the
        # caller's exact Content-Type — a compact-negotiated write must
        # not arrive re-labeled, and aiohttp must not substitute its
        # octet-stream default for a body whose type the caller named.
        fwd_headers = {}
        for name in ("Content-Type", "Accept"):
            value = request.headers.get(name)
            if value is not None:
                fwd_headers[name] = value
        try:
            upstream = await self._proxy_sess().request(
                request.method, url, data=body or None, timeout=timeout,
                headers=fwd_headers)
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return self._err(errors.ServiceUnavailableError(
                f"aggregated apiserver unreachable: {e}"))
        try:
            resp = web.StreamResponse(status=upstream.status)
            # The response Content-Type rides back verbatim too —
            # ``upstream.content_type`` would strip parameters (e.g.
            # a charset) the extension set.
            resp.headers["Content-Type"] = upstream.headers.get(
                "Content-Type", "application/json")
            await resp.prepare(request)
            async for chunk in upstream.content.iter_any():
                await resp.write(chunk)
            return resp
        except (ConnectionResetError, asyncio.CancelledError,
                asyncio.TimeoutError):
            return resp
        finally:
            upstream.close()

    # -- helpers ----------------------------------------------------------

    def _ctx(self, request) -> tuple[str, str]:
        plural = request.match_info["plural"]
        ns = request.match_info.get("namespace", "")
        return plural, ns

    async def _body_obj(self, request, op: str = "other"):
        """Request body -> value, negotiated by ``Content-Type``.

        JSON is the default and the fallback for every media type this
        server does not know better (the patch media types, bare
        octet-stream POSTs). The compact codec's type decodes framed
        msgpack when the gate is on; any OTHER ``application/x-ktpu-*``
        type — or the compact type at a gate-off server — is a clean
        415, so a codec mismatch is diagnosable instead of surfacing
        as "invalid JSON body". ``op`` names the verb for the
        decode-share seams and the compact-write metrics."""
        raw = await request.read()
        from ..util import compactcodec
        ctype = request.content_type
        if ctype.startswith("application/x-ktpu"):
            if ctype != compactcodec.CONTENT_TYPE:
                raise errors.UnsupportedMediaTypeError(
                    f"unsupported media type {ctype!r}: this server "
                    f"speaks {compactcodec.CONTENT_TYPE} and "
                    f"application/json")
            if not compactcodec.enabled():
                raise errors.UnsupportedMediaTypeError(
                    f"{ctype} not negotiated: the CompactWireCodec "
                    f"gate is off on this server (send "
                    f"application/json)")
            try:
                if self.codec_pool is not None:
                    data = await self.codec_pool.decode_body(
                        raw, codec="compact", op=op)
                else:
                    data = compactcodec.decode_request(raw, "compact", op)
            except ValueError as e:
                raise errors.BadRequestError(
                    f"invalid compact ({ctype}) body: {e}") from None
            COMPACT_WRITE_REQUESTS.inc(verb=op)
            compactcodec.count_request("compact", f"{op}_decode",
                                       len(raw))
            return data
        try:
            if self.codec_pool is not None:
                # ApiServerCodecOffload: large bodies (512-item
                # batchCreate payloads) parse off the event loop; the
                # pool's size threshold keeps small ones inline.
                data = await self.codec_pool.decode_body(raw, op=op)
            else:
                data = compactcodec.decode_request(raw, "json", op)
        except json.JSONDecodeError as e:
            raise errors.BadRequestError(
                f"invalid JSON body ({ctype or 'application/json'}): "
                f"{e}") from None
        if compactcodec.enabled():
            # Like-for-like codec_wire_* accounting (the LIST path's
            # rule): the JSON half counts too while the gate is on, so
            # a json-vs-compact write-path delta is computable.
            compactcodec.count_request("json", f"{op}_decode", len(raw))
        return data

    async def _mutate(self, fn, *args):
        """Dispatch a registry mutation via the shared policy point
        (:meth:`Registry.run`): inline for in-memory stores, worker
        thread when a WAL append can block on disk."""
        return await self.registry.run(fn, *args)

    @staticmethod
    def _accepts_compact(request) -> bool:
        """Did this request negotiate a compact RESPONSE body (gate on
        AND the Accept header asks)? Content-Type (the request body's
        codec) is negotiated independently in :meth:`_body_obj` — a
        client may mix."""
        from ..util import compactcodec
        return (compactcodec.enabled()
                and compactcodec.accepts_compact(
                    request.headers.get("Accept", "")))

    # -- verb handlers ----------------------------------------------------

    async def _create(self, request):
        plural, ns = self._ctx(request)
        if plural.endswith(":batchCreate"):
            return await self._batch_create(
                request, plural[: -len(":batchCreate")], ns)
        spec = self.registry.spec_for(plural)
        data = await self._body_obj(request, op="create")
        conv = self._conv_version(request, spec)
        created = await self._create_one(plural, ns, spec, data, conv)
        if plural.endswith("webhookconfigurations"):
            self.webhooks.invalidate()
        if not conv:
            # Encode the response THROUGH the serialize-once cache: the
            # same bytes serve this reply, the create's watch fan-out
            # line to every watcher, and any immediate GET.
            d = to_dict(created)
            rv = d.get("metadata", {}).pop("resource_version", None)
            if rv is not None:
                from ..util import compactcodec
                key = self.registry._key(spec, created.metadata.namespace,
                                         created.metadata.name)
                if self._accepts_compact(request):
                    # Negotiated compact response: one frame around
                    # the cached compact payload (shared with the
                    # watch fan-out's frame for this same revision).
                    body = compactcodec.encode_response_create(
                        lambda: compactcodec.frame(
                            self.registry.encoded_value(
                                key, d, int(rv), codec="compact")))
                    compactcodec.count_request("compact",
                                               "create_encode",
                                               len(body))
                    return web.Response(
                        body=body, status=201,
                        content_type=compactcodec.CONTENT_TYPE)
                body = self.registry.encoded_value(key, d, int(rv))
                if compactcodec.enabled():
                    compactcodec.count_request("json", "create_encode",
                                               len(body))
                return web.Response(
                    body=body,
                    status=201, content_type="application/json")
        return self._obj_response(created, status=201, convert=conv)

    def _decode_create_body(self, ns: str, spec, data: dict, conv: str):
        """Versioned request body dict -> decoded hub object, namespace
        applied. Shared by the single and batch create paths."""
        if conv:
            data = self._body_to_hub(data, conv, spec)
        data.setdefault("api_version", spec.api_version)
        data.setdefault("kind", spec.kind)
        try:
            obj = self.registry.scheme.decode(data)
        except (TypeError, ValueError, KeyError) as e:
            raise errors.BadRequestError(
                f"undecodable {spec.kind} body: {e}") from None
        if ns:
            obj.metadata.namespace = ns
        return obj

    async def _create_one(self, plural: str, ns: str, spec, data: dict,
                          conv: str):
        """The full one-object create pipeline (decode, external
        webhooks, in-tree admission via the registry, store write)."""
        obj = self._decode_create_body(ns, spec, data, conv)
        if self.webhooks.has_hooks("CREATE", plural):
            d = await self.webhooks.run_mutating(
                "CREATE", plural, ns, obj.metadata.name, to_dict(obj))
            obj = self.registry.scheme.decode(d)
            # Validating hooks see the FINAL request object — in-tree
            # defaulting + admission applied (dry-run pass) — matching
            # the reference's mutate-everything-then-validate ordering
            # (admission.go: validating phase after all mutation). The
            # extra pass is skipped when no validating hook matches.
            if self.webhooks.has_validating("CREATE", plural):
                # Deep-copy for the preview: dry_run skips store side
                # effects but stamp/default/admission still mutate the
                # instance in place, and the real write below must not
                # receive a pre-mutated object (idempotence of every
                # admission plugin is not a contract we want to lean on).
                admitted = await self._mutate(
                    self.registry.create, obj_deepcopy(obj), True)
                await self.webhooks.run_validating(
                    "CREATE", plural, ns, obj.metadata.name,
                    to_dict(admitted))
        return await self._mutate(self.registry.create, obj)

    #: Items per inline dispatch of a batch — the no-webhook path runs
    #: synchronous create/bind pipelines back to back, and the shared
    #: event loop (watch fan-out, other requests) must get a turn
    #: between chunks; MAX_BATCH_ITEMS alone only bounds the stall.
    BATCH_DISPATCH_CHUNK = 64

    @staticmethod
    def _batch_items(body, shape: str) -> list:
        """Validated ``items`` list of a batch request body (shared
        envelope rules for every batch subresource)."""
        items = body.get("items") if isinstance(body, dict) else None
        if not isinstance(items, list):
            raise errors.BadRequestError(
                f'batch body must be {{"items": [{shape}, ...]}}')
        if len(items) > MAX_BATCH_ITEMS:
            raise errors.BadRequestError(
                f"batch of {len(items)} exceeds the {MAX_BATCH_ITEMS}-item "
                f"limit; split the request")
        return items

    async def _dispatch_batch(self, fn, ready: list) -> list:
        """Run a registry batch op in event-loop-friendly chunks."""
        outs: list = []
        for off in range(0, len(ready), self.BATCH_DISPATCH_CHUNK):
            outs.extend(await self._mutate(
                fn, ready[off:off + self.BATCH_DISPATCH_CHUNK]))
            if off + self.BATCH_DISPATCH_CHUNK < len(ready):
                await asyncio.sleep(0)  # let watchers/requests breathe
        return outs

    def _batch_response(self, request, kind: str, results: list,
                        emit=None, emit_compact=None,
                        compact_ok: bool = True) -> web.Response:
        """Positional per-item BatchResult from ``(obj, err)`` pairs;
        ``emit(obj) -> dict | None`` adds a success payload on the
        JSON path, ``emit_compact(obj) -> bytes | None`` its compact
        twin (pre-encoded payload — typically the serialize-once
        cache line, embedded without a re-pack). The response body is
        compact when the request negotiated it via Accept (and
        ``compact_ok`` — version-converting requests stay JSON),
        byte-identical JSON otherwise."""
        from ..util import compactcodec
        if compact_ok and self._accepts_compact(request):
            def assemble() -> bytes:
                payloads = []
                for obj, err in results:
                    if err is not None:
                        BATCH_ITEMS.inc(kind=kind, result="error")
                        payloads.append(compactcodec.batch_item_payload(
                            err.code, error=err.to_dict()))
                    else:
                        BATCH_ITEMS.inc(kind=kind, result="ok")
                        payloads.append(compactcodec.batch_item_payload(
                            201, obj_payload=(emit_compact(obj)
                                              if emit_compact is not None
                                              else None)))
                return compactcodec.encode_batch_body(
                    payloads, envelope={"kind": "BatchResult"})
            enc_seam = (compactcodec.encode_response_batch_create
                        if kind == "create"
                        else compactcodec.encode_response_bind)
            body = enc_seam(assemble)
            compactcodec.count_request("compact", f"{kind}_batch_encode",
                                       len(body))
            return web.Response(body=body,
                                content_type=compactcodec.CONTENT_TYPE)
        out_items = []
        for obj, err in results:
            if err is not None:
                BATCH_ITEMS.inc(kind=kind, result="error")
                out_items.append({"status": err.code, "error": err.to_dict()})
            else:
                BATCH_ITEMS.inc(kind=kind, result="ok")
                item = {"status": 201}
                payload = emit(obj) if emit is not None else None
                if payload is not None:
                    item["object"] = payload
                out_items.append(item)
        # The per-verb encode seam (decode_share attribution) produces
        # exactly web.json_response's default bytes; Response(text=...)
        # with this content type is the same wire surface.
        dumps_seam = (compactcodec.dumps_response_batch_create
                      if kind == "create"
                      else compactcodec.dumps_response_bind)
        text = dumps_seam({"kind": "BatchResult", "items": out_items})
        if compactcodec.enabled():
            # Like-for-like codec_wire_* accounting with the compact
            # branch above (the LIST path's rule).
            compactcodec.count_request("json", f"{kind}_batch_encode",
                                       len(text))
        return web.Response(text=text, content_type="application/json")

    async def _batch_create(self, request, plural: str, ns: str):
        """POST ``{plural}:batchCreate`` — N creates in one request.

        Validation + admission run per object; HTTP framing, authn/z,
        audit, and dispatch are paid once. Partial failure is NOT an
        error for the batch: the response carries a positional per-item
        status (201 + object, or the item's error Status)."""
        spec = self.registry.spec_for(plural)
        items = self._batch_items(
            await self._body_obj(request, op="batch_create"), "object")
        BATCH_REQUESTS.inc(kind="create")
        conv = self._conv_version(request, spec)
        # ``?echo=0``: omit created objects from the response — bulk
        # submitters (loadgen) discard them, and N pod encodes + N
        # client parses per batch is pure waste on both sides.
        echo = request.query.get("echo", "1") not in ("0", "false")
        results: list = [None] * len(items)
        if self.webhooks.has_hooks("CREATE", plural):
            # External hooks are per-object async round trips — run each
            # item through the single-create pipeline (the request still
            # amortizes framing/authn/audit across the batch).
            for i, data in enumerate(items):
                try:
                    if not isinstance(data, dict):
                        raise errors.BadRequestError("item must be an object")
                    results[i] = (await self._create_one(
                        plural, ns, spec, dict(data), conv), None)
                except errors.StatusError as e:
                    results[i] = (None, e)
        else:
            decoded, idxs = [], []
            for i, data in enumerate(items):
                try:
                    if not isinstance(data, dict):
                        raise errors.BadRequestError("item must be an object")
                    decoded.append(self._decode_create_body(
                        ns, spec, dict(data), conv))
                    idxs.append(i)
                except errors.StatusError as e:
                    results[i] = (None, e)
            if decoded:
                outs = await self._dispatch_batch(
                    self.registry.create_batch, decoded)
                for i, res in zip(idxs, outs):
                    results[i] = res
        if plural.endswith("webhookconfigurations"):
            self.webhooks.invalidate()

        def emit(created):
            if not echo:
                return None
            d = to_dict(created)
            return (self.registry.scheme.from_hub(conv, created.kind, d)
                    if conv else d)

        def emit_compact(created):
            """Echoed object as the serialize-once cache's compact
            payload — the same bytes the watch fan-out frames for
            this revision."""
            if not echo:
                return None
            d = to_dict(created)
            rv = d.get("metadata", {}).pop("resource_version", None)
            if rv is None:
                from ..util import compactcodec
                return compactcodec.encode_obj(d)
            key = self.registry._key(spec, created.metadata.namespace,
                                     created.metadata.name)
            return self.registry.encoded_value(key, d, int(rv),
                                               codec="compact")

        return self._batch_response(request, "create", results, emit,
                                    emit_compact, compact_ok=not conv)

    async def _bind_batch(self, request):
        """POST ``pods/bindings:batch`` — N scheduler binds, one
        request. Each item runs the atomic bind_pod guaranteed-update;
        the response is a positional per-item status list (the bound
        pod objects are NOT echoed — high-rate callers read results
        through their informer, the same reason ``bind(decode=False)``
        exists)."""
        plural, ns = self._ctx(request)
        if plural != "pods":
            raise errors.BadRequestError(
                f"bindings:batch is a pods subresource, not {plural!r}")
        items = self._batch_items(await self._body_obj(request, op="bind"),
                                  '{"name": ..., "target": {...}}')
        BATCH_REQUESTS.inc(kind="bind")
        from ..api.scheme import from_dict
        from ..api.types import Binding
        results: list = [None] * len(items)
        pairs, idxs = [], []
        for i, item in enumerate(items):
            name = item.get("name", "") if isinstance(item, dict) else ""
            if not name:
                results[i] = (None, errors.BadRequestError(
                    "binding item needs a pod name"))
                continue
            try:
                binding = from_dict(Binding, item)
            except (TypeError, ValueError) as e:
                results[i] = (None, errors.BadRequestError(
                    f"undecodable binding: {e}"))
                continue
            pairs.append((name, binding))
            idxs.append(i)
        if pairs:
            import functools
            outs = await self._dispatch_batch(
                functools.partial(self.registry.bind_pods_batch, ns), pairs)
            for i, res in zip(idxs, outs):
                results[i] = res
        return self._batch_response(request, "bind", results)

    async def _get(self, request):
        plural, ns = self._ctx(request)
        spec = self.registry.spec_for(plural)
        conv = self._conv_version(request, spec)
        if not conv:
            # Serialize-once fast path: the stored dict's cached wire
            # bytes (shared with LIST and the watch fan-out) instead of
            # typed decode -> to_dict -> json.dumps per request.
            return web.Response(
                body=self.registry.get_encoded(
                    plural, ns, request.match_info["name"]),
                content_type="application/json")
        obj = self.registry.get(plural, ns, request.match_info["name"])
        return self._obj_response(obj, convert=conv)

    async def _list_or_watch(self, request):
        plural, ns = self._ctx(request)
        q = request.query
        if q.get("watch") in ("1", "true"):
            return await self._watch(request, plural, ns)
        spec = self.registry.spec_for(plural)
        conv = self._conv_version(request, spec)

        def emit(o):
            d = to_dict(o)
            return (self.registry.scheme.from_hub(conv, spec.kind, d)
                    if conv else d)

        limit = self._int_param(q.get("limit", "0") or "0", "limit")
        if limit or q.get("continue"):
            items, rev, cont = self.registry.list_page(
                plural, ns, q.get("label_selector", ""),
                q.get("field_selector", ""), limit=limit,
                continue_token=q.get("continue", ""))
            meta = {"resource_version": str(rev)}
            if cont:
                meta["continue"] = cont
            return web.json_response({
                "kind": "List", "api_version": "core/v1",
                "metadata": meta,
                "items": [emit(o) for o in items],
            })
        if not conv and not q.get("field_selector"):
            # Serialize-once fast path: assemble the List body from
            # per-item cached wire bytes (shared with GET and the watch
            # fan-out) — no typed decode/encode per object. Field
            # selectors need typed extraction and stay on the slow path.
            # CompactWireCodec (gated + client-negotiated via Accept):
            # the same assembly from compact per-item payloads, framed;
            # every other client keeps the byte-identical JSON body.
            from ..util import compactcodec
            codec = ("compact" if compactcodec.enabled()
                     and compactcodec.accepts_compact(
                         request.headers.get("Accept", "")) else "json")
            if self.codec_pool is not None and self.codec_pool.active:
                # ApiServerCodecOffload: cache MISSES encode in the
                # process pool (a 30k-pod relist after a write burst is
                # thousands of misses); results re-enter the cache
                # through the generation-guarded async seam so a write
                # racing a pool encode can never resurrect the entry.
                parts, misses, rev = self.registry.list_encoded_parts(
                    plural, ns, q.get("label_selector", ""), codec=codec)
                if misses:
                    cache = self.registry.encode_cache
                    which = compactcodec.cache_which("cur", codec)
                    done = 0
                    try:
                        lines = await self.codec_pool.encode_values(
                            [m[3] for m in misses], codec=codec)
                        for (idx, key, mrev, _val, token), line in zip(
                                misses, lines):
                            cache.finish_async_encode(key, mrev, line,
                                                      token, which=which)
                            done += 1
                            parts[idx] = line
                    finally:
                        # Cancellation (client gone mid-LIST) must
                        # release every token still registered, or the
                        # cache's pending bookkeeping leaks per key.
                        for _idx, key, _mrev, _val, _token in \
                                misses[done:]:
                            cache.abort_async_encode(key)
                enc = parts
            else:
                enc, rev = self.registry.list_encoded(
                    plural, ns, q.get("label_selector", ""), codec=codec)
            if codec == "compact":
                body = compactcodec.encode_list_body(rev, enc)
                compactcodec.count_request("compact", "list", len(body))
                return web.Response(
                    body=body, content_type=compactcodec.CONTENT_TYPE)
            body = (b'{"kind":"List","api_version":"core/v1","metadata":'
                    b'{"resource_version":"' + str(rev).encode()
                    + b'"},"items":[' + b",".join(enc) + b"]}")
            if compactcodec.enabled():
                compactcodec.count_request("json", "list", len(body))
            return web.Response(body=body, content_type="application/json")
        items, rev = self.registry.list(
            plural, ns, q.get("label_selector", ""), q.get("field_selector", ""))
        return web.json_response({
            "kind": "List", "api_version": "core/v1",
            "metadata": {"resource_version": str(rev)},
            "items": [emit(o) for o in items],
        })

    @staticmethod
    def _int_param(value, name: str) -> int:
        try:
            return int(value)
        except (TypeError, ValueError):
            raise errors.BadRequestError(
                f"query parameter {name!r} must be an integer, got {value!r}") from None

    def _encode_watch_event(self, etype: str, payload: dict, rev: int,
                            which: str, key: str,
                            codec: str = "json") -> bytes:
        """One encode per store event per codec, shared by every raw
        watcher AND the GET/LIST fast paths (the watch cache's
        serialize-once fan-out, now backed by the registry's encode
        cache; without this, N pod watchers cost N encodes per event
        and the apiserver event loop — shared with every in-process
        component — eats the REST-path latency SLO). Only the object
        payload is cached; the event envelope is a cheap byte concat
        per watcher (a framed fixmap for the compact codec). ``which``
        disambiguates selector-left corpses surfacing at the same
        revision."""
        obj_b = self.registry.encoded_value(key, payload, rev, which,
                                            codec=codec)
        if codec == "compact":
            from ..util import compactcodec
            return compactcodec.event_frame(etype, obj_b)
        return b'{"type":"' + etype.encode() + b'","object":' + obj_b + b"}\n"

    async def _watch(self, request, plural: str, ns: str):
        q = request.query
        spec = self.registry.spec_for(plural)
        conv = self._conv_version(request, spec)
        start_rev = self._int_param(q.get("resource_version", "0") or "0",
                                    "resource_version")
        field_selector = q.get("field_selector", "")
        try:
            if field_selector:
                # Field selectors need typed extraction — slow path.
                watch = self.registry.watch(
                    plural, ns, start_rev,
                    q.get("label_selector", ""), field_selector)
            else:
                watch = self.registry.watch_raw(
                    plural, ns, start_rev, q.get("label_selector", ""))
        except errors.GoneError as e:
            return self._err(e)
        raw_mode = not field_selector
        # CompactWireCodec: a raw-mode storage-version watcher that
        # asked for compact gets framed msgpack events off the shared
        # encode cache; everyone else keeps the byte-identical JSON
        # line stream (conversion watchers always stream JSON).
        from ..util import compactcodec
        compact = (raw_mode and not conv and compactcodec.enabled()
                   and compactcodec.accepts_compact(
                       request.headers.get("Accept", "")))
        resp = web.StreamResponse()
        resp.content_type = (compactcodec.CONTENT_TYPE if compact
                             else "application/json")
        resp.headers["Transfer-Encoding"] = "chunked"
        await resp.prepare(request)
        if compactcodec.enabled():
            compactcodec.count_request(
                "compact" if compact else "json", "watch")

        def event_line(ev) -> Optional[bytes]:
            """Wire line for one event; None ends the stream."""
            if raw_mode:
                etype, payload, rev, which, ev_key = ev
                if etype == "CLOSED":
                    return None
                if conv:
                    # Versioned watcher: per-event conversion off
                    # the shared encode cache (only THIS watcher
                    # pays; storage-version watchers keep the
                    # serialize-once fast path).
                    obj = self.registry.scheme.from_hub(conv, spec.kind, {
                        **payload,
                        "metadata": {**(payload.get("metadata") or {}),
                                     "resource_version": str(rev)}})
                    return (json.dumps({"type": etype, "object": obj})
                            .encode() + b"\n")
                return self._encode_watch_event(
                    etype, payload, rev, which, ev_key,
                    codec="compact" if compact else "json")
            etype, obj = ev
            if etype == "CLOSED":
                return None
            d = to_dict(obj)
            if conv:
                d = self.registry.scheme.from_hub(conv, spec.kind, d)
            return json.dumps({"type": etype, "object": d}).encode() + b"\n"

        def bookmark_line() -> bytes:
            # Bookmark keeps the connection alive and advances the
            # client's resume point (reference: watch bookmarks).
            bookmark = {
                "type": "BOOKMARK",
                "object": {"metadata": {"resource_version": str(self.registry.store.revision)}},
            }
            if compact:
                return compactcodec.frame(compactcodec.encode_obj(bookmark))
            return json.dumps(bookmark).encode() + b"\n"

        from ..util.features import GATES
        # WatchBookmarks: besides the idle-timeout bookmark below
        # (always on — rest.py's liveness timeout depends on it), a
        # gated stream also gets a bookmark about every
        # watch_bookmark_interval seconds WHILE events flow, so a busy
        # informer's resume point keeps advancing past what the store
        # may compact. Gate off = no extra frames, byte-identical.
        bookmarks_on = GATES.enabled("WatchBookmarks")
        loop = asyncio.get_running_loop()
        last_bookmark = loop.time()
        # Always-on width accounting: how the store dispatches to this
        # stream (keyed bucket vs prefix scan) + per-round volume below.
        dispatch = ("indexed"
                    if getattr(watch._raw, "index", None) is not None
                    else "scan")
        WATCH_STREAMS.inc(dispatch=dispatch)
        try:
            if GATES.enabled("WatchFanoutBatch"):
                return await self._watch_fanout(resp, watch, event_line,
                                                bookmark_line, bookmarks_on)
            try:
                closed = False
                while not closed:
                    ev = await watch.next(timeout=10.0)
                    if ev is None:
                        await resp.write(bookmark_line())
                        last_bookmark = loop.time()
                        continue
                    # Coalesce every event already in flight into ONE
                    # socket write: per-event writes made the fan-out's
                    # send() syscalls the apiserver's single largest CPU
                    # cost at density scale (N watchers x M events). The
                    # byte stream is identical — same lines, same order —
                    # and consumers iterate by line regardless of framing.
                    chunks: list = []
                    while True:
                        line = event_line(ev)
                        if line is None:
                            closed = True
                            break
                        chunks.append(line)
                        if len(chunks) >= self.watch_write_batch:
                            break
                        ev = watch.next_nowait()
                        if ev is None:
                            break
                    n_events = len(chunks)
                    if bookmarks_on and loop.time() - last_bookmark \
                            >= self.watch_bookmark_interval:
                        chunks.append(bookmark_line())
                        last_bookmark = loop.time()
                    if chunks:
                        buf = b"".join(chunks)
                        WATCH_ROUNDS.inc()
                        WATCH_ROUND_BYTES.observe(float(len(buf)))
                        if n_events:
                            WATCH_EVENTS_SENT.inc(float(n_events))
                        await resp.write(buf)
            except (ConnectionResetError, asyncio.CancelledError):
                pass
            finally:
                watch.cancel()
            return resp
        finally:
            WATCH_STREAMS.dec(dispatch=dispatch)

    async def _watch_fanout(self, resp, watch, event_line,
                            bookmark_line,
                            bookmarks_on: bool = False
                            ) -> web.StreamResponse:
        """The WatchFanoutBatch half of :meth:`_watch`: this handler
        never writes the socket inline — it drains its registry watch
        queue into a per-watcher sink, and the shared FanoutFlusher's
        sharded workers coalesce each sink's pending frames into one
        buffered send per flush round (see apiserver/fanout.py). Same
        frames, same per-watcher order; a slow consumer stalls only
        its own shard, an overflowing one is closed (client relists)."""
        if self.fanout is None:
            from .fanout import FanoutFlusher
            self.fanout = FanoutFlusher()
        # Local ref: stop() may null self.fanout while this handler is
        # still unwinding — cleanup must use the engine it registered
        # with.
        fanout = self.fanout
        sink = fanout.register(resp)
        loop = asyncio.get_running_loop()
        last_bookmark = loop.time()
        try:
            closed = False
            while not closed and not sink.closed:
                ev = await watch.next(timeout=10.0)
                if ev is None:
                    sink.push(bookmark_line())
                    last_bookmark = loop.time()
                    continue
                pushed = 0
                while True:
                    line = event_line(ev)
                    if line is None:
                        closed = True
                        break
                    sink.push(line)
                    if sink.closed:
                        break
                    pushed += 1
                    if pushed % self.watch_write_batch == 0:
                        # Yield mid-drain: pushes never await, and a
                        # deep backlog must not monopolize the loop.
                        await asyncio.sleep(0)
                    ev = watch.next_nowait()
                    if ev is None:
                        break
                if bookmarks_on and not sink.closed \
                        and loop.time() - last_bookmark \
                        >= self.watch_bookmark_interval:
                    sink.push(bookmark_line())
                    last_bookmark = loop.time()
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            watch.cancel()
            fanout.discard(sink)
            try:
                # Best-effort final flush of frames a worker has not
                # sent yet; the stream is ending either way.
                await fanout.drain(sink)
            except (OSError, RuntimeError, asyncio.CancelledError):
                pass
        return resp

    async def _update(self, request):
        plural, ns = self._ctx(request)
        spec = self.registry.spec_for(plural)
        sub = request.match_info.get("subresource", "")
        if sub == "binding":
            return await self._subresource_post(request)
        if sub not in ("", "status"):
            raise errors.BadRequestError(f"unknown subresource {sub!r}")
        data = await self._body_obj(request)
        conv = self._conv_version(request, spec)
        if conv:
            data = self._body_to_hub(data, conv, spec)
        data.setdefault("api_version", spec.api_version)
        data.setdefault("kind", spec.kind)
        obj = self.registry.scheme.decode(data)
        obj.metadata.namespace = ns or obj.metadata.namespace
        obj.metadata.name = request.match_info["name"]
        if not sub and self.webhooks.has_hooks("UPDATE", plural):
            try:
                old = to_dict(self.registry.get(plural, ns,
                                                obj.metadata.name))
            except errors.NotFoundError:
                old = None
            d = await self.webhooks.run_mutating(
                "UPDATE", plural, ns, obj.metadata.name, to_dict(obj), old)
            obj = self.registry.scheme.decode(d)
            # Validate on the post-in-tree-admission object (see
            # _create); dry-run has no allocator/store side effects.
            if self.webhooks.has_validating("UPDATE", plural):
                admitted = await self._mutate(
                    self.registry.update, obj_deepcopy(obj), sub, True)
                await self.webhooks.run_validating(
                    "UPDATE", plural, ns, obj.metadata.name,
                    to_dict(admitted), old)
        updated = await self._mutate(self.registry.update, obj, sub)
        if plural.endswith("webhookconfigurations"):
            self.webhooks.invalidate()
        return self._obj_response(updated, convert=conv)

    async def _patch(self, request):
        plural, ns = self._ctx(request)
        spec = self.registry.spec_for(plural)
        sub = request.match_info.get("subresource", "")
        name = request.match_info["name"]
        patch = await self._body_obj(request)
        from ..api.patch import JSON_PATCH, STRATEGIC_MERGE_PATCH
        strategic = request.content_type == STRATEGIC_MERGE_PATCH
        # RFC 6902 bodies are arrays; merge-patch bodies are objects.
        # The content type and the body shape must agree.
        if request.content_type == JSON_PATCH and not isinstance(patch, list):
            raise errors.BadRequestError(
                "json-patch body must be an array of ops")
        if request.content_type != JSON_PATCH and isinstance(patch, list):
            raise errors.BadRequestError(
                f"array patch body requires Content-Type {JSON_PATCH}")
        conv = self._conv_version(request, spec) if not sub else ""
        if conv:
            # A versioned PATCH merges in the VERSIONED field space
            # (the reference patches the converted object): convert
            # the current object down, merge, convert the result up,
            # persist as a conflict-guarded full update.
            scheme = self.registry.scheme
            for attempt in range(10):
                old_obj = self.registry.get(plural, ns, name)
                down = scheme.from_hub(conv, spec.kind, to_dict(old_obj))
                if isinstance(patch, list):
                    # RFC 6902 ops apply in the VERSIONED field space,
                    # like the merge flavors below.
                    from .webhooks import apply_json_patch
                    try:
                        merged = apply_json_patch(down, patch)
                    except ValueError as e:
                        raise errors.BadRequestError(str(e)) from None
                elif strategic:
                    from ..api.patch import strategic_merge
                    try:
                        vcls = scheme.class_for(conv, spec.kind)
                    except KeyError:
                        vcls = None  # CRD alternate version: no class
                    merged = strategic_merge(down, patch, vcls)
                else:
                    from .registry import _json_merge
                    merged = _json_merge(down, patch)
                hub = self._body_to_hub(merged, conv, spec)
                obj = scheme.decode(hub)
                obj.metadata.resource_version = \
                    old_obj.metadata.resource_version
                # Admission webhooks see the merged hub object, exactly
                # as on the storage-version PATCH path below — a served
                # alternate version must not be a policy bypass.
                if self.webhooks.has_hooks("UPDATE", plural):
                    old = to_dict(old_obj)
                    d = await self.webhooks.run_mutating(
                        "UPDATE", plural, ns, name, to_dict(obj), old)
                    obj = scheme.decode(d)
                    obj.metadata.resource_version = \
                        old_obj.metadata.resource_version
                    if self.webhooks.has_validating("UPDATE", plural):
                        admitted = await self._mutate(
                            self.registry.update, obj_deepcopy(obj), sub, True)
                        await self.webhooks.run_validating(
                            "UPDATE", plural, ns, name,
                            to_dict(admitted), old)
                try:
                    updated = await self._mutate(
                        self.registry.update, obj, sub)
                    return self._obj_response(updated, convert=conv)
                except errors.ConflictError:
                    if attempt == 9:
                        raise
        if not sub and self.webhooks.has_hooks("UPDATE", plural):
            # A patch is an UPDATE to webhooks (reference semantics —
            # otherwise PATCH would be a policy bypass): compute the
            # merged object, run the hooks on it, persist as a
            # conflict-guarded update carrying any hook mutations.
            for attempt in range(3):
                old_obj = self.registry.get(plural, ns, name)
                merged = self.registry.preview_patch(
                    old_obj, patch, strategic)
                old = to_dict(old_obj)
                d = await self.webhooks.run_mutating(
                    "UPDATE", plural, ns, name, merged, old)
                obj = self.registry.scheme.decode(d)
                obj.metadata.resource_version = \
                    old_obj.metadata.resource_version
                # Validate on the post-in-tree-admission object (see
                # _create).
                if self.webhooks.has_validating("UPDATE", plural):
                    admitted = await self._mutate(
                        self.registry.update, obj_deepcopy(obj), sub, True)
                    await self.webhooks.run_validating(
                        "UPDATE", plural, ns, name, to_dict(admitted), old)
                try:
                    updated = await self._mutate(
                        self.registry.update, obj, sub)
                    return self._obj_response(updated)
                except errors.ConflictError:
                    if attempt == 2:
                        raise
        updated = await self._mutate(
            self.registry.patch, plural, ns, name, patch, sub, strategic)
        if plural.endswith("webhookconfigurations"):
            self.webhooks.invalidate()
        return self._obj_response(updated)

    async def _delete(self, request):
        plural, ns = self._ctx(request)
        name = request.match_info["name"]
        del_conv = self._conv_version(request,
                                      self.registry.spec_for(plural))
        if self.webhooks.has_hooks("DELETE", plural):
            try:
                old = to_dict(self.registry.get(plural, ns, name))
            except errors.NotFoundError:
                old = None
            if old is not None:
                await self.webhooks.run_validating(
                    "DELETE", plural, ns, name, None, old)
        gp = request.query.get("grace_period_seconds")
        obj = await self._mutate(
            self.registry.delete, plural, ns, name,
            self._int_param(gp, "grace_period_seconds") if gp is not None else None,
            request.query.get("uid", ""),
            request.query.get("propagation_policy", ""))
        if plural.endswith("webhookconfigurations"):
            self.webhooks.invalidate()
        return self._obj_response(obj, convert=del_conv)

    async def _delete_collection(self, request):
        plural, ns = self._ctx(request)
        selector = request.query.get("label_selector", "")
        if self.webhooks.has_hooks("DELETE", plural):
            # A collection delete is N deletes to webhooks — otherwise
            # it would be the policy bypass the single-delete path
            # closes. Any denial rejects the whole operation (nothing
            # is deleted), keeping it atomic for the caller.
            objs, _ = self.registry.list(plural, ns, selector)
            for obj in objs:
                await self.webhooks.run_validating(
                    "DELETE", plural, ns, obj.metadata.name,
                    None, to_dict(obj))
        # Always a worker thread: O(collection) work would monopolize
        # the event loop even without a WAL (_mutate's inline fast path
        # is for single-object sub-ms mutations only).
        n, wrote_rev = await asyncio.to_thread(
            self.registry.store.last_write_in,
            self.registry.delete_collection, plural, ns, selector)
        if wrote_rev and self.registry.replica is not None:
            # Replicated plane: the deletes ack only at quorum, same as
            # every run()-dispatched mutation (await_commit hops to the
            # replica's loop when this handler runs on a shard worker).
            await self.registry.await_commit(self.registry.replica,
                                             wrote_rev)
        if plural.endswith("webhookconfigurations"):
            self.webhooks.invalidate()
        return web.json_response({"deleted": n})

    async def _subresource_post(self, request):
        plural, ns = self._ctx(request)
        sub = request.match_info.get("subresource", "")
        if plural == "pods" and sub == "binding":
            data = await self._body_obj(request, op="bind")
            from ..api.scheme import from_dict
            from ..api.types import Binding
            binding = from_dict(Binding, data)
            pod = await self._mutate(
                self.registry.bind_pod, ns, request.match_info["name"], binding)
            return self._obj_response(pod, status=201)
        if plural == "pods" and sub == "eviction":
            data = await self._body_obj(request)
            from ..api.scheme import from_dict
            from ..api.types import Eviction
            eviction = from_dict(Eviction, data)
            await self._mutate(self.registry.evict_pod, ns,
                               request.match_info["name"], eviction)
            # Reference returns a Status, not the pod.
            return web.json_response(
                {"kind": "Status", "status": "Success",
                 "message": f"pod {ns}/{request.match_info['name']} evicted"},
                status=201)
        raise errors.BadRequestError(f"unsupported subresource {plural}/{sub}")

    # -- lifecycle --------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0,
                    ssl_context=None) -> int:
        """``ssl_context``: a server context from
        ``certs.server_ssl_context`` makes this an HTTPS-only endpoint
        with x509 client-cert authn (plaintext connections are refused
        by TLS itself — the reference's secure port)."""
        from ..analysis import loopsan
        from ..util.features import GATES
        # Arm the loop-occupancy sanitizer before any callback of ours
        # runs (TPU_LOOPSAN=1; no-op and byte-identical otherwise).
        loopsan.maybe_arm()
        if self.shards is None and GATES.enabled("ApiServerSharding"):
            from .sharding import ShardPool
            # KTPU_SHARD_MODE overrides the auto probe (the
            # BENCH_THREADS harness arm forces "thread" on multi-core
            # hosts; "inline" forces the single-loop path).
            self.shards = ShardPool(
                mode=os.environ.get("KTPU_SHARD_MODE", "auto"))
            self.shards.on_worker = self._start_shard_probe
        if self.codec_pool is None \
                and GATES.enabled("ApiServerCodecOffload"):
            from .codecpool import CodecPool
            self.codec_pool = CodecPool()
        self._probe_tasks.append(spawn(
            self._loop_lag_probe("router"), name="apiserver-loop-probe"))
        # Periodic MVCC compactor (no-op without a CompactionPolicy on
        # the registry) — aging hygiene runs with the server lifecycle.
        self.registry.start_compactor()
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        # Short shutdown grace: long-lived watch streams would otherwise
        # hold cleanup for the default 60s (they are safely cancellable —
        # clients relist on reconnect).
        site = web.TCPSite(self._runner, host, port, shutdown_timeout=1.0,
                           ssl_context=ssl_context)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("apiserver listening on %s:%d (%s)", host, self.port,
                 "https" if ssl_context else "http")
        return self.port

    async def stop(self) -> None:
        self.registry.stop_compactor()
        for task in self._probe_tasks:
            task.cancel()
        self._probe_tasks.clear()
        for cfut in self._probe_futs:
            cfut.cancel()
        self._probe_futs.clear()
        if self.shards is not None:
            # Thread joins run off-loop: blocking the router loop here
            # would stall sibling servers sharing it (the HA harness
            # runs every replica on one loop) — and a worker wedged in
            # a cross-loop hop TO this loop could never finish while
            # we block it.
            shards, self.shards = self.shards, None
            await asyncio.to_thread(shards.stop)
        if self.codec_pool is not None:
            self.codec_pool.shutdown()
            self.codec_pool = None
        if self.fanout is not None:
            await self.fanout.stop()
            self.fanout = None
        await self.webhooks.close()
        if self._proxy_session is not None and not self._proxy_session.closed:
            await self._proxy_session.close()
        if self._runner:
            await self._runner.cleanup()
            self._runner = None
