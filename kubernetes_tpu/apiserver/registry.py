"""Generic registry — the per-resource REST strategy layer over storage.

Reference: ``staging/src/k8s.io/apiserver/pkg/registry/generic/registry/
store.go`` (``:308 Create``) + per-resource strategies in
``pkg/registry/<group>/<kind>/strategy.go``. One CRUD template runs all
kinds; per-kind behavior (namespacing, status subresource, validation,
field extraction for field selectors, graceful deletion) comes from a
:class:`ResourceSpec`.

The pods/binding subresource reproduces the fork's key atomicity trick:
node name AND concrete chip assignments land in ONE guaranteed update
(``pkg/registry/core/pod/storage/storage.go:130-210
setPodHostAndAnnotations``) so there is no window where a pod is bound
but deviceless.
"""
from __future__ import annotations

import asyncio
import datetime
import json
import logging
import time
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Optional

from .. import tracing
from ..api import errors, extensions as ext, networking as net, \
    queueing as qapi, rbac as r, serving as sapi, training as tapi, \
    types as t, validation as val, workloads as w
from ..api.meta import ObjectMeta, TypedObject, now, stamp as meta_stamp, \
    stamp_new
from ..api.scheme import DEFAULT_SCHEME, Scheme, from_dict, to_dict
from ..api.selectors import match_field_selector, parse_selector
from ..metrics.registry import Counter, Gauge
from ..storage.mvcc import ADDED, DELETED, MODIFIED, MVCCStore, TxnError, \
    Watch, WatchEvent

#: Endurance telemetry: the compactor keeps these current each cycle
#: (the same numbers /debug/v1/storage serves on demand).
STORAGE_COMPACT_REV = Gauge(
    "storage_compact_revision",
    "MVCC compacted floor (watches may not resume at or below it)")
STORAGE_COMPACTIONS = Counter(
    "storage_compactions_total",
    "compactor cycles that advanced the compacted floor")
STORAGE_WAL_BYTES = Gauge(
    "storage_wal_bytes", "WAL bytes since the last snapshot truncation")
STORAGE_HISTORY_LEN = Gauge(
    "storage_watch_history_entries",
    "watch-replay events retained in memory")

BATCH_TXN_COMMITS = Counter(
    "apiserver_batch_txn_commits_total",
    "batch chunks committed as ONE MVCC transaction (BatchWriteTxn)",
    labels=("kind",))
BATCH_TXN_SPLITS = Counter(
    "apiserver_batch_txn_splits_total",
    "items split out of a batch transaction (per-item rejection; the "
    "rest of the chunk still commits)",
    labels=("kind",))


@dataclass
class CompactionPolicy:
    """Retention knobs for the periodic MVCC compactor (reference:
    etcd's ``--auto-compaction-mode/retention`` pair, both modes at
    once). Every ``interval_seconds`` the compactor advances the
    store's compacted floor to the newest revision that is BOTH more
    than ``retention_revisions`` old and older than
    ``retention_seconds`` (a knob set to 0 drops that bound), so a
    watcher always gets at least that much resume headroom before a
    reconnect 410s into a relist. On a replicated registry the floor
    is additionally clamped to the quorum commit revision — an
    uncommitted suffix is never compacted out from under a follower
    catch-up (``committed-never-lost``)."""
    retention_revisions: int = 10_000
    retention_seconds: float = 300.0
    interval_seconds: float = 5.0


@dataclass
class ResourceSpec:
    plural: str
    kind: str
    api_version: str
    cls: type
    namespaced: bool = True
    #: Status handled as a subresource: normal updates keep old status,
    #: /status updates keep old spec (reference strategy pattern).
    has_status: bool = True
    validate_create: Optional[Callable] = None
    validate_update: Optional[Callable] = None
    #: Extract flat fields for field-selector matching.
    field_extractor: Optional[Callable[[Any], dict]] = None
    #: Graceful deletion (pods): DELETE sets deletion_timestamp first.
    graceful_delete: bool = False
    #: Keep client-supplied status at create time. Nodes set this: the
    #: node agent both creates the object and owns its status, and test
    #: rigs (kubemark) seed capacity the same way.
    preserve_status_on_create: bool = False
    #: RBAC-style names ("system:node") are path segments, not
    #: DNS-1123 labels (validation.go ValidatePathSegmentName).
    path_segment_name: bool = False


def _pod_fields(pod: t.Pod) -> dict:
    return {
        "metadata.name": pod.metadata.name,
        "metadata.namespace": pod.metadata.namespace,
        "spec.node_name": pod.spec.node_name,
        "spec.scheduler_name": pod.spec.scheduler_name,
        "spec.gang": pod.spec.gang,
        "status.phase": pod.status.phase,
    }


def _node_fields(node: t.Node) -> dict:
    return {"metadata.name": node.metadata.name,
            "spec.unschedulable": str(node.spec.unschedulable).lower()}


def _parses(check, value: str) -> bool:
    """Run an allocator range check on user input; malformed addresses
    are simply out of range (InvalidError), never a 500."""
    try:
        return check(value)
    except (ValueError, IndexError):
        return False


def _json_merge(base: Any, p: Any) -> Any:
    """RFC 7386 JSON merge-patch (None deletes; dicts merge deep)."""
    if not isinstance(p, dict):
        return p
    if not isinstance(base, dict):
        base = {}
    out = dict(base)
    for k, v in p.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = _json_merge(out.get(k), v)
    return out


def _merge_secret_string_data(sec: t.Secret) -> None:
    """Secret strategy: fold the plaintext ``string_data`` convenience
    field into base64 ``data`` (reference: pkg/registry/core/secret
    strategy + Secret.StringData semantics)."""
    import base64 as _b64
    for k, v in sec.string_data.items():
        sec.data[k] = _b64.b64encode(v.encode()).decode()
    sec.string_data = {}


def _raw_pod_node_name(value: dict) -> Optional[str]:
    """Store-side watch-index extractor: the raw-dict mirror of
    ``_pod_fields``'s ``spec.node_name`` (no typed decode — it runs
    under the store lock on every pod write)."""
    spec = value.get("spec")
    return spec.get("node_name") if isinstance(spec, dict) else None


#: plural -> {field-selector key -> store watch-index name}: fields a
#: single-equality watch selector can subscribe to by bucket instead of
#: the O(watchers) prefix scan. spec.node_name is THE width field — one
#: per-node pod watcher per kubelet-analog, 5k of them at hollow-fleet
#: scale.
_WATCH_INDEXED_FIELDS = {
    "pods": {"spec.node_name": "pods.spec.node_name"},
}


def _watch_index_hint(plural: str,
                      field_selector: str) -> Optional[tuple[str, str]]:
    """(index name, value) when the selector contains an equality term
    on an indexed field. Correctness: field selectors AND their terms,
    so every object the full selector matches extracts to that value —
    bucket delivery (which also fires for the PREVIOUS value, covering
    set-leave transitions) is a strict superset of what the watcher's
    filter can surface."""
    fields = _WATCH_INDEXED_FIELDS.get(plural)
    if not fields or not field_selector:
        return None
    for part in field_selector.split(","):
        part = part.strip()
        if not part or "!=" in part or "=" not in part:
            continue
        key, _, val = part.partition("=")
        name = fields.get(key.strip())
        if name and val.strip():
            return (name, val.strip())
    return None


def _event_fields(ev: t.Event) -> dict:
    return {
        "metadata.name": ev.metadata.name,
        "involved_object.kind": ev.involved_object.kind,
        "involved_object.name": ev.involved_object.name,
        "reason": ev.reason,
        "type": ev.type,
    }


def builtin_resources() -> list[ResourceSpec]:
    """The framework's API surface (reference: pkg/master/master.go
    InstallLegacyAPI/InstallAPIs resource table). Every kind present in
    ``validation.VALIDATORS`` gets its field validators wired
    automatically (see the fill loop at the end) — a kind listed there
    can never silently fall back to metadata-only checks again."""
    core = "core/v1"
    specs = [
        ResourceSpec("pods", "Pod", core, t.Pod, field_extractor=_pod_fields,
                     validate_create=val.validate_pod,
                     validate_update=val.validate_pod_update, graceful_delete=True),
        ResourceSpec("nodes", "Node", core, t.Node, namespaced=False,
                     field_extractor=_node_fields, validate_create=val.validate_node,
                     preserve_status_on_create=True),
        ResourceSpec("services", "Service", core, t.Service,
                     validate_create=val.validate_service),
        ResourceSpec("endpoints", "Endpoints", core, t.Endpoints, has_status=False),
        ResourceSpec("namespaces", "Namespace", core, t.Namespace, namespaced=False,
                     validate_create=val.validate_namespace),
        ResourceSpec("configmaps", "ConfigMap", core, t.ConfigMap, has_status=False),
        ResourceSpec("secrets", "Secret", core, t.Secret, has_status=False,
                     validate_create=val.validate_secret),
        ResourceSpec("events", "Event", core, t.Event, has_status=False,
                     field_extractor=_event_fields),
        ResourceSpec("resourcequotas", "ResourceQuota", core, t.ResourceQuota),
        ResourceSpec("limitranges", "LimitRange", core, t.LimitRange, has_status=False),
        ResourceSpec("priorityclasses", "PriorityClass", core, t.PriorityClass,
                     namespaced=False, has_status=False),
        ResourceSpec("leases", "Lease", core, t.Lease, has_status=False),
        ResourceSpec("podgroups", "PodGroup", core, t.PodGroup,
                     validate_create=val.validate_podgroup),
        ResourceSpec("clusterqueues", "ClusterQueue", qapi.QUEUEING_V1,
                     qapi.ClusterQueue, namespaced=False,
                     validate_create=qapi.validate_clusterqueue,
                     validate_update=qapi.validate_clusterqueue_update),
        ResourceSpec("localqueues", "LocalQueue", qapi.QUEUEING_V1,
                     qapi.LocalQueue,
                     validate_create=qapi.validate_localqueue,
                     validate_update=qapi.validate_localqueue_update),
        ResourceSpec("inferenceservices", "InferenceService",
                     sapi.SERVING_V1, sapi.InferenceService,
                     validate_create=sapi.validate_inferenceservice,
                     validate_update=sapi.validate_inferenceservice_update),
        ResourceSpec("trainjobs", "TrainJob",
                     tapi.TRAINING_V1, tapi.TrainJob,
                     validate_create=tapi.validate_trainjob,
                     validate_update=tapi.validate_trainjob_update),
        ResourceSpec("replicasets", "ReplicaSet", "apps/v1", w.ReplicaSet,
                     validate_create=val.validate_replicaset),
        ResourceSpec("deployments", "Deployment", "apps/v1", w.Deployment,
                     validate_create=val.validate_deployment),
        ResourceSpec("statefulsets", "StatefulSet", "apps/v1", w.StatefulSet,
                     validate_create=val.validate_statefulset),
        ResourceSpec("daemonsets", "DaemonSet", "apps/v1", w.DaemonSet),
        ResourceSpec("jobs", "Job", "batch/v1", w.Job, validate_create=val.validate_job),
        ResourceSpec("cronjobs", "CronJob", "batch/v1", w.CronJob),
        ResourceSpec("horizontalpodautoscalers", "HorizontalPodAutoscaler",
                     "autoscaling/v1", w.HorizontalPodAutoscaler),
        ResourceSpec("poddisruptionbudgets", "PodDisruptionBudget", "policy/v1",
                     w.PodDisruptionBudget),
        ResourceSpec("podsecuritypolicies", "PodSecurityPolicy", "policy/v1",
                     t.PodSecurityPolicy, namespaced=False,
                     has_status=False),
        ResourceSpec("networkpolicies", "NetworkPolicy", net.NETWORKING_V1,
                     net.NetworkPolicy, has_status=False,
                     validate_create=net.validate_network_policy,
                     validate_update=lambda new, old:
                     net.validate_network_policy(new, update=True)),
        ResourceSpec("roles", "Role", r.RBAC_V1, r.Role, has_status=False,
                     path_segment_name=True),
        ResourceSpec("clusterroles", "ClusterRole", r.RBAC_V1, r.ClusterRole,
                     namespaced=False, has_status=False,
                     path_segment_name=True),
        ResourceSpec("rolebindings", "RoleBinding", r.RBAC_V1, r.RoleBinding,
                     has_status=False, path_segment_name=True),
        ResourceSpec("clusterrolebindings", "ClusterRoleBinding", r.RBAC_V1,
                     r.ClusterRoleBinding, namespaced=False, has_status=False,
                     path_segment_name=True),
        ResourceSpec("serviceaccounts", "ServiceAccount", core,
                     t.ServiceAccount, has_status=False),
        ResourceSpec("persistentvolumes", "PersistentVolume", core,
                     t.PersistentVolume, namespaced=False),
        ResourceSpec("persistentvolumeclaims", "PersistentVolumeClaim", core,
                     t.PersistentVolumeClaim),
        ResourceSpec("storageclasses", "StorageClass", "storage/v1",
                     t.StorageClass, namespaced=False, has_status=False),
        ResourceSpec("customresourcedefinitions", "CustomResourceDefinition",
                     ext.EXTENSIONS_V1, ext.CustomResourceDefinition,
                     namespaced=False, validate_create=ext.validate_crd,
                     validate_update=ext.validate_crd_update),
        ResourceSpec("apiservices", "APIService", ext.AGGREGATION_V1,
                     ext.APIService, namespaced=False,
                     validate_create=ext.validate_apiservice,
                     validate_update=ext.validate_apiservice_update),
        ResourceSpec("mutatingwebhookconfigurations",
                     "MutatingWebhookConfiguration", ext.ADMISSION_V1,
                     ext.MutatingWebhookConfiguration, namespaced=False,
                     has_status=False,
                     validate_create=ext.validate_webhook_configuration,
                     validate_update=ext.validate_webhook_configuration_update),
        ResourceSpec("validatingwebhookconfigurations",
                     "ValidatingWebhookConfiguration", ext.ADMISSION_V1,
                     ext.ValidatingWebhookConfiguration, namespaced=False,
                     has_status=False,
                     validate_create=ext.validate_webhook_configuration,
                     validate_update=ext.validate_webhook_configuration_update),
    ]
    for spec in specs:
        create_v, update_v = val.VALIDATORS.get(spec.kind, (None, None))
        if spec.validate_create is None and create_v is not None:
            spec.validate_create = create_v
        if spec.validate_update is None and update_v is not None:
            spec.validate_update = update_v
    return specs


class Registry:
    """CRUD over the MVCC store for every registered resource."""

    def __init__(self, store: Optional[MVCCStore] = None,
                 scheme: Scheme = DEFAULT_SCHEME,
                 admission: Optional["AdmissionChain"] = None,
                 compaction_policy: Optional[CompactionPolicy] = None):
        self.store = store or MVCCStore()
        #: None = the compactor never runs (opt-in, like etcd
        #: autocompaction); see :meth:`start_compactor`.
        self.compaction_policy = compaction_policy
        self._compactor_task: Optional[asyncio.Task] = None
        #: (monotonic time, revision) samples the age-retention bound
        #: interpolates from; bounded by retention_seconds/interval.
        self._compact_samples: list[tuple[float, int]] = []
        self.scheme = scheme
        self.admission = admission
        self._by_plural: dict[str, ResourceSpec] = {}
        self._by_kind: dict[str, ResourceSpec] = {}
        self.service_cidr = "10.96.0.0/16"
        #: /12 -> 4096 node /24 blocks (reference-scale kubemark fleets
        #: run 1000+ hollow nodes; a /16's 256 blocks exhaust there).
        self.cluster_cidr = "10.64.0.0/12"
        #: --node-cidr-mask-size analog: prefix length of each node's
        #: pod block. /24 = 4096 blocks of 254 pods under the /12; a
        #: 5k-node hollow fleet sets 26 (16384 blocks of 62 pods) —
        #: same trade GKE makes for large clusters. Read once, when
        #: the allocator is first built.
        self.node_cidr_mask_size = 24
        self._svc_ips = None     # lazy ServiceIPAllocator
        self._node_cidrs = None  # lazy CIDRAllocator
        # Serialize-once response cache (encodecache.py): encoded JSON
        # bytes per (key, revision), shared by GET / LIST assembly /
        # the watch fan-out; invalidated on every store write.
        from .encodecache import EncodeCache
        self.encode_cache = EncodeCache()
        self.store.add_write_hook(self.encode_cache.invalidate)
        #: Chunk-scoped admission read memo: None outside a batch
        #: admission pass (the common case — one None check on the
        #: read paths), a {(verb, plural, ...): result} dict inside
        #: one (see batch_admission_context / admission.py's
        #: BATCH_MEMO_PLURALS).
        self._adm_memo: Optional[dict] = None
        self.store.add_write_hook(self._adm_memo_invalidate)
        #: Optional storage.replication.ReplicaNode: when set, every
        #: mutation dispatched through :meth:`run` is acknowledged only
        #: once quorum-committed (see run()); None = unreplicated, the
        #: byte-identical single-process path.
        self.replica = None
        for spec in builtin_resources():
            self.add_resource(spec)
        # Keyed watch dispatch (see MVCCStore.register_watch_index):
        # per-node pod watchers subscribe by node name, so fleet width
        # costs one dict lookup per pod event, not a scan of every
        # watcher.
        self.store.register_watch_index(
            "pods.spec.node_name", "/registry/pods/", _raw_pod_node_name)
        # Durable restart: re-install custom resources already defined.
        stored, _rev = self.store.list(
            "/registry/customresourcedefinitions/", copy=False)
        for s in stored:
            crd = from_dict(ext.CustomResourceDefinition, s.value)
            try:
                self._install_crd(crd)
            except errors.StatusError:
                pass  # name collision with a builtin added since

    def add_resource(self, spec: ResourceSpec) -> None:
        self._by_plural[spec.plural] = spec
        self._by_kind[spec.kind] = spec

    def spec_for(self, plural: str) -> ResourceSpec:
        try:
            return self._by_plural[plural]
        except KeyError:
            raise errors.NotFoundError(f"unknown resource type {plural!r}") from None

    def spec_for_kind(self, kind: str) -> ResourceSpec:
        try:
            return self._by_kind[kind]
        except KeyError:
            raise errors.NotFoundError(f"unknown kind {kind!r}") from None

    # -- periodic compaction ----------------------------------------------

    def compact_once(self) -> int:
        """One compactor cycle: compute the retention target under
        :attr:`compaction_policy` and advance the store's compacted
        floor to it. Returns the floor (unchanged when nothing is old
        enough yet). Safe to call directly — the endurance smoke and
        unit tests drive it without the async loop."""
        policy = self.compaction_policy
        if policy is None:
            return self.store.compact_rev
        now = time.monotonic()
        rev = self.store.revision
        self._compact_samples.append((now, rev))
        target = rev
        if policy.retention_revisions:
            target = min(target, rev - policy.retention_revisions)
        if policy.retention_seconds:
            # The newest sampled revision already older than the
            # retention window; no sample that old yet = no age bound
            # cleared, nothing may be compacted on age grounds.
            aged = 0
            cutoff = now - policy.retention_seconds
            for ts, r in self._compact_samples:
                if ts > cutoff:
                    break
                aged = r
            target = min(target, aged)
            # Samples older than the window stay useful only as the
            # single newest one; drop the rest so the list is bounded.
            while len(self._compact_samples) > 1 \
                    and self._compact_samples[1][0] <= cutoff:
                self._compact_samples.pop(0)
        if self.replica is not None:
            # Never compact past quorum: a follower catching up replays
            # from the commit revision — history above the commit point
            # must survive (committed-never-lost).
            target = min(target, self.replica.commit_rev)
        before = self.store.compact_rev
        floor = self.store.compact(target) if target > before else before
        if floor > before:
            STORAGE_COMPACTIONS.inc()
        STORAGE_COMPACT_REV.set(floor)
        STORAGE_WAL_BYTES.set(self.store.wal_bytes)
        STORAGE_HISTORY_LEN.set(self.store.history_len)
        return floor

    def start_compactor(self) -> None:
        """Spawn the periodic compactor on the running loop (no-op
        without a :class:`CompactionPolicy`). The apiserver calls this
        from ``start()``; embedded registries may call it directly."""
        if self.compaction_policy is None or self._compactor_task is not None:
            return

        async def _loop() -> None:
            while True:
                await asyncio.sleep(self.compaction_policy.interval_seconds)
                try:
                    self.compact_once()
                except Exception:  # noqa: BLE001 — keep compacting
                    logging.getLogger("registry").warning(
                        "compactor cycle failed", exc_info=True)

        self._compactor_task = asyncio.get_running_loop().create_task(_loop())

    def stop_compactor(self) -> None:
        if self._compactor_task is not None:
            self._compactor_task.cancel()
            self._compactor_task = None

    # -- keys -------------------------------------------------------------

    def _key(self, spec: ResourceSpec, namespace: str, name: str) -> str:
        if spec.namespaced:
            if not namespace:
                raise errors.BadRequestError(f"{spec.plural} is namespaced; namespace required")
            return f"/registry/{spec.plural}/{namespace}/{name}"
        return f"/registry/{spec.plural}/{name}"

    def _prefix(self, spec: ResourceSpec, namespace: str = "") -> str:
        if spec.namespaced and namespace:
            return f"/registry/{spec.plural}/{namespace}/"
        return f"/registry/{spec.plural}/"

    # -- codec ------------------------------------------------------------

    def _decode(self, spec: ResourceSpec, value: dict, rev: int) -> TypedObject:
        obj = from_dict(spec.cls, value)
        obj.api_version, obj.kind = spec.api_version, spec.kind
        obj.metadata.resource_version = str(rev)
        return obj

    def _encode(self, obj: TypedObject) -> dict:
        d = to_dict(obj)
        # resource_version is store-owned; never persist it inside the value.
        d.get("metadata", {}).pop("resource_version", None)
        return d

    # -- CRUD -------------------------------------------------------------

    def create(self, obj: TypedObject, dry_run: bool = False) -> TypedObject:
        spec, obj, key, create_span = self._prepare_create(obj, dry_run)
        if dry_run:
            return obj
        # IP/CIDR allocation happens last — after admission/validation/
        # dry_run. An already-existing object must surface AlreadyExists
        # (ktl apply's create-then-update fallback depends on it), never
        # a VIP-collision error against itself — so claims are skipped
        # when the key exists, and rollback releases ONLY values this
        # call allocated (releasing a duplicate explicit value would
        # free a block the stored owner still holds).
        rollback: list = []
        if not self.store.exists(key):
            rollback = self._claim_ips(obj)
        try:
            rev = self.store.create(key, self._encode(obj))
        except Exception:
            for release, value in rollback:
                release(value)
            raise
        return self._finish_create(obj, rev, create_span)

    def _prepare_create(self, obj: TypedObject, dry_run: bool = False
                        ) -> tuple:
        """Everything before the store write: defaulting, TypeMeta,
        admission, validation, the create span, the storage key.
        Returns ``(spec, obj, key, create_span)`` (``key`` is None for
        dry runs). Shared verbatim by :meth:`create` and the batch txn
        path so the batch amortizes the COMMIT, never policy."""
        spec = self.spec_for_kind(type(obj).__name__ if not obj.kind else obj.kind)
        obj = self.scheme.default(obj)
        # Stamp TypeMeta like update() does — clients must get fully
        # typed objects back regardless of transport.
        obj.api_version, obj.kind = spec.api_version, spec.kind
        meta = obj.metadata
        if spec.namespaced and not meta.namespace:
            meta.namespace = "default"
        if not spec.namespaced:
            meta.namespace = ""
        stamp_new(meta)
        meta.generation = 1
        # ktrace root: sampled Pods/PodGroups get a durable traceparent
        # annotation pointing at their "create" span — the id then
        # rides every watch event, so informers/agents that never saw
        # this request still join the trace. Disarmed (default): one
        # bool check; armed-but-unsampled: one rng call, no annotation.
        create_span = None
        if not dry_run and tracing.armed() \
                and spec.plural in ("pods", "podgroups"):
            anns = meta.annotations
            if tracing.TRACEPARENT_ANNOTATION not in anns:
                obj_key = f"{meta.namespace}/{meta.name}" \
                    if meta.namespace else meta.name
                attrs = {("pod" if spec.plural == "pods" else "group"):
                         obj_key}
                parent = tracing.current()
                if parent is not None and parent.sampled:
                    # A traced caller (its request/server span) roots
                    # this object's lifecycle in ITS trace.
                    create_span = tracing.start_span(
                        "create", component="apiserver", parent=parent,
                        attrs=attrs)
                else:
                    create_span = tracing.root_span(
                        "create", component="apiserver", attrs=attrs)
                ctx = create_span.context()
                if ctx is not None:
                    anns[tracing.TRACEPARENT_ANNOTATION] = \
                        tracing.encode(ctx)
        if (spec.has_status and hasattr(obj, "status")
                and not spec.preserve_status_on_create):
            # Strategy PrepareForCreate: clients cannot seed status.
            obj.status = type(obj.status)()
        if isinstance(obj, t.Secret):
            _merge_secret_string_data(obj)
        if self.admission is not None:
            obj = self.admission.admit("CREATE", spec, obj, None,
                                       dry_run=dry_run)
        # Generic meta validation on EVERY kind (reference:
        # ValidateObjectMeta), AFTER mutating admission — metadata a
        # plugin rewrites must not bypass the checks.
        val.validate_meta_generic(obj.metadata, spec.namespaced,
                                  spec.path_segment_name)
        if spec.validate_create:
            spec.validate_create(obj)
        if dry_run:
            return spec, obj, None, None
        if isinstance(obj, ext.CustomResourceDefinition):
            self._check_crd_collision(obj)
        key = self._key(spec, meta.namespace, meta.name)
        return spec, obj, key, create_span

    def _finish_create(self, obj: TypedObject, rev: int,
                       create_span) -> TypedObject:
        if isinstance(obj, ext.CustomResourceDefinition):
            self._install_crd(obj)
        obj.metadata.resource_version = str(rev)
        if create_span is not None:
            # Ends only on SUCCESS: a failed create's span is dropped
            # (never collected), matching "no object, no trace".
            create_span.end()
        return obj

    def create_batch(self, objs: list) -> list:
        """Create many objects in one dispatch, per-item outcomes.

        Each item runs the FULL single-create pipeline (defaulting,
        admission, validation, allocator claims) — the batch only
        amortizes transport/dispatch overhead, never policy. Under the
        ``BatchWriteTxn`` gate the chunk commits as ONE store
        transaction (:meth:`_create_batch_txn`) — one lock hold, one
        WAL record, one watch round — with per-item rejections
        split-committed around, so outcomes stay positional either
        way. Returns ``[(created, None) | (None, StatusError), ...]``;
        partial failure is not an error for the batch (reference: the
        per-item Status list of bulk APIs)."""
        from ..util.features import GATES
        if GATES.enabled("BatchWriteTxn") and len(objs) > 1:
            return self._create_batch_txn(objs)
        out = []
        for obj in objs:
            try:
                out.append((self.create(obj), None))
            except errors.StatusError as e:
                out.append((None, e))
        return out

    def _create_batch_txn(self, objs: list) -> list:
        """One chunk -> one :meth:`MVCCStore.txn`. Validation +
        admission run first as one batched pass (read-only admission
        lookups memoized chunk-wide via :meth:`batch_admission_context`
        — the quota charge path is NOT memoized and still CASes per
        item); items that fail policy or claims are rejected
        per-item before the txn; a :class:`TxnError` mid-commit (e.g.
        a duplicate key racing in from outside the batch) splits that
        item out and retries the remainder, so one bad item never
        aborts the chunk."""
        results: list = [None] * len(objs)
        prepared: list = []
        with self.batch_admission_context():
            for i, obj in enumerate(objs):
                try:
                    spec, pobj, key, span = self._prepare_create(obj)
                    prepared.append((i, pobj, key, span))
                except errors.StatusError as e:
                    results[i] = (None, e)
        pending: list = []
        for i, pobj, key, span in prepared:
            try:
                claims = ([] if self.store.exists(key)
                          else self._claim_ips(pobj))
            except errors.StatusError as e:
                results[i] = (None, e)
                BATCH_TXN_SPLITS.inc(kind="create")
                continue
            pending.append((i, pobj, key, span, claims,
                            self._encode(pobj)))
        while pending:
            ops = [(ADDED, p[2], p[5], None) for p in pending]
            try:
                revs = self.store.txn(ops)
            except TxnError as e:
                i, _pobj, _key, _span, claims, _val = pending.pop(e.index)
                for release, value in claims:
                    release(value)
                results[i] = (None, e.error)
                BATCH_TXN_SPLITS.inc(kind="create")
                continue
            except errors.StatusError as e:
                # Store-level failure (follower guard, chaos WAL
                # crash): nothing committed, every pending item fails.
                for i, _pobj, _key, claims in (
                        (p[0], p[1], p[2], p[4]) for p in pending):
                    for release, value in claims:
                        release(value)
                    results[i] = (None, e)
                break
            for (i, pobj, key, span, _claims, val), rev in zip(pending,
                                                               revs):
                # No inline encode here (hot-path-cost): the response's
                # emit_compact and the watch fan-out both read this
                # (key, rev) next and fill the serialize-once cache
                # through their off-loop/async-encode paths — the first
                # reader pays ONE encode, everyone else hits.
                results[i] = (self._finish_create(pobj, rev, span), None)
            BATCH_TXN_COMMITS.inc(kind="create")
            break
        return results

    def batch_admission_context(self):
        """Context manager arming the chunk-scoped admission read memo
        (see admission.py's ``BATCH_MEMO_PLURALS``). Reentrant-safe: a
        nested entry keeps the outer memo. Only successful results are
        memoized — NamespaceLifecycle's NotFound -> auto-create flow
        must re-read, and its create invalidates the plural anyway."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            if self._adm_memo is not None:
                yield
                return
            self._adm_memo = {}
            try:
                yield
            finally:
                self._adm_memo = None
        return _ctx()

    def _adm_memo_invalidate(self, key: str) -> None:
        # Store write hook (under the store lock): free when no batch
        # admission pass is active; inside one, a write to a memoized
        # plural drops that plural's entries.
        memo = self._adm_memo
        if not memo:
            return
        parts = key.split("/", 3)
        plural = parts[2] if len(parts) > 2 else ""
        stale = [k for k in memo if k[1] == plural]
        for k in stale:
            del memo[k]

    def _ensure_svc_allocator(self) -> None:
        """Lazy-build the VIP allocator, occupancy rebuilt from stored
        Services (reference keeps the bitmap in etcd; here the objects
        ARE the checkpoint)."""
        if self._svc_ips is None:
            from ..net.ipam import ServiceIPAllocator
            alloc = ServiceIPAllocator(self.service_cidr)
            stored, _rev = self.store.list("/registry/services/", copy=False)
            for s in stored:
                ip = (s.value.get("spec") or {}).get("cluster_ip", "")
                if ip and ip != "None":
                    alloc.occupy(ip)
            self._svc_ips = alloc

    def _ensure_node_allocator(self) -> None:
        if self._node_cidrs is None:
            from ..net.ipam import CIDRAllocator
            alloc = CIDRAllocator(self.cluster_cidr,
                                  node_prefix_len=self.node_cidr_mask_size)
            stored, _rev = self.store.list("/registry/nodes/", copy=False)
            for s in stored:
                cidr = (s.value.get("spec") or {}).get("pod_cidr", "")
                if cidr:
                    alloc.occupy(cidr)
            self._node_cidrs = alloc

    def _prepare_service(self, svc: t.Service) -> None:
        """Service create strategy: allocate the cluster VIP (reference:
        ``pkg/registry/core/service/storage`` + ipallocator). Headless
        services (cluster_ip "None") keep their sentinel."""
        if svc.spec.cluster_ip:
            return
        self._ensure_svc_allocator()
        svc.spec.cluster_ip = self._svc_ips.allocate()

    def _prepare_node(self, node: t.Node) -> None:
        """Node create strategy: assign the pod CIDR at birth so the
        agent never races the IPAM controller for its first pod IP
        (the controller keeps covering pre-existing durable nodes)."""
        if node.spec.pod_cidr:
            return
        self._ensure_node_allocator()
        node.spec.pod_cidr = self._node_cidrs.allocate()

    def _claim_ips(self, obj: TypedObject) -> list:
        """Create-path counterpart of :meth:`_release_ips`: allocate the
        VIP/CIDR when absent, or claim (occupy) an explicit value —
        rejecting one another object already holds. Returns
        ``[(release_fn, value), ...]`` for exactly what this call took,
        so a failed create rolls back nothing it does not own."""
        rollback: list = []
        if isinstance(obj, t.Service):
            if not obj.spec.cluster_ip:
                self._prepare_service(obj)
                rollback.append((self._svc_ips.release, obj.spec.cluster_ip))
            elif obj.spec.cluster_ip != "None":
                self._ensure_svc_allocator()
                if not _parses(self._svc_ips.contains, obj.spec.cluster_ip):
                    raise errors.InvalidError(
                        f"Service {obj.metadata.name!r}: spec.cluster_ip "
                        f"{obj.spec.cluster_ip} is outside the service "
                        f"CIDR {self.service_cidr}")
                if self._svc_ips.is_used(obj.spec.cluster_ip):
                    raise errors.InvalidError(
                        f"Service {obj.metadata.name!r}: spec.cluster_ip "
                        f"{obj.spec.cluster_ip} is already allocated")
                self._svc_ips.occupy(obj.spec.cluster_ip)
                rollback.append((self._svc_ips.release, obj.spec.cluster_ip))
        if isinstance(obj, t.Node):
            if not obj.spec.pod_cidr:
                self._prepare_node(obj)
                rollback.append((self._node_cidrs.release, obj.spec.pod_cidr))
            else:
                self._ensure_node_allocator()
                if not _parses(self._node_cidrs.contains, obj.spec.pod_cidr):
                    raise errors.InvalidError(
                        f"Node {obj.metadata.name!r}: spec.pod_cidr "
                        f"{obj.spec.pod_cidr} is not a /"
                        f"{self._node_cidrs.node_prefix_len} block of the "
                        f"cluster CIDR {self.cluster_cidr}")
                if self._node_cidrs.is_used(obj.spec.pod_cidr):
                    raise errors.InvalidError(
                        f"Node {obj.metadata.name!r}: spec.pod_cidr "
                        f"{obj.spec.pod_cidr} is already allocated")
                self._node_cidrs.occupy(obj.spec.pod_cidr)
                rollback.append((self._node_cidrs.release, obj.spec.pod_cidr))
        return rollback

    # -- CRDs (apiextensions-apiserver analog) ----------------------------

    def _install_crd(self, crd: ext.CustomResourceDefinition) -> None:
        """Dynamically add the CRD's resource: the HTTP routes are
        parameterized, so a registry-table entry is all installation
        takes (reference: apiextensions' dynamic handler)."""
        names = crd.spec.names
        self._check_crd_collision(crd)
        gv = crd.api_version_str()
        # One subclass per CRD keeps the scheme's class<->gvk bijective.
        cls = type(names.kind, (ext.CustomResource,), {})
        self.scheme.register(gv, names.kind, cls)
        self.add_resource(ResourceSpec(
            plural=names.plural, kind=names.kind, api_version=gv, cls=cls,
            namespaced=crd.spec.scope == ext.SCOPE_NAMESPACED,
            validate_create=ext.make_cr_validator(crd)))
        # Multi-version serving (conversion strategy None): extra
        # served versions get identity conversions to the storage
        # version — decode/encode swap api_version only. Scoped to
        # THIS registry's scheme; versions dropped by a CRD update are
        # unregistered (operators must be able to retire a version).
        # Reference: apiextensions served/storage version flags.
        from ..api import versioning
        prefix = f"{crd.spec.group}/"
        wanted = {f"{crd.spec.group}/{v}" for v in crd.spec.served_versions
                  if v != crd.spec.version}
        for av in self.scheme.conversions_for_kind(names.kind):
            if av.startswith(prefix) and av not in wanted:
                self.scheme.unregister_conversion(av, names.kind)
        for extra_gv in wanted:
            self.scheme.register_conversion(
                extra_gv, names.kind,
                *versioning.identity_conversion(extra_gv, gv))

    def _check_crd_collision(self, crd: ext.CustomResourceDefinition) -> None:
        """Reject plural OR kind collisions with builtins and with other
        CRDs. Re-installing the same CRD (same group/version/kind on the
        same plural — the update/reload path) is allowed."""
        gv = crd.api_version_str()
        names = crd.spec.names
        existing = self._by_plural.get(names.plural)
        if existing is not None and not (
                existing.api_version == gv and existing.kind == names.kind
                and issubclass(existing.cls, ext.CustomResource)):
            raise errors.InvalidError(
                f"CRD {crd.metadata.name!r}: plural {names.plural!r} "
                f"collides with an existing resource")
        by_kind = self._by_kind.get(names.kind)
        if by_kind is not None and by_kind.plural != names.plural:
            raise errors.InvalidError(
                f"CRD {crd.metadata.name!r}: kind {names.kind!r} "
                f"collides with an existing resource")

    def _uninstall_crd(self, crd: ext.CustomResourceDefinition) -> None:
        """Remove the resource + purge its stored objects (reference:
        the CRD finalizer deletes CRs before the definition goes)."""
        names = crd.spec.names
        spec = self._by_plural.get(names.plural)
        if spec is None or not issubclass(spec.cls, ext.CustomResource):
            return
        prefix = f"/registry/{names.plural}/"
        stored, _rev = self.store.list(prefix, copy=False)
        for s in stored:
            try:
                self.store.delete(s.key, expected_revision=s.mod_revision)
            except errors.StatusError:
                pass
        self._by_plural.pop(names.plural, None)
        if self._by_kind.get(names.kind) is spec:
            self._by_kind.pop(names.kind, None)
        self.scheme.unregister(crd.api_version_str(), names.kind)
        for extra in crd.spec.served_versions:
            self.scheme.unregister_conversion(
                f"{crd.spec.group}/{extra}", names.kind)

    def _release_ips(self, obj: TypedObject) -> None:
        """Return an object's IP/CIDR allocation on actual removal —
        both the delete() path and the finalizer-completion path in
        update()."""
        if isinstance(obj, t.Service) and self._svc_ips is not None \
                and obj.spec.cluster_ip and obj.spec.cluster_ip != "None":
            self._svc_ips.release(obj.spec.cluster_ip)
        if isinstance(obj, t.Node) and self._node_cidrs is not None \
                and obj.spec.pod_cidr:
            self._node_cidrs.release(obj.spec.pod_cidr)

    def get(self, plural: str, namespace: str, name: str) -> TypedObject:
        memo = self._adm_memo
        mk = None
        if memo is not None:
            from .admission import BATCH_MEMO_PLURALS
            if plural in BATCH_MEMO_PLURALS:
                mk = ("get", plural, namespace, name)
                hit = memo.get(mk)
                if hit is not None:
                    return hit
        spec = self.spec_for(plural)
        stored = self.store.get(self._key(spec, namespace, name), copy=False)
        obj = self._decode(spec, stored.value, stored.mod_revision)
        if mk is not None:
            memo[mk] = obj
        return obj

    # -- serialize-once reads (see encodecache.py) ------------------------

    def encoded_value(self, key: str, value: dict, rev: int,
                      which: str = "cur", codec: str = "json") -> bytes:
        """Encoded wire bytes of a stored object at ``rev``, with the
        store-owned resource_version injected — cached so every reader
        of the same revision (GET, LIST assembly, each watch fan-out
        consumer) shares ONE encode. ``codec``: "json" (default) or
        "compact" (CompactWireCodec msgpack payloads, cached beside
        the JSON lines under a ``#c``-suffixed ``which`` — same
        identity, same write invalidation). ``value`` must be the
        store-owned dict (never mutated here: the injection shallow-
        copies)."""
        from ..util import compactcodec
        ck_which = compactcodec.cache_which(which, codec)
        line = self.encode_cache.get(key, rev, ck_which)
        if line is None:
            obj = {**value,
                   "metadata": {**(value.get("metadata") or {}),
                                "resource_version": str(rev)}}
            line = compactcodec.encode_wire(obj, codec)
            self.encode_cache.put(key, rev, line, ck_which)
        return line

    def get_encoded(self, plural: str, namespace: str, name: str) -> bytes:
        """GET fast path: the object's wire bytes without the typed
        decode + re-encode round trip (storage-version readers only —
        version conversion takes the typed path)."""
        spec = self.spec_for(plural)
        stored = self.store.get(self._key(spec, namespace, name), copy=False)
        return self.encoded_value(stored.key, stored.value,
                                  stored.mod_revision)

    def list_encoded(self, plural: str, namespace: str = "",
                     label_selector: str = "", codec: str = "json"
                     ) -> tuple[list[bytes], int]:
        """LIST fast path: per-item wire bytes (cache-shared with GET
        and the watch fan-out) + the list revision. Label selectors
        match the raw stored dict, like :meth:`list`; field selectors
        need typed extraction and take the slow path. One snapshot/
        selector walk shared with the codec-pool path
        (:meth:`list_encoded_parts`) — the misses are simply encoded
        inline here. ``codec`` selects the wire encoding (see
        :meth:`encoded_value`)."""
        from ..util import compactcodec
        parts, misses, rev = self.list_encoded_parts(plural, namespace,
                                                     label_selector,
                                                     codec=codec)
        cache = self.encode_cache
        which = compactcodec.cache_which("cur", codec)
        for idx, key, mrev, value, token in misses:
            line = compactcodec.encode_wire(value, codec)
            cache.finish_async_encode(key, mrev, line, token, which=which)
            parts[idx] = line
        return parts, rev

    def list_encoded_parts(self, plural: str, namespace: str = "",
                           label_selector: str = "", codec: str = "json"
                           ) -> tuple[list, list, int]:
        """The codec-pool half of the LIST fast path: cached wire bytes
        where the serialize-once cache has them, and MISS records
        ``(index, key, mod_revision, value_with_rv, token)`` for the
        rest, so the apiserver can encode the misses off the event
        loop and re-enter them through the cache's async-encode guard
        (``token`` is minted BEFORE the value is read — a write racing
        the pool encode provably invalidates it). Returns
        ``(parts, misses, revision)`` with ``parts[index] is None`` at
        each miss slot. ``codec`` keys the cache lookups (compact
        payloads live beside the JSON lines; one write invalidates
        both)."""
        from ..util import compactcodec
        spec = self.spec_for(plural)
        stored, rev = self.store.list(self._prefix(spec, namespace),
                                      copy=False)
        sel = parse_selector(label_selector) if label_selector else None
        which = compactcodec.cache_which("cur", codec)
        parts: list = []
        misses: list = []
        for s in stored:
            if sel is not None:
                raw_labels = (s.value.get("metadata") or {}).get("labels") or {}
                if not sel.matches(raw_labels):
                    continue
            line = self.encode_cache.get(s.key, s.mod_revision, which)
            if line is None:
                token = self.encode_cache.begin_async_encode(s.key)
                obj = {**s.value,
                       "metadata": {**(s.value.get("metadata") or {}),
                                    "resource_version": str(s.mod_revision)}}
                misses.append((len(parts), s.key, s.mod_revision, obj,
                               token))
                parts.append(None)
            else:
                parts.append(line)
        return parts, misses, rev

    def list(self, plural: str, namespace: str = "", label_selector: str = "",
             field_selector: str = "") -> tuple[list[TypedObject], int]:
        memo = self._adm_memo
        mk = None
        if memo is not None:
            from .admission import BATCH_MEMO_PLURALS
            if plural in BATCH_MEMO_PLURALS:
                mk = ("list", plural, namespace, label_selector,
                      field_selector)
                hit = memo.get(mk)
                if hit is not None:
                    return hit
        spec = self.spec_for(plural)
        stored, rev = self.store.list(self._prefix(spec, namespace), copy=False)
        sel = parse_selector(label_selector) if label_selector else None
        if field_selector and not spec.field_extractor:
            raise errors.BadRequestError(
                f"{spec.plural} does not support field selectors")
        out = []
        for s in stored:
            if sel is not None:
                # Label prefilter on the RAW stored dict — decoding
                # every filtered-out object was a dominant cost for
                # selector lists at density scale.
                raw_labels = (s.value.get("metadata") or {}).get("labels") or {}
                if not sel.matches(raw_labels):
                    continue
            obj = self._decode(spec, s.value, s.mod_revision)
            if field_selector and not match_field_selector(
                    field_selector, spec.field_extractor(obj)):
                continue
            out.append(obj)
        if mk is not None:
            memo[mk] = (out, rev)
        return out, rev

    def list_page(self, plural: str, namespace: str = "",
                  label_selector: str = "", field_selector: str = "",
                  limit: int = 0, continue_token: str = ""
                  ) -> tuple[list[TypedObject], int, str]:
        """Paginated LIST (reference: meta.v1 ListOptions limit/continue,
        ``etcd3/store.go`` range pagination). Items are key-ordered;
        the opaque continue token resumes after the last key served.

        Divergence from etcd-backed pagination, documented: pages read
        the CURRENT revision, not the first page's snapshot — objects
        created/deleted between pages may appear/miss (the reference's
        own "inconsistent continue" fallback after compaction has the
        same contract). ``limit`` counts items POST-selector, like the
        reference."""
        import base64 as b64
        after = ""
        if continue_token:
            try:
                decoded = b64.b64decode(continue_token, validate=True).decode()
                tok_rev, after = decoded.split("\x00", 1)
                int(tok_rev)  # token carries the minting revision; must be numeric
            except Exception:  # noqa: BLE001
                raise errors.BadRequestError("malformed continue token") from None
        spec = self.spec_for(plural)
        stored, rev = self.store.list(self._prefix(spec, namespace), copy=False)
        sel = parse_selector(label_selector) if label_selector else None
        if field_selector and not spec.field_extractor:
            raise errors.BadRequestError(
                f"{spec.plural} does not support field selectors")
        out: list[TypedObject] = []
        cont = ""
        # Defensive init only: cont is minted after >=1 append today,
        # but a reorder of the limit check must not hit a NameError.
        last_key = after
        for s in stored:  # store.list returns key-sorted items
            if after and s.key <= after:
                continue
            if sel is not None:
                raw_labels = (s.value.get("metadata") or {}).get("labels") or {}
                if not sel.matches(raw_labels):
                    continue
            obj = self._decode(spec, s.value, s.mod_revision)
            if field_selector and not match_field_selector(
                    field_selector, spec.field_extractor(obj)):
                continue
            if limit and len(out) >= limit:
                # One extra match proves there are more pages.
                cont = b64.b64encode(
                    f"{rev}\x00{last_key}".encode()).decode()
                break
            out.append(obj)
            last_key = s.key
        return out, rev, cont

    def update(self, obj: TypedObject, subresource: str = "",
               dry_run: bool = False) -> TypedObject:
        """Full-object update with optimistic concurrency.

        ``subresource=''``: spec/meta update, status preserved from old.
        ``subresource='status'``: status update, spec/meta preserved.
        ``dry_run=True`` stops after defaulting + admission +
        validation and returns the would-be object (no allocator or
        store side effects) — the apiserver uses it to show validating
        webhooks the post-in-tree-admission object.
        """
        spec = self.spec_for_kind(obj.kind or type(obj).__name__)
        if subresource == "status" and not spec.has_status:
            raise errors.MethodNotAllowedError(
                f"{spec.kind} has no status subresource")
        meta = obj.metadata
        key = self._key(spec, meta.namespace, meta.name)
        stored = self.store.get(key, copy=False)
        old = self._decode(spec, stored.value, stored.mod_revision)
        if meta.resource_version and meta.resource_version != old.metadata.resource_version:
            raise errors.ConflictError(
                f"{spec.kind} {obj.key()!r}: stale resource_version "
                f"{meta.resource_version} (current {old.metadata.resource_version})"
            )
        new = obj
        if spec.has_status and hasattr(obj, "status"):
            if subresource == "status":
                full = from_dict(spec.cls, self._encode(old))
                full.status = obj.status
                full.metadata = old.metadata
                new = full
            else:
                new.status = old.status
        if subresource != "status":
            # Immutable server-owned fields.
            new.metadata.uid = old.metadata.uid
            new.metadata.creation_timestamp = old.metadata.creation_timestamp
            if isinstance(new, t.Secret):
                _merge_secret_string_data(new)
            if self._spec_changed(spec, new, old):
                new.metadata.generation = old.metadata.generation + 1
            else:
                new.metadata.generation = old.metadata.generation
            if self.admission is not None:
                new = self.admission.admit("UPDATE", spec, new, old,
                                           dry_run=dry_run)
            val.validate_meta_generic(new.metadata, spec.namespaced,
                                      spec.path_segment_name)
            if spec.validate_update:
                spec.validate_update(new, old)
            elif spec.validate_create:
                spec.validate_create(new, False)
        new.api_version, new.kind = spec.api_version, spec.kind
        if dry_run:
            return new
        # Finalizer-driven actual deletion: once an object marked for
        # deletion has no finalizers left, the update removes it.
        ns_finalizers = (isinstance(new, t.Namespace) and new.spec.finalizers)
        # Scheduled pods keep their graceful contract: clearing the last
        # finalizer must hand the pod to the node agent's termination
        # flow (grace-0 confirmation completes it), not hard-delete a
        # pod whose containers are still running.
        graceful_pod = (spec.graceful_delete and isinstance(new, t.Pod)
                        and bool(new.spec.node_name))
        if new.metadata.deletion_timestamp is not None \
                and not new.metadata.finalizers and not ns_finalizers \
                and not graceful_pod:
            self.store.delete(key, expected_revision=stored.mod_revision)
            self._release_ips(new)
            if isinstance(new, ext.CustomResourceDefinition):
                self._uninstall_crd(new)
            new.metadata.resource_version = str(self.store.revision)
            return new
        # The registry is the ONLY pod-CIDR allocator (a second,
        # controller-side allocator would race it): nodes that still
        # lack a CIDR — legacy durable data — get one on their next
        # write (the IPAM controller just triggers that write).
        if isinstance(new, t.Node) and subresource != "status":
            if not new.spec.pod_cidr:
                self._prepare_node(new)
            elif self._node_cidrs is not None:
                self._node_cidrs.occupy(new.spec.pod_cidr)
        # (Cluster-IP immutability lives in validate_service_update —
        # one definition of the rule, enforced on every update path.)
        rev = self.store.update(key, self._encode(new),
                                expected_revision=stored.mod_revision)
        if isinstance(new, ext.CustomResourceDefinition):
            # Schema may have changed: refresh the validator closure
            # (identity fields are immutable per validate_crd_update).
            self._install_crd(new)
        new.metadata.resource_version = str(rev)
        return new

    def _spec_changed(self, spec: ResourceSpec, new: TypedObject, old: TypedObject) -> bool:
        if not hasattr(new, "spec"):
            return False
        return to_dict(new.spec) != to_dict(old.spec)

    def preview_patch(self, cur: TypedObject, patch,
                      strategic: bool = False) -> dict:
        """The merged object dict a patch WOULD produce against ``cur``
        — shared by :meth:`patch` and the apiserver's webhook path
        (hooks must see the post-merge object, not the raw patch).
        A LIST patch is RFC 6902 JSON Patch (the body shape is
        self-describing: merge patches are objects, op lists are
        arrays — reference types.go JSONPatchType)."""
        spec = self.spec_for_kind(cur.kind or type(cur).__name__)
        if isinstance(patch, list):
            from .webhooks import apply_json_patch
            try:
                merged = apply_json_patch(self._encode(cur), patch)
            except ValueError as e:
                raise errors.BadRequestError(str(e)) from None
            if not isinstance(merged, dict):
                raise errors.BadRequestError(
                    "json patch must produce an object")
        elif strategic:
            from ..api.patch import strategic_merge
            merged = strategic_merge(self._encode(cur), patch, spec.cls)
        else:
            merged = _json_merge(self._encode(cur), patch)
        merged.setdefault("api_version", spec.api_version)
        merged.setdefault("kind", spec.kind)
        return merged

    def patch(self, plural: str, namespace: str, name: str, patch,
              subresource: str = "", strategic: bool = False) -> TypedObject:
        """JSON merge-patch (RFC 7386), RFC 6902 JSON Patch (list
        body), or, with ``strategic=True``, strategic merge patch
        (list merge by per-type keys — see ``api/patch.py``)."""
        spec = self.spec_for(plural)
        for _ in range(10):
            cur = self.get(plural, namespace, name)
            merged = self.preview_patch(cur, patch, strategic)
            obj = from_dict(spec.cls, merged)
            obj.api_version, obj.kind = spec.api_version, spec.kind
            obj.metadata.resource_version = cur.metadata.resource_version
            try:
                return self.update(obj, subresource=subresource)
            except errors.ConflictError:
                continue
        raise errors.ConflictError(f"patch {plural}/{namespace}/{name}: too much contention")

    def delete(self, plural: str, namespace: str, name: str,
               grace_period_seconds: Optional[int] = None,
               preconditions_uid: str = "",
               propagation_policy: str = "") -> TypedObject:
        """``propagation_policy``: "" / "Background" (delete now, GC
        cascades later — the default), "Orphan" (GC strips dependents'
        owner refs so they survive), "Foreground" (GC deletes
        dependents FIRST; the owner stays terminating until none
        remain). Reference: metav1.DeletionPropagation, carried as the
        orphan/foregroundDeletion finalizers so a crash mid-cascade
        resumes instead of leaking."""
        spec = self.spec_for(plural)
        key = self._key(spec, namespace, name)
        stored = self.store.get(key, copy=False)
        obj = self._decode(spec, stored.value, stored.mod_revision)
        if preconditions_uid and obj.metadata.uid != preconditions_uid:
            raise errors.ConflictError(
                f"uid precondition failed: have {obj.metadata.uid}, want {preconditions_uid}")
        if propagation_policy not in ("", "Background", "Orphan",
                                      "Foreground"):
            raise errors.BadRequestError(
                f"propagation_policy must be Background, Orphan, or "
                f"Foreground; got {propagation_policy!r}")
        from ..api.meta import FINALIZER_FOREGROUND, FINALIZER_ORPHAN
        want_fin = {"Orphan": FINALIZER_ORPHAN,
                    "Foreground": FINALIZER_FOREGROUND}.get(propagation_policy)
        if want_fin and want_fin not in obj.metadata.finalizers:
            obj.metadata.finalizers.append(want_fin)
            if obj.metadata.deletion_timestamp is not None:
                # Already terminating: the no-op branches below would
                # silently drop the just-requested policy — persist it.
                rev = self.store.update(key, self._encode(obj),
                                        expected_revision=stored.mod_revision)
                obj.metadata.resource_version = str(rev)
                return obj
        graceful = spec.graceful_delete and (grace_period_seconds is None or grace_period_seconds > 0)
        # Namespace deletion is finalizer-gated via spec.finalizers: the
        # namespace controller purges contents, then clears them
        # (reference: pkg/registry/core/namespace + namespace controller).
        if isinstance(obj, t.Namespace) and obj.spec.finalizers \
                and obj.metadata.deletion_timestamp is None:
            obj.metadata.deletion_timestamp = now()
            obj.status.phase = t.NS_TERMINATING
            rev = self.store.update(key, self._encode(obj),
                                    expected_revision=stored.mod_revision)
            obj.metadata.resource_version = str(rev)
            return obj
        if graceful and isinstance(obj, t.Pod) and not obj.spec.node_name:
            # Unscheduled pods have no node agent to confirm termination:
            # delete immediately (reference: pkg/registry/core/pod/strategy.go
            # CheckGracefulDelete zeroes grace when the pod is unassigned).
            graceful = False
        if obj.metadata.deletion_timestamp is None and (graceful or obj.metadata.finalizers):
            # First DELETE: mark, don't remove (kubelet / finalizer owners
            # complete the deletion). Reference: graceful pod termination.
            obj.metadata.deletion_timestamp = now()
            if spec.graceful_delete and isinstance(obj, t.Pod):
                gp = grace_period_seconds
                if gp is None:
                    gp = obj.spec.termination_grace_period_seconds
                obj.spec.termination_grace_period_seconds = gp
            rev = self.store.update(key, self._encode(obj),
                                    expected_revision=stored.mod_revision)
            obj.metadata.resource_version = str(rev)
            return obj
        if obj.metadata.finalizers:
            # Already terminating but finalizers present: no-op.
            return obj
        if (obj.metadata.deletion_timestamp is not None and graceful):
            # Repeated graceful DELETE on an already-terminating pod is an
            # idempotent no-op; only an explicit grace 0 (the node agent's
            # confirmation) completes removal — reference semantics.
            return obj
        self.store.delete(key, expected_revision=stored.mod_revision)
        self._release_ips(obj)
        if isinstance(obj, ext.CustomResourceDefinition):
            self._uninstall_crd(obj)
        return obj

    def delete_collection(self, plural: str, namespace: str = "",
                          label_selector: str = "") -> int:
        items, _ = self.list(plural, namespace, label_selector)
        n = 0
        for obj in items:
            try:
                self.delete(plural, obj.metadata.namespace, obj.metadata.name,
                            grace_period_seconds=0)
                n += 1
            except errors.NotFoundError:
                pass
        return n

    # -- watch ------------------------------------------------------------

    def watch(self, plural: str, namespace: str = "", start_revision: int = 0,
              label_selector: str = "", field_selector: str = "",
              loop: Optional[asyncio.AbstractEventLoop] = None) -> "ObjectWatch":
        spec = self.spec_for(plural)
        raw = self.store.watch(self._prefix(spec, namespace), start_revision,
                               loop=loop,
                               index=_watch_index_hint(plural, field_selector))
        return ObjectWatch(self, spec, raw, label_selector, field_selector)

    def watch_raw(self, plural: str, namespace: str = "",
                  start_revision: int = 0, label_selector: str = "",
                  loop: Optional[asyncio.AbstractEventLoop] = None
                  ) -> "RawObjectWatch":
        """Raw-dict watch for wire serving (no typed decode per event);
        see :class:`RawObjectWatch`. Field-selector watchers must use
        :meth:`watch`."""
        spec = self.spec_for(plural)
        raw = self.store.watch(self._prefix(spec, namespace), start_revision,
                               loop=loop)
        return RawObjectWatch(raw, label_selector)

    async def run(self, fn, *args):
        """Async dispatch for a registry call: inline when the store is
        purely in-memory (sub-ms CPU work — a to_thread handoff costs
        more than it buys and the GIL serializes it anyway), via a
        worker thread when a WAL append may block on disk. The single
        policy point shared by LocalClient and the apiserver.

        Replicated control plane (``self.replica`` set): a call that
        wrote is acknowledged only after ITS OWN highest revision is
        quorum-committed (per-thread capture — a concurrent neighbor's
        in-flight write can neither be waited on nor ride this ack) —
        the client's success response IS the durability promise the
        committed-never-lost invariant checks. Reads (nothing written)
        return immediately."""
        replica = self.replica
        if replica is None:
            if self.store.durable:
                return await asyncio.to_thread(fn, *args)
            return fn(*args)
        if self.store.durable:
            out, rev = await asyncio.to_thread(
                self.store.last_write_in, fn, *args)
        else:
            out, rev = self.store.last_write_in(fn, *args)
        if rev:
            await self.await_commit(replica, rev)
        return out

    @staticmethod
    async def await_commit(replica, rev: int) -> None:
        """Await quorum commit of ``rev`` from WHATEVER loop the caller
        runs on. The replica's commit machinery (waiter futures,
        ``_set_commit``) lives on the loop that started it; a sharded
        apiserver worker awaiting from its own loop must hop — a
        future created here and completed from the replica's loop
        would wake through the wrong loop's call_soon (a cross-thread
        asyncio error, or worse, a silent lost wakeup)."""
        rloop = getattr(replica, "_loop", None)
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if rloop is None or rloop is running:
            await replica.wait_commit(rev)
            return
        cfut = asyncio.run_coroutine_threadsafe(
            replica.wait_commit(rev), rloop)
        try:
            await asyncio.wrap_future(cfut)
        except asyncio.CancelledError:
            cfut.cancel()
            raise

    # -- pods/eviction subresource ----------------------------------------

    EVICTION_CAS_RETRIES = 20
    #: disrupted_pods entries older than this are the PDB controller's
    #: to prune; the eviction handler refuses only on a huge backlog.
    MAX_DISRUPTED_PODS = 2000

    def evict_pod(self, namespace: str, name: str,
                  eviction: t.Eviction) -> t.Pod:
        """The PDB-gated voluntary delete (reference:
        ``pkg/registry/core/pod/storage/eviction.go:57-120`` Create +
        checkAndDecrement). Finds the PDB covering the pod,
        verify-and-decrements ``status.disruptions_allowed`` with CAS
        retry, records the pod in ``disrupted_pods``, then deletes.
        429 (TooManyRequests) when the budget allows no disruption —
        the caller's signal to retry later, never to bypass.

        ``eviction.override_budget`` (priority policy: preemption,
        dead-node escalation) skips the allowed check but still records
        the disruption so the controller's arithmetic stays honest."""
        pod = self.get("pods", namespace, name)
        pdbs, _rev = self.list("poddisruptionbudgets", namespace)
        # selector None = match-all, the SAME rule the disruption
        # controller applies — the gate and the arithmetic must agree.
        covering = [p for p in pdbs
                    if p.spec.selector is None
                    or p.spec.selector.matches(pod.metadata.labels)]
        charged: list[tuple[str, str, bool]] = []  # (ns, pdb, decremented)
        try:
            if eviction.override_budget:
                # The escape hatch must actually open: record the
                # disruption in EVERY covering budget, no gate — a dead
                # node's pod covered by two overlapping PDBs still has
                # to go somewhere else.
                for pdb in covering:
                    self._check_and_decrement(
                        pdb.metadata.namespace, pdb.metadata.name,
                        pod.metadata.name, override=True)
                    charged.append((pdb.metadata.namespace,
                                    pdb.metadata.name, False))
            elif len(covering) > 1:
                # Reference parity: ambiguous coverage is a hard error
                # for VOLUNTARY evictions. details.cause marks this a
                # BUDGET refusal — callers' escalation clocks key on it
                # and must never start on a generic 503.
                raise errors.ServiceUnavailableError(
                    f"pod {namespace}/{name} is covered by more than one "
                    f"PodDisruptionBudget "
                    f"({sorted(p.metadata.name for p in covering)})",
                    details={"cause": "DisruptionBudget"})
            elif covering:
                self._check_and_decrement(covering[0].metadata.namespace,
                                          covering[0].metadata.name,
                                          pod.metadata.name, override=False)
                charged.append((covering[0].metadata.namespace,
                                covering[0].metadata.name, True))
        except errors.StatusError:
            # A later budget's CAS storm must not leave an earlier
            # budget charged for a disruption that never happened.
            for cns, cname, decremented in charged:
                self._refund_charge(cns, cname, pod.metadata.name,
                                    decremented)
            raise
        try:
            return self.delete(
                "pods", namespace, name,
                grace_period_seconds=eviction.grace_period_seconds)
        except errors.StatusError:
            # The delete did not happen (pod vanished between get and
            # delete, store refusal): a charged-but-undisrupted budget
            # would block legitimate evictions for the controller's
            # disrupted-pods timeout, so best-effort refund it.
            for cns, cname, decremented in charged:
                self._refund_charge(cns, cname, pod.metadata.name,
                                    decremented)
            raise

    def _refund_charge(self, ns: str, pdb_name: str, pod_name: str,
                       decremented: bool) -> None:
        """Best-effort undo of _check_and_decrement's accounting."""
        for _ in range(self.EVICTION_CAS_RETRIES):
            try:
                pdb = self.get("poddisruptionbudgets", ns, pdb_name)
            except errors.NotFoundError:
                return
            st = pdb.status
            if pod_name not in st.disrupted_pods:
                return  # controller already pruned it
            st.disrupted_pods = {k: v for k, v in st.disrupted_pods.items()
                                 if k != pod_name}
            if decremented:
                st.disruptions_allowed += 1
            try:
                self.update(pdb, subresource="status")
                return
            except errors.ConflictError:
                continue
            except errors.StatusError:
                return  # refund is best-effort by design

    def _check_and_decrement(self, ns: str, pdb_name: str, pod_name: str,
                             override: bool = False) -> None:
        for _ in range(self.EVICTION_CAS_RETRIES):
            try:
                pdb = self.get("poddisruptionbudgets", ns, pdb_name)
            except errors.NotFoundError:
                return  # PDB vanished: nothing gates the eviction
            st = pdb.status
            # details.cause distinguishes a budget refusal from other
            # 429s (e.g. apiserver max-in-flight) — the escalation
            # clocks in nodelifecycle/drain key on it (reference:
            # StatusCause Type "DisruptionBudget", eviction.go).
            cause = {"cause": "DisruptionBudget", "budget": pdb_name}
            if not override:
                if st.observed_generation < pdb.metadata.generation:
                    raise errors.TooManyRequestsError(
                        f"cannot evict {pod_name}: the disruption "
                        f"budget {pdb_name!r} is still being processed "
                        f"by the server", details=cause)
                if len(st.disrupted_pods) >= self.MAX_DISRUPTED_PODS:
                    raise errors.ForbiddenError(
                        f"too many evictions not yet confirmed by the "
                        f"disruption controller for {pdb_name!r}",
                        details=cause)
                if st.disruptions_allowed <= 0:
                    raise errors.TooManyRequestsError(
                        f"cannot evict {pod_name}: it would violate "
                        f"the disruption budget {pdb_name!r} "
                        f"(needs {st.desired_healthy} healthy, has "
                        f"{st.current_healthy})", details=cause)
                st.disruptions_allowed -= 1
            st.disrupted_pods = dict(st.disrupted_pods)
            st.disrupted_pods[pod_name] = meta_stamp(now())
            try:
                self.update(pdb, subresource="status")
                return
            except errors.ConflictError:
                continue
        raise errors.ConflictError(
            f"too much contention updating disruption budget {pdb_name!r}")

    # -- pods/binding subresource ----------------------------------------

    def bind_pod(self, namespace: str, name: str, binding: t.Binding,
                 decode: bool = True) -> Optional[t.Pod]:
        """Atomically set node_name + chip assignments + PodScheduled.

        Reference: ``BindingREST.Create`` -> ``setPodHostAndAnnotations``
        (``pkg/registry/core/pod/storage/storage.go:138-197``): one
        GuaranteedUpdate writes host and device IDs together.
        ``decode=False`` skips typing the written pod for callers that
        only need success/failure (the batch bind path — its response
        carries per-item status, not pod echoes).
        """
        spec = self.spec_for("pods")
        key = self._key(spec, namespace, name)
        target = binding.target

        def apply(cur: Optional[dict]) -> dict:
            return self._bind_value(namespace, name, target, cur)

        value, rev = self.store.guaranteed_update(key, apply)
        if not decode:
            return None
        return self._decode(spec, value, rev)

    def _bind_value(self, namespace: str, name: str, target,
                    cur: Optional[dict]) -> dict:
        # Dict-level on the stored value: a bind touches node_name,
        # claim assignments, and one condition of a pod that is
        # otherwise UNCHANGED — the full scheme decode + re-encode
        # this replaces was a measured per-bind hot-path cost at
        # density scale. ``cur`` is the caller's private copy
        # (guaranteed_update's, or the batch path's _freeze), so
        # in-place mutation is safe. Semantics mirror the typed path
        # (update_pod_condition) exactly.
        meta = cur.get("metadata") or {}
        if meta.get("deletion_timestamp") is not None:
            raise errors.ConflictError(f"pod {namespace}/{name} is terminating")
        spec_d = cur.get("spec") or {}
        bound_to = spec_d.get("node_name") or ""
        if bound_to and bound_to != target.node_name:
            raise errors.ConflictError(
                f"pod {namespace}/{name} already bound to {bound_to}")
        spec_d["node_name"] = target.node_name
        cur["spec"] = spec_d
        by_name = {b.name: b for b in target.tpu_bindings}
        claims = spec_d.get("tpu_resources") or []
        for claim in claims:
            b = by_name.pop(claim.get("name", ""), None)
            if b is not None:
                claim["assigned"] = list(b.chip_ids)
        if by_name:
            raise errors.BadRequestError(
                f"binding names {sorted(by_name)} match no tpu_resources claim")
        missing = [c.get("name", "") for c in claims
                   if not c.get("assigned")]
        if missing:
            raise errors.BadRequestError(
                f"binding must assign chips for claims {missing}")
        status_d = cur.get("status") or {}
        conds = status_d.get("conditions") or []
        existing = next((c for c in conds
                         if c.get("type") == t.COND_POD_SCHEDULED), None)
        if existing is None or existing.get("status") != "True" \
                or existing.get("reason") or existing.get("message"):
            newc = to_dict(t.PodCondition(
                type=t.COND_POD_SCHEDULED, status="True",
                last_transition_time=now()))
            if existing is not None:
                if existing.get("status") == "True":
                    # Same truth value: transition time is preserved
                    # (update_pod_condition semantics).
                    newc["last_transition_time"] = \
                        existing.get("last_transition_time")
                conds.remove(existing)
            conds.append(newc)
        status_d["conditions"] = conds
        cur["status"] = status_d
        meta.pop("resource_version", None)
        return cur

    def bind_pods_batch(self, namespace: str,
                        items: list[tuple[str, t.Binding]]) -> list:
        """Bind many pods in one dispatch, per-item outcomes.

        Each (name, binding) pair runs :meth:`bind_pod`'s full
        guaranteed-update (atomic node+chips write, conflict checks);
        only the per-call transport/bookkeeping is amortized. Returns
        ``[(None, None) | (None, StatusError), ...]`` positionally —
        success carries no pod echo (callers read results through
        informers), and one failed member never aborts the rest (the
        gang path owns rollback policy, not the storage layer). Under
        ``BatchWriteTxn`` the chunk commits as one CAS-guarded store
        transaction (:meth:`_bind_batch_txn`), same per-item
        semantics."""
        from ..util.features import GATES
        if GATES.enabled("BatchWriteTxn") and len(items) > 1:
            return self._bind_batch_txn(namespace, items)
        out = []
        for name, binding in items:
            try:
                out.append((self.bind_pod(namespace, name, binding,
                                          decode=False), None))
            except errors.StatusError as e:
                out.append((None, e))
        return out

    def _bind_batch_txn(self, namespace: str,
                        items: list[tuple[str, t.Binding]]) -> list:
        """One bind chunk -> one :meth:`MVCCStore.txn` of CAS-guarded
        MODIFIED ops. The new values are computed OUTSIDE the store
        lock from each pod's current revision; a concurrent writer
        losing us the CAS aborts the (all-or-nothing) txn and the
        whole remainder recomputes — the guaranteed_update retry loop,
        amortized over the chunk. Per-item policy failures (already
        bound elsewhere, terminating, bad claim names) drop just that
        item, like the single-bind path."""
        spec = self.spec_for("pods")
        results: list = [None] * len(items)
        pending = [(i, name, binding)
                   for i, (name, binding) in enumerate(items)]
        # Convergence is quick in practice (one recompute per losing
        # race); the cap only guards a livelock under pathological
        # write pressure, mirroring guaranteed_update's own bound.
        for _attempt in range(100):
            if not pending:
                break
            ops = []
            in_txn = []
            for i, name, binding in pending:
                key = self._key(spec, namespace, name)
                try:
                    cur = self.store.get(key, copy=False)
                    new = self._bind_value(
                        namespace, name, binding.target,
                        MVCCStore._freeze(cur.value))
                except errors.StatusError as e:
                    results[i] = (None, e)
                    BATCH_TXN_SPLITS.inc(kind="bind")
                    continue
                ops.append((MODIFIED, key, new, cur.mod_revision))
                in_txn.append((i, name, binding))
            pending = in_txn
            if not ops:
                break
            try:
                self.store.txn(ops)
            except TxnError as e:
                if isinstance(e.error, errors.ConflictError):
                    # CAS lost to a concurrent writer — recompute the
                    # whole (aborted) chunk against fresh revisions.
                    continue
                i, _name, _binding = pending.pop(e.index)
                results[i] = (None, e.error)
                BATCH_TXN_SPLITS.inc(kind="bind")
                continue
            except errors.StatusError as e:
                # Store-level failure (follower guard, chaos WAL
                # crash): nothing committed, per-item outcome for all.
                for i, _name, _binding in pending:
                    results[i] = (None, e)
                pending = []
                break
            for i, _name, _binding in pending:
                results[i] = (None, None)
            BATCH_TXN_COMMITS.inc(kind="bind")
            pending = []
        for i, name, _binding in pending:
            results[i] = (None, errors.ConflictError(
                f"pod {namespace}/{name}: batch bind kept losing the "
                f"revision race; retry"))
        return results


class ObjectWatch:
    """Decoded, selector-filtered watch stream.

    Label-selector transitions are translated the way the reference's
    watch cache does: an object entering the selected set surfaces as
    ADDED, leaving it as DELETED.
    """

    #: Event type surfaced when the underlying stream ends (consumer must
    #: reconnect/relist). Distinct from a ``None`` idle-timeout return.
    CLOSED = "CLOSED"

    def __init__(self, registry: Registry, spec: ResourceSpec, raw: Watch,
                 label_selector: str = "", field_selector: str = ""):
        self._registry = registry
        self._spec = spec
        self._raw = raw
        self._sel = parse_selector(label_selector) if label_selector else None
        if field_selector and not spec.field_extractor:
            raw.cancel()
            raise errors.BadRequestError(
                f"{spec.plural} does not support field selectors")
        self._fsel = field_selector

    def cancel(self) -> None:
        self._raw.cancel()

    @property
    def closed(self) -> bool:
        return self._raw.closed

    def _match(self, obj: Optional[TypedObject]) -> bool:
        if obj is None:
            return False
        if self._sel and not self._sel.matches(obj.metadata.labels):
            return False
        if self._fsel and not match_field_selector(
                self._fsel, self._spec.field_extractor(obj)):
            return False
        return True

    async def next(self, timeout: Optional[float] = None):
        while True:
            ev = await self._raw.next(timeout)
            if ev is None:
                if self._raw.closed:
                    return (self.CLOSED, None)
                return None
            out = self._translate(ev)
            if out is not None:
                return out

    def next_nowait(self):
        """An already-delivered (translated) event or None — the
        fan-out drain primitive (see ``Watch.next_nowait``)."""
        while True:
            ev = self._raw.next_nowait()
            if ev is None:
                return None
            out = self._translate(ev)
            if out is not None:
                return out

    def _translate(self, ev: WatchEvent):
        obj = self._registry._decode(self._spec, ev.value, ev.revision)
        old = (self._registry._decode(self._spec, ev.prev_value, ev.revision)
               if ev.prev_value is not None else None)
        old_match = self._match(old)
        if ev.type == DELETED:
            return (DELETED, obj) if old_match else None
        if self._match(obj):
            return (ADDED if (ev.type == ADDED or not old_match) else MODIFIED, obj)
        if old_match:  # left the selected set
            return (DELETED, old)
        return None

    def __aiter__(self):
        return self

    async def __anext__(self):
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev


class RawObjectWatch:
    """Label-selector-filtered watch yielding STORE-OWNED raw dicts.

    The HTTP watch fast path — the role of the reference's watch cache
    (``staging/src/k8s.io/apiserver/pkg/storage/cacher.go``): events a
    wire watcher only re-serializes must not pay a full typed decode +
    re-encode per watcher. Label selectors match the raw
    ``metadata.labels`` dict (same trick the list path uses); field
    selectors need typed extraction, so those watchers take the
    :class:`ObjectWatch` path.

    ``next`` yields ``(etype, payload_dict, revision, which, key)``
    where ``which`` is ``"cur"`` or ``"prev"`` — a cache key component:
    the same store revision can surface different payloads to different
    watchers (a selector-left MODIFIED surfaces the corpse as DELETED)
    — and ``key`` is the store key, which the serialize-once encode
    cache (encodecache.py) indexes by.
    Payload dicts alias the store log: consumers MUST NOT mutate them.
    """

    CLOSED = ObjectWatch.CLOSED

    def __init__(self, raw: Watch, label_selector: str = ""):
        self._raw = raw
        self._sel = parse_selector(label_selector) if label_selector else None

    def cancel(self) -> None:
        self._raw.cancel()

    @property
    def closed(self) -> bool:
        return self._raw.closed

    def _match(self, value: Optional[dict]) -> bool:
        if value is None:
            return False
        if self._sel is None:
            return True
        labels = (value.get("metadata") or {}).get("labels") or {}
        return self._sel.matches(labels)

    async def next(self, timeout: Optional[float] = None):
        while True:
            ev = await self._raw.next(timeout)
            if ev is None:
                if self._raw.closed:
                    return (self.CLOSED, None, 0, "cur", "")
                return None
            out = self._translate(ev)
            if out is not None:
                return out

    def next_nowait(self):
        """An already-delivered (translated) event or None — lets the
        HTTP watch handler coalesce every in-flight event into one
        socket write (the fan-out's syscall count was a measured
        apiserver CPU cost at density scale)."""
        while True:
            ev = self._raw.next_nowait()
            if ev is None:
                return None
            out = self._translate(ev)
            if out is not None:
                return out

    def _translate(self, ev: WatchEvent):
        # Mirrors ObjectWatch._translate on raw dicts (same
        # selector-transition semantics as the reference watch cache).
        old_match = self._match(ev.prev_value)
        if ev.type == DELETED:
            return ((DELETED, ev.value, ev.revision, "cur", ev.key)
                    if old_match else None)
        if self._match(ev.value):
            etype = ADDED if (ev.type == ADDED or not old_match) else MODIFIED
            return (etype, ev.value, ev.revision, "cur", ev.key)
        if old_match:  # left the selected set
            return (DELETED, ev.prev_value, ev.revision, "prev", ev.key)
        return None


# Imported late to avoid a cycle (admission imports registry types).
from .admission import AdmissionChain  # noqa: E402,F401
