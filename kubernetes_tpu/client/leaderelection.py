"""Lease-based leader election.

Reference: ``staging/src/k8s.io/client-go/tools/leaderelection/
leaderelection.go:70 Run, :138 renew loop`` — HA control-plane
components (scheduler, controller-manager) elect one active instance by
CAS-ing a Lease object; losing the lease stops the callbacks.
"""
from __future__ import annotations

import asyncio
import datetime
import logging
from typing import Awaitable, Callable, Optional

from ..api import errors
from ..api.meta import ObjectMeta, now
from ..api.types import Lease, LeaseSpec
from .interface import Client

log = logging.getLogger("leaderelection")


class LeaderElector:
    def __init__(self, client: Client, name: str, identity: str,
                 namespace: str = "kube-system",
                 lease_duration: float = 15.0, renew_deadline: float = 10.0,
                 retry_period: float = 2.0):
        self.client = client
        self.name = name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.is_leader = False

    async def run(self, on_started_leading: Callable[[], Awaitable[None]],
                  on_stopped_leading: Optional[Callable[[], None]] = None) -> None:
        """Acquire, then run the payload while renewing; if renewal fails
        the payload is cancelled (crash-only handoff)."""
        while True:
            await self._acquire()
            self.is_leader = True
            log.info("%s: %s became leader", self.name, self.identity)
            payload = asyncio.get_running_loop().create_task(on_started_leading())
            try:
                await self._renew_loop()
            finally:
                self.is_leader = False
                payload.cancel()
                try:
                    await payload
                except asyncio.CancelledError:
                    pass
                except Exception as e:  # noqa: BLE001
                    log.warning("%s: leader payload for %s raised during "
                                "teardown: %s", self.name, self.identity, e)
                if on_stopped_leading:
                    on_stopped_leading()
                log.warning("%s: %s lost leadership", self.name, self.identity)

    async def _acquire(self) -> None:
        while True:
            if await self._try_acquire_or_renew():
                return
            await asyncio.sleep(self.retry_period)

    async def _renew_loop(self) -> None:
        while True:
            await asyncio.sleep(self.retry_period)
            deadline = asyncio.get_running_loop().time() + self.renew_deadline
            ok = False
            while asyncio.get_running_loop().time() < deadline:
                try:
                    ok = await self._try_acquire_or_renew()
                    break
                except Exception:  # noqa: BLE001
                    await asyncio.sleep(self.retry_period / 4)
            if not ok:
                return  # lost it

    async def _try_acquire_or_renew(self) -> bool:
        try:
            lease = await self.client.get("leases", self.namespace, self.name)
        except errors.NotFoundError:
            lease = Lease(metadata=ObjectMeta(name=self.name, namespace=self.namespace),
                          spec=LeaseSpec(holder_identity=self.identity,
                                         lease_duration_seconds=self.lease_duration,
                                         acquire_time=now(), renew_time=now()))
            try:
                await self.client.create(lease)
                return True
            except errors.AlreadyExistsError:
                return False
        spec = lease.spec
        if spec.holder_identity and spec.holder_identity != self.identity:
            expired = (spec.renew_time is None or
                       (now() - spec.renew_time).total_seconds() > spec.lease_duration_seconds)
            if not expired:
                return False
            spec.lease_transitions += 1
            spec.acquire_time = now()
        spec.holder_identity = self.identity
        spec.renew_time = now()
        spec.lease_duration_seconds = self.lease_duration
        try:
            await self.client.update(lease)
            return True
        except (errors.ConflictError, errors.NotFoundError):
            return False
