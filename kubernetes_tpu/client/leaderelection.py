"""Lease-based leader election.

Reference: ``staging/src/k8s.io/client-go/tools/leaderelection/
leaderelection.go:70 Run, :138 renew loop`` — HA control-plane
components (scheduler, controller-manager) elect one active instance by
CAS-ing a Lease object; losing the lease stops the callbacks.
"""
from __future__ import annotations

import asyncio
import datetime
import logging
from typing import Awaitable, Callable, Optional

from ..api import errors
from ..api.meta import ObjectMeta, now
from ..api.types import Lease, LeaseSpec
from .interface import Client

log = logging.getLogger("leaderelection")


class LeaderElector:
    def __init__(self, client: Client, name: str, identity: str,
                 namespace: str = "kube-system",
                 lease_duration: float = 15.0, renew_deadline: float = 10.0,
                 retry_period: float = 2.0):
        self.client = client
        self.name = name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.is_leader = False

    async def run(self, on_started_leading: Callable[[], Awaitable[None]],
                  on_stopped_leading: Optional[Callable[[], None]] = None) -> None:
        """Acquire, then run the payload while renewing; if renewal fails
        the payload is cancelled (crash-only handoff).

        Graceful stop (this coroutine cancelled, or the payload
        returning) RELEASES the Lease — holder_identity CAS'd to empty
        — so a standby acquires on its next retry tick instead of
        waiting out ``lease_duration`` (reference:
        ``ReleaseOnCancel``). A crash skips the release by definition
        and standbys pay the full expiry, which is exactly the
        fast-handoff-vs-crash-handoff split the tests pin down."""
        try:
            while True:
                await self._acquire()
                self.is_leader = True
                log.info("%s: %s became leader", self.name, self.identity)
                loop = asyncio.get_running_loop()
                payload = loop.create_task(on_started_leading())
                renew = loop.create_task(self._renew_loop())
                payload_done = False
                try:
                    # First-completed wins: renewal failing ends the
                    # payload (crash-only handoff), and the payload
                    # finishing — return OR crash — ends leadership
                    # too. Without watching the payload, a crashed one
                    # would leave a zombie leader renewing a Lease it
                    # does nothing with, locking every standby out.
                    done, _ = await asyncio.wait(
                        {payload, renew},
                        return_when=asyncio.FIRST_COMPLETED)
                    payload_done = payload in done
                finally:
                    self.is_leader = False
                    payload.cancel()
                    renew.cancel()
                    try:
                        await payload
                    except asyncio.CancelledError:
                        pass
                    except Exception as e:  # noqa: BLE001
                        log.warning("%s: leader payload for %s raised: %s",
                                    self.name, self.identity, e)
                    try:
                        await renew
                    except asyncio.CancelledError:
                        pass
                    if on_stopped_leading:
                        on_stopped_leading()
                    log.warning("%s: %s lost leadership", self.name, self.identity)
                if payload_done:
                    # The payload chose to stop (or died): hand the
                    # lease over (outer finally) instead of re-electing
                    # ourselves to run nothing.
                    return
        finally:
            # Runs on cancellation (and payload crash propagation): if
            # the lease is plausibly still ours, hand it over NOW.
            # Shielded so the cancellation that got us here cannot kill
            # the release mid-flight; bounded so a dead apiserver
            # degrades to the crash path, not a hung teardown.
            try:
                await asyncio.shield(
                    asyncio.wait_for(self.release(), 2.0))
            except (asyncio.TimeoutError, asyncio.CancelledError,
                    errors.StatusError) as e:
                log.warning("%s: %s could not release the lease (%s); "
                            "standbys will wait out the full "
                            "lease_duration", self.name, self.identity, e)

    async def release(self) -> None:
        """CAS the Lease's holder back to empty if we still hold it —
        the fast-handoff half of graceful shutdown. Safe to call when
        not holding: a foreign holder (or a missing Lease) is a no-op.
        Conflict losses are fine too: someone else already took or
        touched it, which is the outcome release exists to enable."""
        try:
            lease = await self.client.get("leases", self.namespace, self.name)
        except errors.NotFoundError:
            return
        if lease.spec.holder_identity != self.identity:
            return
        lease.spec.holder_identity = ""
        lease.spec.renew_time = now()
        try:
            await self.client.update(lease)
            log.info("%s: %s released the lease", self.name, self.identity)
        except (errors.ConflictError, errors.NotFoundError):
            pass  # raced with a taker — the handoff already happened

    async def _acquire(self) -> None:
        while True:
            if await self._try_acquire_or_renew():
                return
            await asyncio.sleep(self.retry_period)

    async def _renew_loop(self) -> None:
        while True:
            await asyncio.sleep(self.retry_period)
            deadline = asyncio.get_running_loop().time() + self.renew_deadline
            ok = False
            while asyncio.get_running_loop().time() < deadline:
                try:
                    ok = await self._try_acquire_or_renew()
                    break
                except Exception:  # noqa: BLE001
                    await asyncio.sleep(self.retry_period / 4)
            if not ok:
                return  # lost it

    async def _try_acquire_or_renew(self) -> bool:
        try:
            lease = await self.client.get("leases", self.namespace, self.name)
        except errors.NotFoundError:
            lease = Lease(metadata=ObjectMeta(name=self.name, namespace=self.namespace),
                          spec=LeaseSpec(holder_identity=self.identity,
                                         lease_duration_seconds=self.lease_duration,
                                         acquire_time=now(), renew_time=now()))
            try:
                await self.client.create(lease)
                return True
            except errors.AlreadyExistsError:
                return False
        spec = lease.spec
        if spec.holder_identity and spec.holder_identity != self.identity:
            expired = (spec.renew_time is None or
                       (now() - spec.renew_time).total_seconds() > spec.lease_duration_seconds)
            if not expired:
                return False
            spec.lease_transitions += 1
            spec.acquire_time = now()
        spec.holder_identity = self.identity
        spec.renew_time = now()
        spec.lease_duration_seconds = self.lease_duration
        try:
            await self.client.update(lease)
            return True
        except (errors.ConflictError, errors.NotFoundError):
            return False
