from .interface import Client  # noqa: F401
from .local import LocalClient  # noqa: F401
from .rest import RESTClient  # noqa: F401
