"""Cache mutation detector — the client-go analog, env-gated.

Reference: ``staging/src/k8s.io/client-go/tools/cache/
mutation_detector.go`` — when ``KUBE_CACHE_MUTATION_DETECTOR`` is set,
every object entering the watch cache is deep-copied, and the copy is
periodically compared against the live object; any drift means some
consumer mutated a shared cached object in place and the process
panics with the diff.

This port snapshots a digest of the object's canonical repr (the
dataclass repr covers every field recursively; the wire codec would
elide default-valued fields and miss default-shaped mutations) at
upsert and re-checks it on read-back (``get``/``list``/``by_index``)
instead of on a timer, so a violating test fails at the first read
after the mutation — deterministically, with the key in hand. Gate:
``TPU_CACHE_MUTATION_DETECTOR=1`` (or construct with
``enabled=True``). Disabled, every hook is a single attribute check.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Optional

log = logging.getLogger("mutation-detector")

ENV_VAR = "TPU_CACHE_MUTATION_DETECTOR"


def enabled_from_env() -> bool:
    return os.environ.get(ENV_VAR, "").lower() in ("1", "true", "yes", "on")


class CacheMutationDetectedError(AssertionError):
    """A consumer mutated an object it obtained from a shared cache."""


class CacheMutationDetector:
    """Digest snapshots keyed like the cache that owns the detector."""

    def __init__(self, name: str, enabled: Optional[bool] = None):
        self.name = name
        self.enabled = enabled_from_env() if enabled is None else enabled
        self._digests: dict[str, str] = {}

    @staticmethod
    def digest(obj: Any) -> str:
        # Dataclass repr covers every field recursively (unlike the wire
        # codec, which elides default-valued fields — a mutation writing
        # a default-shaped value would slip through a to_dict digest).
        if isinstance(obj, (dict, list, tuple, set)):
            # Armed-only debug path (TPU_CACHE_MUTATION_DETECTOR):
            # never on in production; the digest IS the detector.
            payload = json.dumps(obj, sort_keys=True, default=repr)  # tpuvet: ignore[hot-path-cost]
        else:
            payload = repr(obj)
        return hashlib.sha1(payload.encode()).hexdigest()

    def capture(self, key: str, obj: Any) -> None:
        """Snapshot ``obj`` as it enters the cache (upsert path)."""
        if self.enabled:
            self._digests[key] = self.digest(obj)

    def forget(self, key: str) -> None:
        if self.enabled:
            self._digests.pop(key, None)

    def verify(self, key: str, obj: Any) -> None:
        """Assert ``obj`` still matches its upsert-time snapshot
        (read-back path). Raises :class:`CacheMutationDetectedError`."""
        if not self.enabled or obj is None:
            return
        want = self._digests.get(key)
        if want is None:
            return
        got = self.digest(obj)
        if got != want:
            raise CacheMutationDetectedError(
                f"{self.name}: cached object {key!r} was mutated in place "
                f"after caching (digest {want[:12]} -> {got[:12]}). Some "
                f"consumer modified a shared cache object — deepcopy "
                f"before writing.")

    def verify_all(self, items: dict) -> None:
        for key, obj in items.items():
            self.verify(key, obj)

    def clear(self) -> None:
        self._digests.clear()
