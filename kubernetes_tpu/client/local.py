"""In-process client: direct registry calls.

Used by the single-binary control plane and the integration test tier
(reference: controllers/scheduler tested against an in-proc master,
``test/integration/framework/master_utils.go:290-305``).

Dispatch goes through :meth:`Registry.run` — inline for in-memory
stores (microsecond dict ops; a to_thread round trip costs ~1ms of
jittery handoff and dominated the gang-bench wall clock), worker
thread when the store's WAL can block on disk.
"""
from __future__ import annotations

from typing import Any, Optional

from ..api.types import Binding
from ..apiserver.registry import ObjectWatch, Registry
from .interface import Client, WatchStream


class _LocalWatch(WatchStream):
    def __init__(self, ow: ObjectWatch):
        self._ow = ow

    def cancel(self) -> None:
        self._ow.cancel()

    async def next(self, timeout: Optional[float] = None):
        return await self._ow.next(timeout)


class LocalClient(Client):
    def __init__(self, registry: Registry):
        self.registry = registry

    async def _call(self, fn, *args):
        return await self.registry.run(fn, *args)

    async def create(self, obj: Any) -> Any:
        return await self._call(self.registry.create, obj)

    async def get(self, plural: str, namespace: str, name: str) -> Any:
        return self.registry.get(plural, namespace, name)

    async def list(self, plural: str, namespace: str = "", label_selector: str = "",
                   field_selector: str = "") -> tuple[list, int]:
        return await self._call(
            self.registry.list, plural, namespace, label_selector, field_selector)

    async def update(self, obj: Any, subresource: str = "") -> Any:
        return await self._call(self.registry.update, obj, subresource)

    async def patch(self, plural: str, namespace: str, name: str, patch: dict,
                    subresource: str = "", strategic: bool = False) -> Any:
        return await self._call(
            self.registry.patch, plural, namespace, name, patch, subresource,
            strategic)

    async def delete(self, plural: str, namespace: str, name: str,
                     grace_period_seconds: Optional[int] = None, uid: str = "",
                     propagation_policy: str = "") -> Any:
        return await self._call(
            self.registry.delete, plural, namespace, name,
            grace_period_seconds, uid, propagation_policy)

    async def watch(self, plural: str, namespace: str = "", resource_version: int = 0,
                    label_selector: str = "", field_selector: str = "") -> WatchStream:
        ow = self.registry.watch(plural, namespace, resource_version,
                                 label_selector, field_selector)
        return _LocalWatch(ow)

    async def bind(self, namespace: str, name: str, binding: Binding,
                   decode: bool = True) -> Any:
        del decode  # in-proc: the typed object is free
        return await self._call(self.registry.bind_pod, namespace, name, binding)

    async def evict(self, namespace: str, name: str, eviction: Any) -> Any:
        return await self._call(self.registry.evict_pod, namespace, name,
                                eviction)
