"""Client interface — what every component programs against.

Reference: client-go's typed clientsets + REST client
(``staging/src/k8s.io/client-go``). Two implementations:

- :class:`~kubernetes_tpu.client.local.LocalClient` — direct registry
  calls, used in integration tests and the single-binary control plane
  (the reference's in-process master in
  ``test/integration/framework/master_utils.go:290``).
- :class:`~kubernetes_tpu.client.rest.RESTClient` — HTTP to a remote
  apiserver, used by node agents / CLI / separate-process components.

All methods are async so both implementations compose with informers.
"""
from __future__ import annotations

from typing import Any, AsyncIterator, Optional

from ..api.types import Binding


class WatchStream:
    """Async iterator of (event_type, object) tuples; must be cancelled."""

    def cancel(self) -> None:
        raise NotImplementedError

    async def next(self, timeout: Optional[float] = None):
        raise NotImplementedError

    def __aiter__(self) -> AsyncIterator:
        return self

    async def __anext__(self):
        ev = await self.next()
        if ev is None:
            raise StopAsyncIteration
        return ev


class Client:
    async def create(self, obj: Any) -> Any:
        raise NotImplementedError

    async def get(self, plural: str, namespace: str, name: str) -> Any:
        raise NotImplementedError

    async def list(self, plural: str, namespace: str = "", label_selector: str = "",
                   field_selector: str = "") -> tuple[list, int]:
        raise NotImplementedError

    async def update(self, obj: Any, subresource: str = "") -> Any:
        raise NotImplementedError

    async def update_status(self, obj: Any) -> Any:
        return await self.update(obj, subresource="status")

    async def patch(self, plural: str, namespace: str, name: str, patch: dict,
                    subresource: str = "", strategic: bool = False) -> Any:
        """``strategic=True`` selects strategic-merge-patch semantics
        (list merge by per-type keys) instead of RFC 7386."""
        raise NotImplementedError

    async def delete(self, plural: str, namespace: str, name: str,
                     grace_period_seconds: Optional[int] = None, uid: str = "",
                     propagation_policy: str = "") -> Any:
        raise NotImplementedError

    async def watch(self, plural: str, namespace: str = "", resource_version: int = 0,
                    label_selector: str = "", field_selector: str = "") -> WatchStream:
        raise NotImplementedError

    async def bind(self, namespace: str, name: str, binding: Binding,
                   decode: bool = True) -> Any:
        """``decode=False``: high-rate callers (the scheduler) may skip
        typing the response; implementations may ignore the hint."""
        raise NotImplementedError

    async def bind_many(self, namespace: str,
                        bindings: list) -> list:
        """Bind many pods: ``bindings`` is ``[(name, Binding), ...]``;
        returns a positional list of per-item outcomes — None on
        success, the item's exception instance on failure. A
        transport-level failure raises for the whole call.

        Default: a sequential loop over :meth:`bind` (kept deliberately
        on ``self.bind`` so tests monkeypatching ``bind`` keep working);
        RESTClient overrides with one ``pods/bindings:batch`` round
        trip — the scheduler's gang bind and bind coalescer depend on
        that for wire-path throughput."""
        out = []
        for name, binding in bindings:
            try:
                await self.bind(namespace, name, binding, decode=False)
                out.append(None)
            except Exception as e:  # noqa: BLE001 — per-item outcome list
                out.append(e)
        return out

    async def create_many(self, objs: list, decode: bool = True) -> list:
        """Create many objects; returns a positional list of per-item
        outcomes — the created object, or the item's exception
        instance. Partial failure does not raise. RESTClient overrides
        with one ``{plural}:batchCreate`` round trip; ``decode=False``
        lets implementations skip echoing/typing created objects
        (successes may then be None)."""
        out = []
        for obj in objs:
            try:
                out.append(await self.create(obj))
            except Exception as e:  # noqa: BLE001 — per-item outcome list
                out.append(e)
        return out

    async def evict(self, namespace: str, name: str, eviction: Any) -> Any:
        """PDB-gated voluntary delete (pods/<name>/eviction). Raises
        TooManyRequestsError while the budget allows no disruption."""
        raise NotImplementedError

    async def close(self) -> None:
        pass
